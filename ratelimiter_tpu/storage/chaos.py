"""Fault-injecting storage wrapper (chaos testing).

The reference has no fault injection at all (SURVEY.md §5.3 — its failure
handling is asserted, not exercised). This wrapper makes failure paths
first-class testable: it delegates to any ``RateLimitStorage`` and injects
``StorageException`` (and optional latency) on a configurable schedule, so
retry logic, fail-open policy, and metric accounting can be driven
deterministically in tests and chaos drills.

Determinism: failures come from a seeded RNG; ``fail_next(n)`` forces the
next n operations to fail regardless of probability — the tool for exact
retry-count assertions (the reference's retry wrapper does 3 attempts with
linear backoff; ``service/app.py`` implements the documented fail-open on
exhaustion).
"""

from __future__ import annotations

import collections
import random
import threading
import time

from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.storage.errors import StorageException

_DECISION_OPS = ("acquire", "acquire_many", "acquire_many_ids",
                 "acquire_stream_ids", "acquire_stream_strs",
                 "available_many", "reset_key")
_LEGACY_OPS = ("increment_and_expire", "get", "set", "compare_and_set",
               "delete", "z_add", "z_remove_range_by_score", "z_count",
               "eval_script")


class FaultInjectingStorage(RateLimitStorage):
    """Wraps a real backend; injects failures/latency on configured ops."""

    def __init__(
        self,
        inner: RateLimitStorage,
        failure_rate: float = 0.0,
        latency_ms: float = 0.0,
        seed: int = 0,
        ops: tuple = _DECISION_OPS + _LEGACY_OPS,
    ):
        self._inner = inner
        self.failure_rate = float(failure_rate)
        self.latency_ms = float(latency_ms)
        self._rng = random.Random(seed)
        self._ops = set(ops)
        self._lock = threading.Lock()
        self._forced = 0
        self.injected_failures = 0
        # Recent op names only — bounded so long-running drills can't leak.
        self.calls = collections.deque(maxlen=1024)

    # -- control surface ------------------------------------------------------
    def fail_next(self, n: int = 1) -> None:
        """Force the next ``n`` wrapped operations to fail."""
        with self._lock:
            self._forced += int(n)

    def heal(self) -> None:
        """Cancel any remaining forced failures (drills: end an outage)."""
        with self._lock:
            self._forced = 0

    def _maybe_fail(self, op: str) -> None:
        if op not in self._ops:
            return
        with self._lock:
            self.calls.append(op)
            if self._forced > 0:
                self._forced -= 1
                self.injected_failures += 1
                raise StorageException(f"injected failure in {op}")
            if self.failure_rate and self._rng.random() < self.failure_rate:
                self.injected_failures += 1
                raise StorageException(f"injected failure in {op}")
        if self.latency_ms:
            time.sleep(self.latency_ms / 1000.0)

    def __getattr__(self, name):
        # Everything not explicitly wrapped (register_limiter, flush,
        # checkpoints, attributes like engine/trace) passes straight through.
        return getattr(self._inner, name)

    # -- wrapped surface ------------------------------------------------------
    @property
    def supports_device_batching(self):  # type: ignore[override]
        return getattr(self._inner, "supports_device_batching", False)


def _wrap(op: str):
    def method(self, *args, **kwargs):
        self._maybe_fail(op)
        return getattr(self._inner, op)(*args, **kwargs)

    method.__name__ = op
    return method


for _op in _DECISION_OPS + _LEGACY_OPS + ("is_available", "close"):
    setattr(FaultInjectingStorage, _op, _wrap(_op))
# is_available/close are wrapped for delegation but never injected by
# default (they are the health/shutdown path; pass them in ``ops`` to
# chaos-test the health check itself).
#
# The abstract-method set was frozen before the loop above filled the
# contract in; clear it so the wrapper instantiates.
FaultInjectingStorage.__abstractmethods__ = frozenset()


# ---------------------------------------------------------------------------
# Network fault injection (sidecar ingress chaos)
# ---------------------------------------------------------------------------


class FaultInjectingProxy:
    """TCP man-in-the-middle for ingress chaos (service/sidecar.py).

    Listens on a local port and forwards each connection to a target
    server, injecting network faults into the CLIENT->SERVER direction on
    a configured schedule.  Fault classes (``set_fault``):

    - ``None``        — transparent passthrough (baseline),
    - ``"truncate"``  — forward only the first ``after`` bytes, then
      swallow everything else (the server holds a half-written frame
      until its read deadline fires — the slowloris shape),
    - ``"delay"``     — forward in 1-byte pieces with ``delay_ms`` sleeps
      (a slow writer that keeps the frame perpetually almost-done),
    - ``"garbage"``   — after ``after`` forwarded bytes, inject ``n``
      seeded-random bytes into the stream (framing corruption), then keep
      forwarding,
    - ``"kill"``      — abruptly close both sides after ``after``
      forwarded bytes (a client dying mid-pipeline),
    - ``"partition"`` — drop bytes without closing either socket (no
      RST, no FIN): the network-partition shape — the peer looks
      silently gone, exactly what an ack deadline/heartbeat must detect
      (``partition()`` / ``heal()`` are shorthands).  ``direction=``
      scopes the cut: ``"both"`` (default), ``"up"`` (client->server),
      or ``"down"`` (server->client only — the HALF-OPEN link where
      sends land but acks vanish),
    - ``"flap"``      — alternate partitioned and healthy every half
      ``period_s`` (a flaky link that heals before any single probe
      window closes — what the orchestrator's hysteresis must damp).

    ALL fault modes are evaluated LIVE, per chunk: a ``set_fault``/
    ``heal`` takes effect on in-flight connections at their next chunk
    boundary, not just on new accepts.  A long-lived connection (a
    replication link, a pinned sidecar session) must be degradable and
    healable mid-stream without reconnecting — the chaos conductor
    flips faults on links whose connections outlive every schedule
    step.  Per-connection byte counters (``after`` bookkeeping, the
    one-shot garbage injection) still start at accept time.
    Server->client bytes pass through untouched except under
    partition/flap — those attack the LINK, not just the ingress.
    """

    def __init__(self, target_port: int, target_host: str = "127.0.0.1",
                 host: str = "127.0.0.1", port: int = 0, seed: int = 0):
        import socket
        import socketserver

        self.target = (target_host, int(target_port))
        self._rng = random.Random(seed)
        self._fault: tuple = (None, {})
        self._flap_t0 = time.monotonic()
        self._lock = threading.Lock()
        self.connections = 0
        self.faults_injected = 0
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with outer._lock:
                    outer.connections += 1
                try:
                    up = socket.create_connection(outer.target, timeout=10.0)
                except OSError:
                    return
                down = threading.Thread(
                    target=outer._pump_down, args=(up, self.request),
                    daemon=True)
                down.start()
                try:
                    outer._pump_up(self.request, up)
                finally:
                    for s in (up, self.request):
                        try:
                            s.shutdown(socket.SHUT_RDWR)
                        except OSError:
                            pass
                        try:
                            s.close()
                        except OSError:
                            pass
                    down.join(timeout=2.0)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="chaos-proxy",
            daemon=True)

    # -- control surface ------------------------------------------------------
    def set_fault(self, mode: str | None, **params) -> None:
        """Set the fault class, applied LIVE: in-flight connections see
        the new mode at their next chunk boundary, new connections from
        their first byte.

        ``after``: client bytes forwarded before the fault engages
        (default 0); ``n``: garbage byte count; ``delay_ms``: per-byte
        delay for ``"delay"``; ``period_s``: full flap cycle for
        ``"flap"`` (half up, half partitioned); ``direction``: which
        pump(s) a ``"partition"`` cuts — ``"both"`` (default), ``"up"``
        (client->server dropped, responses flow), or ``"down"``
        (server->client dropped: the HALF-OPEN link — sends land, acks
        vanish — that only an ack deadline can detect)."""
        if mode not in (None, "truncate", "delay", "garbage", "kill",
                        "partition", "flap"):
            raise ValueError(f"unknown fault mode: {mode!r}")
        direction = params.get("direction", "both")
        if direction not in ("both", "up", "down"):
            raise ValueError(f"unknown partition direction: {direction!r}")
        with self._lock:
            self._fault = (mode, dict(params))
            if mode == "flap":
                self._flap_t0 = time.monotonic()

    def partition(self, direction: str = "both") -> None:
        """Drop ``direction`` on every connection, live — no RST, no
        FIN: the silent network partition.  ``direction="down"`` makes
        the link HALF-OPEN (client bytes still arrive at the server,
        its acks/responses are swallowed) — the asymmetric-partition
        shape a one-byte-ack protocol can only catch via its ack
        deadline.  ``heal()`` restores."""
        self.set_fault("partition", direction=direction)

    def flap(self, period_s: float) -> None:
        """Alternate healthy/partitioned every ``period_s / 2``, live."""
        self.set_fault("flap", period_s=float(period_s))

    def heal(self) -> None:
        """Back to transparent passthrough (ends a partition/flap)."""
        self.set_fault(None)

    def _link_cut(self, direction: str = "both") -> bool:
        """Live verdict: are bytes currently being dropped in
        ``direction`` ("up" = client->server, "down" = server->client)?
        (Only the partition/flap modes cut the link wholesale; the
        ingress faults shape bytes in :meth:`_pump_up` instead.)"""
        with self._lock:
            mode, params = self._fault
            if mode == "partition":
                cut = params.get("direction", "both")
                return cut == "both" or cut == direction
            if mode == "flap":
                period = float(params.get("period_s", 0.2))
                phase = (time.monotonic() - self._flap_t0) % period
                return phase >= period / 2.0
            return False

    def start(self) -> "FaultInjectingProxy":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- pumps ----------------------------------------------------------------
    def _pump_down(self, up, client) -> None:
        """Server->client passthrough until either side dies (bytes are
        silently dropped while a live partition/flap cut is on)."""
        while True:
            try:
                chunk = up.recv(65536)
            except OSError:
                return
            if not chunk:
                try:
                    client.shutdown(1)  # SHUT_WR: flush EOF downstream
                except OSError:
                    pass
                return
            if self._link_cut("down"):
                with self._lock:
                    self.faults_injected += 1
                continue  # dropped: no RST, no FIN — silence
            try:
                client.sendall(chunk)
            except OSError:
                return

    def _pump_up(self, client, up) -> None:
        """Client->server with the CURRENT fault applied — the mode is
        re-read per chunk, so a mid-connection ``set_fault``/``heal``
        takes effect without a reconnect.  The ``forwarded`` byte
        counter and the garbage one-shot are per-connection state; the
        one-shot re-arms whenever the mode leaves ``"garbage"``, so a
        heal-then-reinject cycle corrupts the stream again."""
        forwarded = 0
        injected = False
        while True:
            try:
                chunk = client.recv(65536)
            except OSError:
                return
            if not chunk:
                return
            if self._link_cut("up"):
                with self._lock:
                    self.faults_injected += 1
                continue  # partition/flap: dropped — silence, no close
            with self._lock:
                mode, params = self._fault
                garbage = b""
                if mode == "garbage" and not injected:
                    garbage = bytes(self._rng.randrange(256)
                                    for _ in range(params.get("n", 64)))
            after = int(params.get("after", 0))
            if mode != "garbage":
                injected = False
            if mode == "kill" and forwarded + len(chunk) >= after:
                cut = max(after - forwarded, 0)
                try:
                    if cut:
                        up.sendall(chunk[:cut])
                except OSError:
                    return
                with self._lock:
                    self.faults_injected += 1
                return  # handler's finally closes both sides abruptly
            if mode == "truncate":
                if forwarded >= after:
                    continue  # swallow: server waits on a half frame
                chunk = chunk[:max(after - forwarded, 0)]
                if forwarded + len(chunk) >= after:
                    with self._lock:
                        self.faults_injected += 1
            if mode == "garbage" and not injected \
                    and forwarded + len(chunk) >= after:
                cut = max(after - forwarded, 0)
                chunk = chunk[:cut] + garbage + chunk[cut:]
                injected = True
                with self._lock:
                    self.faults_injected += 1
            try:
                if mode == "delay":
                    delay_s = float(params.get("delay_ms", 20.0)) / 1000.0
                    for i in range(len(chunk)):
                        up.sendall(chunk[i:i + 1])
                        time.sleep(delay_s)
                else:
                    up.sendall(chunk)
            except OSError:
                return
            forwarded += len(chunk)


# ---------------------------------------------------------------------------
# Ingress drill (sidecar under network faults, differential vs the oracle)
# ---------------------------------------------------------------------------

def ingress_drill(
    num_slots: int = 1024,
    n_keys: int = 32,
    waves: int = 3,
    pipeline: int = 12,
    max_pipeline: int = 16,
    read_timeout_ms: float = 300.0,
    seed: int = 0,
    registry=None,
) -> dict:
    """Deterministic sidecar-ingress chaos drill.

    Runs the hardened sidecar (protocol v2, tight frame/pipeline/deadline
    bounds) over a controlled-clock ``TpuBatchedStorage`` and attacks it
    with every fault class — malformed frames sent directly, plus
    truncate / garbage / kill-mid-pipeline through a
    :class:`FaultInjectingProxy` — while a healthy v2 client keeps making
    pipelined decisions that are checked BIT-IDENTICAL against
    ``semantics/oracle.py``.  Proves, per the ISSUE contract:

    - the server stays up under every fault class (PING works, later
      decisions still exact);
    - malformed frames are answered in-protocol with ``BAD_FRAME`` (the
      attacking connection survives and can still make valid decisions);
    - a slow/truncated frame trips the read deadline instead of pinning
      a handler thread;
    - a client killed mid-pipeline leaks nothing: batcher queue depth and
      the unresolved-waiter set return to baseline (abandoned futures are
      withdrawn or consumed), and handler threads are reaped;
    - pipeline overflow is shed with the typed retry-after status;
    - the health state machine's inputs transition as PR 2 defines:
      shedding is visible via ``last_shed_s`` within the health window
      and clears after it.

    Returns a report dict; raises AssertionError on any violated claim.
    """
    import socket as socket_mod
    import struct

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.semantics.oracle import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.service import sidecar as sc
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = random.Random(seed)
    clock = {"t": 1_753_000_000_000}
    # max_inflight=1 pins the drain pool at one worker so the end-of-drill
    # thread-leak check compares like with like.
    storage = TpuBatchedStorage(num_slots=num_slots, max_delay_ms=0.2,
                                max_inflight=1,
                                clock_ms=lambda: clock["t"])
    server = sc.SidecarServer(
        storage, host="127.0.0.1", meter_registry=registry,
        max_frame_bytes=512, max_key_bytes=64,
        max_pipeline=max_pipeline, max_connections=64,
        idle_timeout_ms=5_000.0, read_timeout_ms=read_timeout_ms,
        drain_timeout_ms=500.0).start()
    report = {"decisions": 0, "mismatches": 0, "faults": [],
              "shed": 0, "malformed_answered": 0}
    proxy = FaultInjectingProxy(server.port, seed=seed).start()
    try:
        cfg_sw = RateLimitConfig(max_permits=10, window_ms=2000,
                                 enable_local_cache=False)
        cfg_tb = RateLimitConfig(max_permits=20, window_ms=2000,
                                 refill_rate=8.0)
        lid_sw = server.register("sw", cfg_sw)
        lid_tb = server.register("tb", cfg_tb)
        # The attacker gets its own limiter so its mutations never touch
        # the oracle-tracked keyspace.
        lid_atk = server.register("tb", RateLimitConfig(
            max_permits=1000, window_ms=60_000, refill_rate=100.0))
        oracle_sw = SlidingWindowOracle(cfg_sw)
        oracle_tb = TokenBucketOracle(cfg_tb)
        healthy = sc.SidecarClient("127.0.0.1", server.port)
        assert healthy.server_version >= 3, "handshake failed"

        def healthy_wave() -> None:
            """Pipelined decisions on the DIRECT path, oracle-checked."""
            clock["t"] += rng.choice([3, 17, 250, 999, 2000])
            now = clock["t"]
            keys = [f"u{rng.randrange(n_keys)}" for _ in range(pipeline)]
            perms = [rng.choice([1, 1, 2, 5]) for _ in range(pipeline)]
            for lid, oracle in ((lid_sw, oracle_sw), (lid_tb, oracle_tb)):
                got = healthy.acquire_batch(lid, keys, perms)
                for j, (status, allowed, rem) in enumerate(got):
                    assert status == sc.ST_OK, (lid, j, status)
                    d = oracle.try_acquire(keys[j], perms[j], now)
                    report["decisions"] += 1
                    if allowed != d.allowed or (
                            lid == lid_tb and int(rem) != d.remaining_hint):
                        report["mismatches"] += 1

        def frame(op, a, b, key_bytes=b""):
            body = struct.pack("<BII", op, a, b) + key_bytes
            return struct.pack("<I", len(body)) + body

        # Baselines: warm one wave, then record thread/queue levels.
        healthy_wave()
        base_threads = threading.active_count()
        batcher = storage._batcher
        assert batcher.queue_depth() == 0

        # -- fault 1: malformed frames, sent directly --------------------
        # Pinned to v3: the hand-built frames below use the headerless
        # pre-v4 layout, so the connection must negotiate it.
        atk = sc.SidecarClient("127.0.0.1", server.port, protocol=3)
        declared = 100_000  # far over max_frame_bytes=512
        bad = [
            frame(1, lid_atk, 1, b"x" * 128),             # key too long
            struct.pack("<I", 4) + b"abc\x00",            # short frame
            frame(42, lid_atk, 1, b"k"),                  # unknown op
            frame(1, lid_atk, 1, b"\xff\xfe\xff"),        # invalid UTF-8 key
            struct.pack("<I", declared) + b"\x00" * declared,  # oversized
        ]
        # The oversized frame's declared payload is discarded as it
        # streams (never buffered) and the stream stays in sync: a valid
        # frame directly behind it still decides.
        atk._send(b"".join(bad))
        got = atk._read_responses(len(bad))
        for status, _, errno in got:
            assert status == sc.ST_BAD_FRAME, got
            report["malformed_answered"] += 1
        assert [g[2] for g in got] == [
            sc.ERR_KEY_TOO_LONG, sc.ERR_SHORT_FRAME, sc.ERR_UNKNOWN_OP,
            sc.ERR_BAD_KEY, sc.ERR_FRAME_TOO_LONG], got
        assert atk.try_acquire(lid_atk, "atk-ok") is True
        atk.close()
        report["faults"].append("malformed")
        healthy_wave()

        # -- fault 1b: malformed v5 columnar frames ----------------------
        # A v5 attacker hand-builds BATCH frames whose columns lie about
        # themselves.  Every one must be answered in-protocol with
        # BAD_FRAME + the right errno, the stream must stay in sync, and
        # a well-formed batch directly after must still decide.
        import numpy as _np
        atk5 = sc.SidecarClient("127.0.0.1", server.port)
        assert atk5.server_version >= 5

        def batch_frame(rows, klen, key_col, offs, flags, permits=b""):
            payload = (struct.pack("<I", klen) + key_col
                       + _np.asarray(offs, dtype=_np.uint32).tobytes()
                       + bytes([flags]) + permits)
            body = struct.pack("<BIIQ", sc.OP_BATCH, lid_atk, rows,
                               0) + payload
            return struct.pack("<I", len(body)) + body

        bad5 = [
            # column length mismatch: flags declare a permits column the
            # frame does not carry.
            batch_frame(2, 4, b"abcd", [0, 2, 4], 1),
            # offsets out of bounds: offs[-1] walks past the key column.
            batch_frame(2, 4, b"abcd", [0, 2, 9], 0),
            # offsets not monotonic.
            batch_frame(2, 4, b"abcd", [0, 3, 2][:3], 0),
            # declared rows over the frame cap (max_pipeline).
            batch_frame(max_pipeline + 1, 4, b"abcd",
                        [0] * (max_pipeline + 2), 0),
        ]
        atk5._send(b"".join(bad5))
        got5 = atk5._read_responses(len(bad5))
        for status, _, errno in got5:
            assert status == sc.ST_BAD_FRAME, got5
            report["malformed_answered"] += 1
        assert [g[2] for g in got5] == [
            sc.ERR_SHORT_FRAME, sc.ERR_BAD_COLUMN, sc.ERR_BAD_COLUMN,
            sc.ERR_FRAME_TOO_LONG], got5
        # Stream in sync: a valid columnar batch right behind the attack
        # still decides (and the bitmask has exactly its rows).
        assert atk5.acquire_block(lid_atk, ["b5-a", "b5-b"]) == [True, True]
        atk5.close()
        report["faults"].append("malformed_v5_columns")
        healthy_wave()

        # -- fault 2: slowloris / truncated frame ------------------------
        idle_before = server.idle_closed_total
        slow = socket_mod.create_connection(("127.0.0.1", server.port),
                                            timeout=5.0)
        slow.sendall(frame(1, lid_atk, 1, b"half-frame")[:9])  # partial
        t0 = time.monotonic()
        got_eof = slow.recv(16)  # server must close within the deadline
        dt = time.monotonic() - t0
        assert got_eof == b"", "server answered a half frame?"
        assert dt < read_timeout_ms / 1000.0 + 2.0, (
            f"read deadline did not fire in time ({dt:.2f}s)")
        assert server.idle_closed_total > idle_before
        slow.close()
        report["faults"].append("slowloris")
        healthy_wave()

        # -- fault 3: garbage injection through the proxy ----------------
        proxy.set_fault("garbage", after=17, n=48)
        gbg = sc.SidecarClient("127.0.0.1", proxy.port, protocol=1)
        try:
            # The injected garbage corrupts this connection's framing;
            # the server answers in-protocol or the conn dies — either
            # way the SERVER survives and other clients are unaffected.
            gbg.acquire_batch(lid_atk, [f"g{i}" for i in range(8)])
        except (ConnectionError, RuntimeError, socket_mod.timeout):
            pass
        finally:
            gbg.close()
        report["faults"].append("garbage")
        healthy_wave()

        # -- fault 4: kill mid-pipeline ----------------------------------
        proxy.set_fault("kill", after=120)  # dies mid-burst
        kil = sc.SidecarClient("127.0.0.1", proxy.port, protocol=1)
        try:
            kil.acquire_batch(lid_atk, [f"k{i}" for i in range(24)])
        except (ConnectionError, socket_mod.timeout, OSError):
            pass
        finally:
            kil.close()
        report["faults"].append("kill_mid_pipeline")
        healthy_wave()

        # -- pipeline-cap shed: typed retry-after status -----------------
        # The cap engages when the burst lands in one read; loopback with
        # TCP_NODELAY delivers an ~800-byte burst in one segment, but a
        # kernel split would halve it — retry a couple of times before
        # calling the cap broken.
        burst = max_pipeline * 2
        n_ok = n_shed = 0
        for _ in range(3):
            got = healthy.acquire_batch(
                lid_tb, [f"shed-{i}" for i in range(burst)])
            # Shed frames never reach the device, so the oracle stream is
            # untouched; ok frames mutate only shed-* keys (not tracked).
            n_ok = sum(1 for s, _, _ in got if s == sc.ST_OK)
            n_shed = sum(1 for s, _, _ in got if s == sc.ST_SHED)
            assert n_ok + n_shed == burst, got
            if n_shed:
                break
        assert n_shed >= 1, "pipeline cap never engaged"
        for status, _, rem in got:
            if status == sc.ST_SHED:
                assert rem > 0, "shed without a retry-after hint"
        report["shed"] = n_shed
        # Health machine input (PR 2 state machine): a recent shed reads
        # as SHEDDING inside the window...
        assert server.last_shed_s > 0
        assert (time.monotonic() - server.last_shed_s) <= 5.0
        healthy_wave()

        # -- convergence: no leaked threads, futures, or queue depth -----
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with batcher._cv:
                waiters = len(batcher._waiters)
            if (batcher.queue_depth() == 0 and waiters == 0
                    and threading.active_count() <= base_threads):
                break
            time.sleep(0.05)
        with batcher._cv:
            waiters = len(batcher._waiters)
        assert batcher.queue_depth() == 0, "queue depth did not drain"
        assert waiters == 0, f"{waiters} batcher future(s) leaked"
        assert threading.active_count() <= base_threads, (
            f"handler threads leaked: {threading.active_count()} > "
            f"baseline {base_threads}")
        assert storage.is_available(), "server/storage not healthy at end"
        assert healthy.ping(), "sidecar did not survive the fault classes"
        healthy.close()

        report["threads"] = threading.active_count()
        report["idle_closed"] = server.idle_closed_total
        report["malformed"] = server.malformed_total
        report["pipeline_shed"] = server.pipeline_shed_total
        report["futures_abandoned"] = server.futures_abandoned
        if report["mismatches"]:
            raise AssertionError(
                f"healthy decisions diverged from the oracle: {report}")
        return report
    finally:
        proxy.stop()
        server.stop()
        storage.close()


# ---------------------------------------------------------------------------
# Failover drill (replication/ — kill the primary mid-soak, promote)
# ---------------------------------------------------------------------------

def failover_drill(
    num_slots: int = 2048,
    n_keys: int = 64,
    waves: int = 6,
    kill_after_wave: int = 3,
    post_waves: int = 3,
    batch: int = 48,
    seed: int = 0,
    registry=None,
    background_interval_ms: float | None = None,
) -> dict:
    """Deterministic replicated-failover drill, differential vs the oracle.

    Builds a primary and a same-geometry standby ``TpuBatchedStorage``
    under a controlled clock, replicates primary -> standby through the
    full frame pipeline (journal -> log -> encoded wire frames ->
    receiver), and drives mixed sliding-window + token-bucket waves with
    every decision checked against ``semantics/oracle.py``.  After
    ``kill_after_wave`` waves the drill ships a final epoch, runs one
    more LOSS wave that is never replicated, kills the primary
    (``close()``), promotes the standby, and verifies that every
    post-failover decision is bit-identical to an oracle rolled back to
    the promoted epoch — the exact availability contract: state at or
    before the last replicated epoch survives, the loss wave does not.

    ``background_interval_ms`` additionally runs the async replicator
    thread during the soak (the production shape); the drill still cuts
    a deterministic final epoch before the kill so the differential
    stays exact.  Returns a report dict; raises AssertionError on any
    decision mismatch.
    """
    import copy
    import random

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.replication import (
        InProcessSink,
        ReplicationLog,
        Replicator,
        StandbyReceiver,
    )
    from ratelimiter_tpu.semantics.oracle import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = random.Random(seed)
    clock = {"t": 1_753_000_000_000}
    primary = TpuBatchedStorage(num_slots=num_slots,
                                clock_ms=lambda: clock["t"])
    standby = TpuBatchedStorage(num_slots=num_slots,
                                clock_ms=lambda: clock["t"])
    cfg_sw = RateLimitConfig(max_permits=20, window_ms=2000,
                             enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=30, window_ms=2000,
                             refill_rate=10.0)
    lid_sw = primary.register_limiter("sw", cfg_sw)
    lid_tb = primary.register_limiter("tb", cfg_tb)
    # The standby registers limiters from replicated frames, not here —
    # that path is part of what the drill proves.
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(standby, registry=registry)
    repl = Replicator(log, InProcessSink(receiver), registry=registry,
                      interval_ms=background_interval_ms or 200.0)
    if background_interval_ms:
        repl.start()

    oracle_sw = SlidingWindowOracle(cfg_sw)
    oracle_tb = TokenBucketOracle(cfg_tb)
    report = {"decisions": 0, "mismatches": 0, "lag_ms_samples": [],
              "frames": 0, "loss_wave_decisions": 0}

    def run_wave(storage) -> None:
        clock["t"] += rng.choice([1, 7, 250, 999, 2000, 2001])
        now = clock["t"]
        keys = [f"u{rng.randrange(n_keys)}" for _ in range(batch)]
        perms = [rng.choice([1, 1, 1, 2, 5, 21]) for _ in range(batch)]
        out = storage.acquire_many("sw", [lid_sw] * batch, keys, perms)
        for j in range(batch):
            d = oracle_sw.try_acquire(keys[j], perms[j], now)
            report["decisions"] += 1
            if (bool(out["allowed"][j]) != d.allowed
                    or int(out["observed"][j]) != d.observed):
                report["mismatches"] += 1
        out = storage.acquire_many("tb", [lid_tb] * batch, keys, perms)
        for j in range(batch):
            d = oracle_tb.try_acquire(keys[j], perms[j], now)
            report["decisions"] += 1
            if (bool(out["allowed"][j]) != d.allowed
                    or int(out["remaining"][j]) != d.remaining_hint):
                report["mismatches"] += 1

    try:
        for _ in range(max(kill_after_wave, 1)):
            run_wave(primary)
            if not background_interval_ms:
                report["frames"] += repl.ship_now()
                report["lag_ms_samples"].append(log.last_cut_lag_ms)
        if background_interval_ms:
            repl.stop()
        # Final deterministic epoch: everything up to here survives.
        report["frames"] += repl.ship_now()
        report["lag_ms_samples"].append(log.last_cut_lag_ms)
        snap_sw = copy.deepcopy(oracle_sw)
        snap_tb = copy.deepcopy(oracle_tb)
        promoted_epoch = log.epoch

        # Loss wave: mutations after the last replicated epoch die with
        # the primary.  The oracle rolls back to the snapshot below.
        pre = report["decisions"]
        run_wave(primary)
        report["loss_wave_decisions"] = report["decisions"] - pre
    finally:
        repl.stop()
        primary.close()  # the "crash"

    # Roll the oracle back to the promoted epoch: the loss wave's
    # mutations died with the primary, by contract.
    oracle_sw = snap_sw
    oracle_tb = snap_tb
    promoted = receiver.promote()
    assert promoted is standby

    for _ in range(post_waves):
        run_wave(promoted)
    promoted.close()
    report["promoted_epoch"] = promoted_epoch
    report["frames_applied"] = receiver.frames_applied
    if report["mismatches"]:
        raise AssertionError(
            f"failover drill diverged from the oracle: {report}")
    return report


# ---------------------------------------------------------------------------
# Shard failover drill (kill ONE shard of N mid-Zipf-stream, promote only it)
# ---------------------------------------------------------------------------

def shard_failover_drill(
    n_shards: int = 4,
    slots_per_shard: int = 512,
    n_keys: int = 96,
    waves: int = 5,
    kill_after_wave: int = 3,
    post_waves: int = 3,
    stream_n: int = 1536,
    batch: int = 32,
    kill_shard: int | None = None,
    seed: int = 0,
    registry=None,
    background_interval_ms: float | None = None,
    journal_kind: str = "auto",
) -> dict:
    """Deterministic ONE-shard-of-N failover drill, differential vs the
    oracle — the per-shard HA contract of shard-aware replication
    (replication/sharded.py).

    Topology: a sharded primary (``n_shards`` CPU-mesh shards) under a
    controlled clock, one flat same-geometry standby per shard (the
    standby mesh), per-shard epoch streams through the full frame
    pipeline.  Traffic is a Zipf int-key token-bucket stream (the
    headline shape, via ``acquire_stream_ids``) plus string-key
    sliding-window batches, every decision checked bit-exact against
    ``semantics/oracle.py``.

    After ``kill_after_wave`` waves the drill ships a final
    deterministic epoch for every shard, then runs one LOSS wave of
    victim-shard-only traffic that is never replicated, kills the
    victim shard (``ShardFailoverRouter.fail_shard``), and proves:

    - **survivors never stop**: a full traffic wave runs DURING the
      promotion window on the surviving shards, bit-identical to the
      oracle, while victim-shard requests are denied fail-closed
      (counted — bounded UNDER-admission, never unbounded over-
      admission);
    - **loss is bounded**: the loss wave's per-key admissions never
      exceed the policy ceiling (the over-admission bound of the
      promotion window: state the dead shard admitted but never
      replicated);
    - **single-shard promotion is exact**: after promoting ONLY the
      victim's standby (per-shard ``full`` re-baseline + index rebuilt
      from that shard's fingerprint journal through
      ``promote_from_replica``), every post-failover decision — victim
      keys on the promoted flat storage, survivor keys still on the
      primary — is bit-identical to the oracle;
    - the health surface reports the DEGRADED-shard state (router
      ``shard_health``), not DOWN.

    Returns a report dict; raises AssertionError on any violated claim.
    """
    import random

    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh
    from ratelimiter_tpu.parallel.sharded import shard_of_int_keys, shard_of_key
    from ratelimiter_tpu.replication import (
        ShardedReplicationLog,
        ShardedReplicator,
        ShardFailoverRouter,
        ShardStandbySet,
    )
    from ratelimiter_tpu.semantics.oracle import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    from ratelimiter_tpu.observability import flight_recorder

    frec = flight_recorder()
    fmark = frec.mark()
    rng = random.Random(seed)
    nrng = np.random.default_rng(seed)
    clock = {"t": 1_753_000_000_000}
    engine = ShardedDeviceEngine(
        slots_per_shard=slots_per_shard, table=LimiterTable(),
        mesh=make_mesh(n_devices=n_shards))
    primary = TpuBatchedStorage(engine=engine, clock_ms=lambda: clock["t"])
    router = ShardFailoverRouter(primary)
    cfg_tb = RateLimitConfig(max_permits=25, window_ms=2000,
                             refill_rate=8.0)
    cfg_sw = RateLimitConfig(max_permits=15, window_ms=2000,
                             enable_local_cache=False)
    lid_tb = primary.register_limiter("tb", cfg_tb)
    lid_sw = primary.register_limiter("sw", cfg_sw)
    mesh_set = ShardStandbySet(
        n_shards,
        lambda: TpuBatchedStorage(num_slots=slots_per_shard,
                                  clock_ms=lambda: clock["t"]),
        registry=registry)
    log = ShardedReplicationLog(primary, journal_kind=journal_kind)
    repl = ShardedReplicator(log, mesh_set.in_process_sinks(),
                             registry=registry,
                             interval_ms=background_interval_ms or 200.0)
    if background_interval_ms:
        repl.start()

    oracle_tb = TokenBucketOracle(cfg_tb)
    oracle_sw = SlidingWindowOracle(cfg_sw)
    report = {"decisions": 0, "mismatches": 0, "frames": 0,
              "loss_wave_decisions": 0, "loss_wave_admitted": 0,
              "window_decisions": 0, "window_denied": 0,
              "journal_kind": log.journal_kind}

    # Key population and victim selection: int keys route by the
    # splitmix hash; the victim is the shard owning the most keys (the
    # worst single-shard blast radius), unless the caller pinned one.
    key_shard = shard_of_int_keys(np.arange(n_keys, dtype=np.int64),
                                  n_shards)
    victim = (int(np.bincount(key_shard, minlength=n_shards).argmax())
              if kill_shard is None else int(kill_shard))
    sw_keys = [f"u{i}" for i in range(n_keys)]
    sw_shard = np.asarray([shard_of_key((lid_sw, k), n_shards)
                           for k in sw_keys])

    def zipf_keys(n):
        return (nrng.zipf(1.3, size=n) - 1) % n_keys

    def tb_wave(backend, keys, check=True):
        clock["t"] += rng.choice([1, 7, 250, 999, 2000, 2001])
        now = clock["t"]
        out = backend.acquire_stream_ids("tb", lid_tb,
                                         np.asarray(keys, dtype=np.int64))
        admitted = int(out.sum())
        if check:
            for k, got in zip(keys, out):
                d = oracle_tb.try_acquire(int(k), 1, now)
                report["decisions"] += 1
                if bool(got) != d.allowed:
                    report["mismatches"] += 1
        return admitted, len(out)

    def sw_wave(backend, idx_keys, check=True):
        clock["t"] += rng.choice([1, 7, 250, 999])
        now = clock["t"]
        keys = [sw_keys[i] for i in idx_keys]
        perms = [rng.choice([1, 1, 2, 5]) for _ in keys]
        out = backend.acquire_many("sw", [lid_sw] * len(keys), keys, perms)
        if check:
            for j, k in enumerate(keys):
                d = oracle_sw.try_acquire(k, perms[j], now)
                report["decisions"] += 1
                if (bool(out["allowed"][j]) != d.allowed
                        or int(out["observed"][j]) != d.observed):
                    report["mismatches"] += 1

    victim_tb_keys = np.nonzero(key_shard == victim)[0].astype(np.int64)
    survivor_tb_keys = np.nonzero(key_shard != victim)[0].astype(np.int64)
    survivor_sw_idx = np.nonzero(sw_shard != victim)[0]
    assert len(victim_tb_keys) and len(survivor_tb_keys), (
        "degenerate key split; raise n_keys")

    try:
        # Phase 1: healthy sharded soak, replicated per shard.
        for _ in range(max(kill_after_wave, 1)):
            tb_wave(router, zipf_keys(stream_n))
            sw_wave(router, [rng.randrange(n_keys) for _ in range(batch)])
            if not background_interval_ms:
                report["frames"] += repl.ship_now()
        if background_interval_ms:
            repl.stop()
        # Final deterministic epoch for EVERY shard: everything up to
        # here survives the kill.
        report["frames"] += repl.ship_now()
        report["promoted_epoch"] = log.epochs[victim]

        # Loss wave: victim-shard-only mutations after the last
        # replicated epoch — they die with the shard.  Checked against a
        # throwaway oracle copy (proves the primary still decided
        # correctly) but NEVER applied to the main oracle: the promoted
        # standby won't know them, by contract.
        import copy

        loss_oracle = copy.deepcopy(oracle_tb)
        clock["t"] += rng.choice([1, 7, 250])
        now = clock["t"]
        loss_keys = victim_tb_keys[
            nrng.integers(0, len(victim_tb_keys), size=min(stream_n, 512))]
        out = primary.acquire_stream_ids(
            "tb", lid_tb, np.asarray(loss_keys, dtype=np.int64))
        per_key_admitted: dict = {}
        for k, got in zip(loss_keys, out):
            d = loss_oracle.try_acquire(int(k), 1, now)
            report["loss_wave_decisions"] += 1
            if bool(got) != d.allowed:
                report["mismatches"] += 1
            if got:
                per_key_admitted[int(k)] = per_key_admitted.get(int(k),
                                                                0) + 1
        report["loss_wave_admitted"] = int(out.sum())
        # Bounded over-admission: what the dead shard admitted but never
        # replicated is capped per key by the policy ceiling.
        over = {k: c for k, c in per_key_admitted.items()
                if c > cfg_tb.max_permits}
        assert not over, f"loss-wave admissions exceeded the ceiling: {over}"
    finally:
        repl.stop()

    # r8 follow-up (PR 6): the victim dies with work still IN its drain
    # pool — one per-shard relay dispatch is enqueued on the victim's
    # device and deliberately NOT fetched before the kill, so the drill
    # proves single-shard promotion does not depend on the dead shard's
    # pipeline being quiesced.  (Victim-only post-epoch traffic: the
    # same loss class as the loss wave above — it dies with the shard.)
    undrained = None
    if hasattr(engine, "relay_shard_dispatch") and engine.relay_usable():
        word = np.array([1 << (engine.rank_bits + 1)], dtype=np.uint32)
        undrained = engine.relay_shard_dispatch(
            "tb", victim, "bits", word, np.int32(lid_tb), clock["t"])
    report["undrained_at_kill"] = undrained is not None

    # The kill: shard `victim` is gone.  Its standby survives.
    router.fail_shard(victim)
    health = router.shard_health()
    assert health[victim] == "failed" and all(
        v == "active" for q, v in health.items() if q != victim), health

    # Promotion window: survivors keep serving (bit-identical), victim
    # requests are denied fail-closed and counted.
    pre = report["decisions"]
    tb_wave(router, survivor_tb_keys[
        nrng.integers(0, len(survivor_tb_keys), size=min(stream_n, 512))])
    sw_wave(router, [int(survivor_sw_idx[rng.randrange(
        len(survivor_sw_idx))]) for _ in range(batch)])
    report["window_decisions"] = report["decisions"] - pre
    denied_before = router.unavailable_denies
    probe = victim_tb_keys[:8]
    got = router.acquire_stream_ids("tb", lid_tb, probe)
    assert not got.any(), "failed shard served during the window"
    report["window_denied"] = router.unavailable_denies - denied_before
    assert report["window_denied"] == len(probe)

    # Promote ONLY the victim's standby and route its keys there.
    promoted = mesh_set.promote(victim)
    router.install_replacement(victim, promoted)
    health = router.shard_health()
    assert health[victim] == "promoted", health

    # Post-failover: full mixed traffic through the router — victim keys
    # on the promoted flat storage, survivors on the primary — all
    # bit-identical to the oracle.
    for _ in range(post_waves):
        tb_wave(router, zipf_keys(stream_n))
        sw_wave(router, [rng.randrange(n_keys) for _ in range(batch)])

    # Flight-recorder timeline (ARCHITECTURE §13): the failover must
    # read back as kill -> promote -> serving replacement, in order,
    # all naming the victim shard.
    events = [e for e in frec.events(since=fmark)
              if e["kind"] in ("shard.failed", "replication.promote",
                               "shard.promoted")]
    kinds = [e["kind"] for e in events]
    timeline = iter(kinds)
    assert all(k in timeline for k in (
        "shard.failed", "replication.promote", "shard.promoted")), (
        f"flight recorder missed the failover timeline: {kinds}")
    for e in events:
        if "shard" in e:
            assert e["shard"] == victim, e
    report["flight_timeline"] = kinds

    if undrained is not None:
        # Promotion + post-failover serving all happened with the dead
        # shard's dispatch still undrained; the handle must also still
        # resolve (on the virtual mesh the device itself never dies) —
        # a wedged or poisoned handle here would mean promotion depended
        # on quiescing the victim's drain pool.
        assert np.asarray(undrained).shape[0] >= 1, (
            "undrained victim dispatch did not resolve after promotion")

    report["victim_shard"] = victim
    report["shard_health"] = router.shard_health()
    router.close()  # closes primary + promoted replacement
    mesh_set.close(except_shards=(victim,))
    if report["mismatches"]:
        raise AssertionError(
            f"shard failover drill diverged from the oracle: {report}")
    return report


# ---------------------------------------------------------------------------
# Orchestrated failover drill (ZERO manual promotion calls)
# ---------------------------------------------------------------------------

def orchestrated_failover_drill(
    n_shards: int = 4,
    slots_per_shard: int = 256,
    n_keys: int = 64,
    waves: int = 3,
    stream_n: int = 768,
    batch: int = 24,
    kill_shard: int | None = None,
    seed: int = 0,
    registry=None,
    probe_interval_ms: float = 50.0,
    suspect_threshold: int = 3,
    hysteresis_ms: float = 200.0,
    cycles: int = 1,
) -> dict:
    """Self-healing one-shard-of-N failover with ZERO manual actuator
    calls — the orchestrator (replication/orchestrator.py) must detect
    the kill, fence, promote, route, and re-seed on its own.

    Topology is the ``shard_failover_drill`` one (sharded primary under
    a controlled clock, in-process standby mesh, per-shard epoch
    streams) plus a ``FailoverOrchestrator`` driven by deterministic
    ``tick()`` calls against a SIMULATED monotonic clock — every probe,
    hysteresis window, and transition lands at an exact simulated
    millisecond, so the timeline assertions are exact.  Proves:

    - **detection is bounded**: kill -> FENCING within the configured
      probe budget (``suspect_threshold`` probes + hysteresis + one
      interval of phase slack), measured in simulated time;
    - **survivors serve during detection**: full survivor-shard waves
      run between probe ticks, bit-identical to the oracle;
    - **the zombie is fenced**: after FENCING, dispatching the victim
      shard's keys DIRECTLY at the primary (router bypassed — the
      zombie shape) raises the typed ``FencedError`` and is counted;
      survivor keys dispatched directly still serve;
    - **promotion is exact**: post-promotion mixed traffic through the
      router is bit-identical to the oracle (victim keys on the
      promoted flat storage, survivors on the primary);
    - **the system returns to N+1**: the orchestrator re-seeds a FRESH
      standby for the promoted replica via a FULL frame; the drill
      asserts it is consistent, unpromoted, and byte-converged with
      the promoted storage;
    - **the flight recorder reads back in order**: MONITORING ->
      SUSPECT -> FENCING -> PROMOTING -> RESTORED -> MONITORING for the
      victim shard, with ``shard.failed`` before
      ``replication.promote`` before ``shard.promoted``.

    ``cycles > 1`` repeats kill -> promote -> re-seed against the shard
    that is now serving from a promoted flat replacement (the soak's
    kill-again path: the re-seeded standby is promoted next, proving
    re-seeding actually restores failover capacity).

    Returns a report dict; raises AssertionError on any violated claim.
    """
    import copy
    import random

    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh
    from ratelimiter_tpu.parallel.sharded import shard_of_int_keys, shard_of_key
    from ratelimiter_tpu.replication import (
        FailoverOrchestrator,
        OrchestratorConfig,
        ShardedReplicationLog,
        ShardedReplicator,
        ShardFailoverRouter,
        ShardStandbySet,
    )
    from ratelimiter_tpu.semantics.oracle import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.storage.errors import FencedError
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    from ratelimiter_tpu.observability import flight_recorder

    frec = flight_recorder()
    fmark = frec.mark()
    rng = random.Random(seed)
    nrng = np.random.default_rng(seed)
    clock = {"t": 1_753_000_000_000}
    engine = ShardedDeviceEngine(
        slots_per_shard=slots_per_shard, table=LimiterTable(),
        mesh=make_mesh(n_devices=n_shards))
    primary = TpuBatchedStorage(engine=engine, clock_ms=lambda: clock["t"])
    router = ShardFailoverRouter(primary)
    cfg_tb = RateLimitConfig(max_permits=25, window_ms=2000,
                             refill_rate=8.0)
    cfg_sw = RateLimitConfig(max_permits=15, window_ms=2000,
                             enable_local_cache=False)
    lid_tb = primary.register_limiter("tb", cfg_tb)
    lid_sw = primary.register_limiter("sw", cfg_sw)

    def standby_factory():
        return TpuBatchedStorage(num_slots=slots_per_shard,
                                 clock_ms=lambda: clock["t"])

    mesh_set = ShardStandbySet(n_shards, standby_factory, registry=registry)
    log = ShardedReplicationLog(primary)
    repl = ShardedReplicator(log, mesh_set.in_process_sinks(),
                             registry=registry)

    # Simulated monotonic clock: one probe interval per tick — the
    # orchestrator's hysteresis math runs on EXACT simulated time.
    sim = {"s": 0.0}
    dead = {"flag": False, "at_promotions": 0}
    probe_victim = [None]
    cfg = OrchestratorConfig(probe_interval_ms=probe_interval_ms,
                             suspect_threshold=suspect_threshold,
                             hysteresis_ms=hysteresis_ms,
                             promote_backoff_ms=1.0)

    def probe(q):
        # The victim's serving backend is "dead" from the kill until
        # THIS cycle's replacement is installed (a prior cycle's
        # replacement does not clear a fresh kill); everything else
        # answers.
        if dead["flag"] and q == probe_victim[0] \
                and orch.promotions == dead["at_promotions"]:
            return False
        return True
    orch = FailoverOrchestrator(
        router, mesh_set, repl, standby_factory=standby_factory,
        config=cfg, probe=probe, registry=registry,
        clock=lambda: sim["s"], sleep=lambda s: None)

    def tick(n=1):
        for _ in range(n):
            sim["s"] += cfg.probe_interval_ms / 1000.0
            orch.tick()

    oracle_tb = TokenBucketOracle(cfg_tb)
    oracle_sw = SlidingWindowOracle(cfg_sw)
    report = {"decisions": 0, "mismatches": 0, "frames": 0,
              "false_alarms": 0, "cycles": [], "manual_promotions": 0}

    key_shard = shard_of_int_keys(np.arange(n_keys, dtype=np.int64),
                                  n_shards)
    sw_keys = [f"u{i}" for i in range(n_keys)]
    sw_shard = np.asarray([shard_of_key((lid_sw, k), n_shards)
                           for k in sw_keys])

    def zipf_keys(n):
        return (nrng.zipf(1.3, size=n) - 1) % n_keys

    def tb_wave(backend, keys):
        clock["t"] += rng.choice([1, 7, 250, 999, 2000, 2001])
        now = clock["t"]
        out = backend.acquire_stream_ids("tb", lid_tb,
                                         np.asarray(keys, dtype=np.int64))
        for k, got in zip(keys, out):
            d = oracle_tb.try_acquire(int(k), 1, now)
            report["decisions"] += 1
            if bool(got) != d.allowed:
                report["mismatches"] += 1

    def sw_wave(backend, idx_keys):
        clock["t"] += rng.choice([1, 7, 250, 999])
        now = clock["t"]
        keys = [sw_keys[i] for i in idx_keys]
        perms = [rng.choice([1, 1, 2, 5]) for _ in keys]
        out = backend.acquire_many("sw", [lid_sw] * len(keys), keys, perms)
        for j, k in enumerate(keys):
            d = oracle_sw.try_acquire(k, perms[j], now)
            report["decisions"] += 1
            if (bool(out["allowed"][j]) != d.allowed
                    or int(out["observed"][j]) != d.observed):
                report["mismatches"] += 1

    try:
        for cycle in range(max(int(cycles), 1)):
            if cycle == 0:
                # Victim: the busiest shard (worst blast radius) unless
                # pinned; later cycles RE-KILL the same shard — its
                # serving backend is now the promoted replacement, so a
                # re-kill proves the re-seeded standby actually restored
                # failover capacity.
                counts = np.bincount(key_shard, minlength=n_shards)
                victim = (int(kill_shard) if kill_shard is not None
                          else int(counts.argmax()))
            probe_victim[0] = victim
            victim_tb = np.nonzero(key_shard == victim)[0].astype(np.int64)
            survivor_tb = np.nonzero(key_shard != victim)[0].astype(np.int64)
            survivor_sw = np.nonzero(sw_shard != victim)[0]
            assert len(victim_tb) and len(survivor_tb), (
                "degenerate key split; raise n_keys")

            # Healthy soak: traffic + ships + idle orchestrator ticks.
            for _ in range(max(waves, 1)):
                tb_wave(router, zipf_keys(stream_n))
                sw_wave(router, [rng.randrange(n_keys) for _ in range(batch)])
                report["frames"] += repl.ship_now()
                tick()
            assert orch.status()["shards"][victim]["state"] == "MONITORING"
            base_promotions = orch.promotions

            # Final deterministic epoch, then (first cycle only) the
            # loss wave: victim-only traffic that is never replicated —
            # it dies with the shard; checked against a throwaway
            # oracle, never the main one.  Later cycles skip it: the
            # promoted replacement's re-seed stream ships on every
            # orchestrator tick, so pre-fence mutations there SURVIVE
            # by design (less loss, not more).
            report["frames"] += repl.ship_now()
            if cycle == 0:
                loss_oracle = copy.deepcopy(oracle_tb)
                clock["t"] += rng.choice([1, 7, 250])
                now = clock["t"]
                loss_keys = victim_tb[nrng.integers(
                    0, len(victim_tb), size=min(stream_n, 256))]
                out = primary.acquire_stream_ids(
                    "tb", lid_tb, np.asarray(loss_keys, dtype=np.int64))
                for k, got in zip(loss_keys, out):
                    if bool(got) != loss_oracle.try_acquire(
                            int(k), 1, now).allowed:
                        report["mismatches"] += 1

            # THE KILL.  No actuator call follows — the orchestrator
            # must do everything.
            dead["flag"] = True
            dead["at_promotions"] = orch.promotions
            fence_before = orch.fence_epoch
            ticks_to_fence = 0
            while orch.fence_epoch == fence_before and ticks_to_fence < 64:
                tick()
                ticks_to_fence += 1
                # Survivors serve while detection is in progress.
                if ticks_to_fence == suspect_threshold:
                    tb_wave(router, survivor_tb[nrng.integers(
                        0, len(survivor_tb), size=min(stream_n, 256))])
            detection_ms = ticks_to_fence * cfg.probe_interval_ms
            assert orch.fence_epoch > fence_before, (
                "orchestrator never fenced the dead shard")
            assert detection_ms <= cfg.detection_budget_ms \
                + cfg.probe_interval_ms, (
                f"detection took {detection_ms} ms (simulated); budget "
                f"{cfg.detection_budget_ms} ms")

            # Promotion is same-tick; a few more ticks settle RESTORED
            # -> MONITORING (the re-seed FULL frame ships on a tick).
            settle = 0
            while (orch.status()["shards"][victim]["state"] != "MONITORING"
                   and settle < 32):
                tick()
                settle += 1
            assert orch.promotions == base_promotions + 1, (
                "orchestrator did not promote exactly once this cycle")
            assert router.shard_health()[victim] == "promoted"

            # Zombie check: the fenced old backend refuses victim-shard
            # keys DIRECTLY (router bypassed) with the typed error,
            # while survivor keys dispatched directly still serve.
            zombie = primary if cycle == 0 else zombie_prev
            rejected_before = orch.total_fence_rejected()
            try:
                zombie.acquire_stream_ids(
                    "tb", lid_tb, np.asarray(victim_tb[:8], dtype=np.int64))
                raise AssertionError(
                    "fenced zombie served victim-shard dispatches")
            except FencedError:
                pass
            assert orch.total_fence_rejected() > rejected_before
            if cycle == 0:
                # Shard-scoped fence: survivors through the SAME storage
                # still serve (their shards are not fenced).
                probe_keys = survivor_tb[:8]
                clock["t"] += 3
                got = primary.acquire_stream_ids(
                    "tb", lid_tb, np.asarray(probe_keys, dtype=np.int64))
                # Those direct dispatches hit real state: keep the
                # oracle in agreement (one permit each, same stamp).
                for j, k in enumerate(probe_keys):
                    d = oracle_tb.try_acquire(int(k), 1, clock["t"])
                    report["decisions"] += 1
                    if bool(got[j]) != d.allowed:
                        report["mismatches"] += 1

            # Back to N+1: a FRESH standby was re-seeded for the
            # promoted replica and is byte-converged with it.
            fresh_rx = mesh_set.receivers[victim]
            assert fresh_rx.consistent and not fresh_rx.promoted, (
                "re-seeded standby not consistent")
            promoted_storage = router.replacements[victim]
            from ratelimiter_tpu.replication import engine_state_fingerprint

            fp_p = engine_state_fingerprint(promoted_storage.engine)
            fp_s = engine_state_fingerprint(
                mesh_set.storages[victim].engine)
            np.testing.assert_array_equal(fp_p["tb"], fp_s["tb"])

            # Post-failover mixed traffic: bit-identical via the router.
            dead["flag"] = False
            for _ in range(2):
                tb_wave(router, zipf_keys(stream_n))
                sw_wave(router, [rng.randrange(n_keys) for _ in range(batch)])
                tick()
            report["cycles"].append({
                "victim": victim, "detection_ms": detection_ms,
                "fence_epoch": orch.fence_epoch})
            zombie_prev = promoted_storage

        # Flight-recorder timeline: the victim's state machine must read
        # back in order, and the failover triplet must be ordered.
        victim0 = report["cycles"][0]["victim"]
        trans = [(e["from"], e["to"]) for e in frec.events(since=fmark)
                 if e["kind"] == "orchestrator.transition"
                 and e["shard"] == victim0]
        expect = [("MONITORING", "SUSPECT"), ("SUSPECT", "FENCING"),
                  ("FENCING", "PROMOTING"), ("PROMOTING", "RESTORED"),
                  ("RESTORED", "MONITORING")]
        it = iter(trans)
        assert all(step in it for step in expect), (
            f"orchestrator timeline out of order: {trans}")
        kinds = [e["kind"] for e in frec.events(since=fmark)
                 if e["kind"] in ("shard.failed", "replication.promote",
                                  "shard.promoted")]
        it = iter(kinds)
        assert all(k in it for k in ("shard.failed", "replication.promote",
                                     "shard.promoted")), (
            f"failover triplet out of order: {kinds}")
        report["flight_transitions"] = trans
        report["false_alarms"] = orch.false_alarms
        report["promotions"] = orch.promotions
        report["reseeds"] = orch.reseeds
        report["fence_rejected"] = orch.total_fence_rejected()
        assert orch.false_alarms == 0, "healthy probes raised false alarms"
        if report["mismatches"]:
            raise AssertionError(
                f"orchestrated failover diverged from the oracle: {report}")
        return report
    finally:
        orch.close()
        repl.stop()
        router.close()
        mesh_set.close()


def lease_failover_drill(
    n_shards: int = 4,
    slots_per_shard: int = 256,
    n_keys: int = 16,
    burns: int = 600,
    budget: int = 16,
    seed: int = 0,
    registry=None,
    probe_interval_ms: float = 50.0,
    suspect_threshold: int = 3,
    hysteresis_ms: float = 200.0,
) -> dict:
    """Token leases under failure: dead clients, a killed shard, and an
    orchestrated promotion — with the lease over-admission bound held
    and the reserve/credit stream reconciling bit-identically against
    ``semantics/oracle.py`` once renewals drain.  Proves:

    - **wire collapse**: a leased client burning ``burns`` decisions
      spends <= burns/10 wire round trips (the >=10x frame reduction is
      the subsystem's reason to exist — the loopback bench gates the
      TCP version of the same claim);
    - **dead client is bounded by construction**: killing a client
      mid-burn strands only its outstanding budget, each per-key term
      <= the grant cap <= the policy's ``max_permits`` (the reserve
      kernel bounded every grant by the remaining-window budget), and
      the strand is reclaimed: after TTL expiry the key grants again;
    - **honor-or-revoke across failover**: the orchestrator kills one
      shard and promotes its standby with zero manual calls; burns made
      against outstanding leases during the failover window are honored
      locally (bounded by the outstanding budget at fence time), every
      renewal after the fence-epoch bump is REVOKED (never silently
      honored against the wrong backend), re-grants land on the
      promoted replacement carrying the new epoch, and the manager's
      ``over_admission`` counter accounts exactly the burns reported on
      revoked leases;
    - **bit-identical reconciliation**: after every lease is released
      and renewals drain, replaying the manager's recorded reserve/
      credit stream into the oracles reproduces the device counters
      bit-for-bit for every key (grants included — each replayed
      reserve must grant exactly what the device granted).

    Deterministic: controlled decision clock, simulated orchestrator
    clock, in-process transports.  Raises AssertionError on any
    violated claim; returns a report dict.
    """
    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.leases import DirectTransport, LeaseClient, LeaseManager
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh
    from ratelimiter_tpu.parallel.sharded import shard_of_key
    from ratelimiter_tpu.replication import (
        FailoverOrchestrator,
        OrchestratorConfig,
        ShardedReplicationLog,
        ShardedReplicator,
        ShardFailoverRouter,
        ShardStandbySet,
    )
    from ratelimiter_tpu.semantics.oracle import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    clock = {"t": 1_753_000_000_000}
    engine = ShardedDeviceEngine(
        slots_per_shard=slots_per_shard, table=LimiterTable(),
        mesh=make_mesh(n_devices=n_shards))
    primary = TpuBatchedStorage(engine=engine, clock_ms=lambda: clock["t"])
    router = ShardFailoverRouter(primary)
    cfg_tb = RateLimitConfig(max_permits=1 << 14, window_ms=60_000,
                             refill_rate=1000.0)
    cfg_sw = RateLimitConfig(max_permits=1 << 14, window_ms=60_000,
                             enable_local_cache=False)
    lid_tb = primary.register_limiter("tb", cfg_tb)
    lid_sw = primary.register_limiter("sw", cfg_sw)

    def standby_factory():
        return TpuBatchedStorage(num_slots=slots_per_shard,
                                 clock_ms=lambda: clock["t"])

    mesh_set = ShardStandbySet(n_shards, standby_factory, registry=registry)
    repl = ShardedReplicator(ShardedReplicationLog(primary),
                             mesh_set.in_process_sinks(), registry=registry)
    sim = {"s": 0.0}
    dead = {"flag": False}
    victim_box = [None]
    cfg = OrchestratorConfig(probe_interval_ms=probe_interval_ms,
                             suspect_threshold=suspect_threshold,
                             hysteresis_ms=hysteresis_ms,
                             promote_backoff_ms=1.0)

    def probe(q):
        return not (dead["flag"] and q == victim_box[0])

    orch = FailoverOrchestrator(
        router, mesh_set, repl, standby_factory=standby_factory,
        config=cfg, probe=probe, registry=registry,
        clock=lambda: sim["s"], sleep=lambda s: None)

    def tick(n=1):
        for _ in range(n):
            sim["s"] += cfg.probe_interval_ms / 1000.0
            orch.tick()

    mgr = LeaseManager(router, default_budget=budget, max_budget=budget,
                       ttl_ms=5_000.0, registry=registry, record_ops=True,
                       clock_ms=lambda: clock["t"])
    # Strict lease-only clients: every device mutation flows through the
    # replayable reserve/credit log (no per-decision fallback traffic).
    cli_tb = LeaseClient(DirectTransport(mgr), lid_tb, budget=budget,
                         clock_ms=lambda: clock["t"],
                         direct_fallback=False)
    cli_sw = LeaseClient(DirectTransport(mgr), lid_sw, budget=budget,
                         clock_ms=lambda: clock["t"],
                         direct_fallback=False)
    tb_keys = [f"lease-tb-{i}" for i in range(n_keys)]
    sw_keys = [f"lease-sw-{i}" for i in range(n_keys)]
    report = {"decisions": 0, "local_denies": 0}

    try:
        # -- Phase A: healthy leased burn (both algos) --------------------
        for i in range(burns):
            clock["t"] += 1
            assert cli_tb.try_acquire(tb_keys[i % n_keys]), "tb burn denied"
            assert cli_sw.try_acquire(sw_keys[i % n_keys]), "sw burn denied"
            report["decisions"] += 2
            if i % 100 == 0:
                repl.ship_now()
                tick()
        wire = cli_tb.wire_ops + cli_sw.wire_ops
        assert wire * 10 <= report["decisions"], (
            f"wire ops {wire} for {report['decisions']} decisions — "
            "the >=10x frame reduction failed in-process")
        report["wire_ops_healthy"] = wire

        # -- Phase B: dead client — bounded strand, reclaimed by TTL ------
        # A dedicated short-TTL manager so the expiry advance cannot
        # expire the main clients' leases (one lease per key per
        # manager; "dead-key" belongs only to this one).
        mgr_dead = LeaseManager(router, default_budget=budget,
                                max_budget=budget, ttl_ms=5.0,
                                record_ops=True,
                                clock_ms=lambda: clock["t"])
        cli_dead = LeaseClient(DirectTransport(mgr_dead), lid_tb,
                               budget=budget,
                               clock_ms=lambda: clock["t"],
                               direct_fallback=False)
        for i in range(budget // 2):
            assert cli_dead.try_acquire("dead-key")
        stranded = cli_dead.drop()
        assert set(stranded) == {"dead-key"}
        assert 0 < stranded["dead-key"]["remaining"] <= budget \
            <= cfg_tb.max_permits, "strand exceeds the grant bound"
        expired_before = mgr_dead.expired_total
        clock["t"] += int(mgr_dead.ttl_ms) + 1  # past the lease TTL
        g = mgr_dead.grant(lid_tb, "dead-key", budget)
        assert g.granted > 0, "expired lease still blocks the key"
        assert mgr_dead.expired_total == expired_before + 1
        mgr_dead.release(lid_tb, "dead-key", 0)
        report["stranded_budget"] = stranded["dead-key"]["remaining"]

        # -- Phase C: orchestrated failover — honor-or-revoke -------------
        # Victim: the shard holding the most leased tb keys.
        shard_of = {k: int(shard_of_key((lid_tb, k), n_shards))
                    for k in tb_keys}
        counts = [0] * n_shards
        for k in tb_keys:
            counts[shard_of[k]] += 1
        victim = victim_box[0] = int(np.argmax(counts))
        victim_keys = [k for k in tb_keys if shard_of[k] == victim]
        assert victim_keys, "degenerate key split; raise n_keys"
        # Complete replication BEFORE the kill: every charge is on the
        # standby, so the reconciliation phase is exact (the unshipped-
        # epoch delta is exactly the documented over-admission term).
        repl.ship_now()
        epoch_before = orch.fence_epoch
        dead["flag"] = True
        burned_after_fence = 0
        ticks = 0
        while orch.fence_epoch == epoch_before and ticks < 64:
            tick()
            ticks += 1
        assert orch.fence_epoch > epoch_before, "never fenced"
        # Burns against outstanding leases during the failover window
        # are honored LOCALLY — this is the bounded over-admission.
        outstanding_at_fence = {
            k: cli_tb._leases[k].remaining for k in victim_keys
            if k in cli_tb._leases}
        for k in victim_keys:
            lease = cli_tb._leases.get(k)
            while lease is not None and lease.remaining > 0:
                clock["t"] += 1
                assert cli_tb.try_acquire(k)
                burned_after_fence += 1
        assert burned_after_fence == sum(outstanding_at_fence.values())
        assert all(v <= budget <= cfg_tb.max_permits
                   for v in outstanding_at_fence.values()), (
            "outstanding budget exceeds the per-key bound")
        # Settle the promotion.
        settle = 0
        while (orch.status()["shards"][victim]["state"] != "MONITORING"
               and settle < 32):
            tick()
            settle += 1
        assert orch.promotions == 1
        assert router.shard_health()[victim] == "promoted"
        dead["flag"] = False
        # Every renewal now hits the fence-epoch check: REVOKED, then
        # the client re-grants against the promoted replacement.
        over_before = mgr.over_admission_total
        revoked_before = mgr.revoked_total
        used_unreported = {k: cli_tb._leases[k].used
                           for k in victim_keys if k in cli_tb._leases}
        post_burns = 0
        for k in victim_keys:
            clock["t"] += 1
            assert cli_tb.try_acquire(k), (
                "post-promotion re-grant failed to serve")
            post_burns += 1
        assert mgr.revoked_total > revoked_before, "no lease was revoked"
        assert cli_tb.revoked_seen >= 1
        # over_admission accounts exactly the burns reported on revoked
        # leases (every other burn was reported on a live renewal).
        assert mgr.over_admission_total - over_before == \
            sum(used_unreported.values()), (
            mgr.over_admission_total, over_before, used_unreported)
        # Fresh grants carry the new fence epoch.
        for k in victim_keys:
            if k in cli_tb._leases:
                assert cli_tb._leases[k].epoch == orch.fence_epoch, (
                    "re-grant does not carry the bumped fence epoch")
        # SCOPED revocation (ARCHITECTURE §14b): the fence above named
        # only the victim shard, so survivor-shard leases renew WITHOUT
        # a revocation or an epoch bounce — failover cost is O(leases
        # routing to the promoted shard), not O(clients).
        survivor_keys = [k for k in tb_keys if shard_of[k] != victim]
        assert survivor_keys, "degenerate key split; raise n_keys"
        revoked_settled = mgr.revoked_total
        survivor_epochs = {k: cli_tb._leases[k].epoch
                           for k in survivor_keys if k in cli_tb._leases}
        assert survivor_epochs, "no survivor lease left to renew"
        survivor_burns = 0
        for k in survivor_keys:
            lease = cli_tb._leases.get(k)
            # Drain the slice, then one more burn to force a wire RENEW
            # through the fence-epoch check.
            while lease is not None and lease.remaining > 0:
                clock["t"] += 1
                assert cli_tb.try_acquire(k), "survivor burn denied"
                survivor_burns += 1
            clock["t"] += 1
            assert cli_tb.try_acquire(k), "survivor renewal denied"
            survivor_burns += 1
        assert mgr.revoked_total == revoked_settled, (
            "a survivor-shard lease was revoked by the scoped fence")
        for k, ep in survivor_epochs.items():
            if k in cli_tb._leases:
                assert cli_tb._leases[k].epoch == ep, (
                    f"survivor {k!r} epoch bounced across the scoped "
                    f"promotion: {ep} -> {cli_tb._leases[k].epoch}")
        report["survivor_renewals"] = len(survivor_epochs)
        report["decisions"] += (burned_after_fence + post_burns
                                + survivor_burns)
        report["burned_after_fence"] = burned_after_fence
        report["revoked"] = mgr.revoked_total
        report["over_admission"] = mgr.over_admission_total

        # -- Phase D: drain + bit-identical reconciliation ----------------
        cli_tb.release_all()
        cli_sw.release_all()
        router.flush()
        oracle_tb = TokenBucketOracle(cfg_tb)
        oracle_sw = SlidingWindowOracle(cfg_sw)
        oracles = {"tb": oracle_tb, "sw": oracle_sw}
        # The two managers touch disjoint key sets, so appending the
        # dead-client log preserves per-key operation order.
        for op in mgr.ops + mgr_dead.ops:
            if op[0] == "reserve":
                _, algo, _lid, key, req, granted, ws, stamp = op
                g, w = oracles[algo].reserve(key, req, stamp)
                assert (g, w) == (granted, ws), (
                    f"replayed reserve diverged for {key!r}: oracle "
                    f"({g}, {w}) vs device ({granted}, {ws})")
            else:
                _, algo, _lid, key, unused, ws, stamp = op
                oracles[algo].credit(key, unused, ws, stamp)
        now = clock["t"]
        for k in tb_keys + ["dead-key"]:
            got = int(router.available_many("tb", lid_tb, [k])[0])
            want = oracle_tb.get_available_permits(k, now)
            assert got == want, (
                f"tb availability diverged for {k!r}: device {got} vs "
                f"oracle {want}")
        for k in sw_keys:
            got = int(router.available_many("sw", lid_sw, [k])[0])
            want = oracle_sw.get_available_permits(k, now)
            assert got == want, (
                f"sw availability diverged for {k!r}: device {got} vs "
                f"oracle {want}")
        report["local_denies"] = cli_tb.local_denies + cli_sw.local_denies
        report["status"] = mgr.status()
        report["promotions"] = orch.promotions
        report["fence_epoch"] = orch.fence_epoch
        return report
    finally:
        orch.close()
        repl.stop()
        router.close()
        mesh_set.close()


def aggregator_failover_drill(
    n_shards: int = 4,
    slots_per_shard: int = 256,
    n_keys: int = 12,
    burns: int = 500,
    bulk_budget: int = 192,
    slice_budget: int = 12,
    n_clients: int = 4,
    seed: int = 0,
    registry=None,
    probe_interval_ms: float = 50.0,
    suspect_threshold: int = 3,
    hysteresis_ms: float = 200.0,
) -> dict:
    """The edge aggregator tier under failure (ARCHITECTURE §14b): an
    aggregator killed mid-Zipf, its replacement resuming, and a scoped
    shard promotion revoking only the bulk leases it names.  Proves:

    - **multiplicative wire collapse**: ``n_clients`` clients burning a
      Zipf-skewed key set through one aggregator spend <= decisions/5
      upstream frames (the loopback bench gates the TCP version);
    - **death is bounded by the bulk budgets**: killing the aggregator
      WITHOUT a final flush strands only the subleased permits already
      in clients' hands — every burn after the death is served from
      those slices, and their sum is <= the dropped bulk budgets (the
      nesting invariant's fleet-level bound);
    - **TTL reclaims the carcass**: the dead aggregator's bulk leases
      expire at the core like any dead client's, and a re-granted
      aggregator takes the keys over cleanly;
    - **scoped revocation**: a victim-shard promotion revokes exactly
      the bulk pools whose keys route to that shard — survivor pools
      renew without revocation or epoch bounce (failover is
      O(affected aggregator pools), not O(clients)) — and the burns
      clients fold onto the revoked pools land in the core's
      ``lease.over_admission``, equal tier-to-tier;
    - **bit-identical reconciliation**: replaying the core manager's
      reserve/credit stream into ``semantics/oracle.py`` reproduces the
      device counters bit-for-bit for every key.

    Deterministic: controlled decision clock, simulated orchestrator
    clock, in-process transports.  Raises AssertionError on any
    violated claim; returns a report dict.
    """
    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.edge import EdgeAggregator
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.leases import DirectTransport, LeaseClient, LeaseManager
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh
    from ratelimiter_tpu.parallel.sharded import shard_of_key
    from ratelimiter_tpu.replication import (
        FailoverOrchestrator,
        OrchestratorConfig,
        ShardedReplicationLog,
        ShardedReplicator,
        ShardFailoverRouter,
        ShardStandbySet,
    )
    from ratelimiter_tpu.semantics.oracle import TokenBucketOracle
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    clock = {"t": 1_753_000_000_000}
    engine = ShardedDeviceEngine(
        slots_per_shard=slots_per_shard, table=LimiterTable(),
        mesh=make_mesh(n_devices=n_shards))
    primary = TpuBatchedStorage(engine=engine, clock_ms=lambda: clock["t"])
    router = ShardFailoverRouter(primary)
    cfg_tb = RateLimitConfig(max_permits=1 << 14, window_ms=60_000,
                             refill_rate=1000.0)
    lid = primary.register_limiter("tb", cfg_tb)

    def standby_factory():
        return TpuBatchedStorage(num_slots=slots_per_shard,
                                 clock_ms=lambda: clock["t"])

    mesh_set = ShardStandbySet(n_shards, standby_factory, registry=registry)
    repl = ShardedReplicator(ShardedReplicationLog(primary),
                             mesh_set.in_process_sinks(), registry=registry)
    sim = {"s": 0.0}
    dead = {"flag": False}
    victim_box = [None]
    cfg = OrchestratorConfig(probe_interval_ms=probe_interval_ms,
                             suspect_threshold=suspect_threshold,
                             hysteresis_ms=hysteresis_ms,
                             promote_backoff_ms=1.0)

    def probe(q):
        return not (dead["flag"] and q == victim_box[0])

    orch = FailoverOrchestrator(
        router, mesh_set, repl, standby_factory=standby_factory,
        config=cfg, probe=probe, registry=registry,
        clock=lambda: sim["s"], sleep=lambda s: None)

    def tick(n=1):
        for _ in range(n):
            sim["s"] += cfg.probe_interval_ms / 1000.0
            orch.tick()

    mgr = LeaseManager(router, default_budget=slice_budget,
                       max_budget=slice_budget, max_bulk_budget=bulk_budget,
                       ttl_ms=5_000.0, registry=registry, record_ops=True,
                       clock_ms=lambda: clock["t"])

    def make_aggregator():
        return EdgeAggregator(DirectTransport(mgr),
                              bulk_budget=bulk_budget,
                              slice_budget=slice_budget,
                              flush_ms=20.0, registry=registry,
                              clock_ms=lambda: clock["t"])

    agg = make_aggregator()
    clients = [LeaseClient(agg.session(), lid, budget=slice_budget,
                           clock_ms=lambda: clock["t"],
                           direct_fallback=False, telemetry=False)
               for _ in range(n_clients)]
    keys = [f"edge-{i}" for i in range(n_keys)]
    shard_of = {k: int(shard_of_key((lid, k), n_shards)) for k in keys}
    # Zipf-skewed draws: the hot keys every client hammers are exactly
    # where bulk leases multiply the collapse.
    rng = np.random.default_rng(seed)
    p = 1.0 / np.arange(1, n_keys + 1) ** 1.1
    draws = rng.choice(n_keys, size=burns + 200, p=p / p.sum())
    report = {"decisions": 0}

    try:
        # -- Phase A: healthy Zipf burn through one aggregator ------------
        for i in range(burns):
            clock["t"] += 1
            assert clients[i % n_clients].try_acquire(keys[draws[i]]), (
                "healthy edge burn denied")
            report["decisions"] += 1
            if i % 100 == 0:
                repl.ship_now()
                tick()
        agg.flush()  # settle burn reports before the kill window
        assert agg.upstream_frames * 5 <= report["decisions"], (
            f"{agg.upstream_frames} upstream frames for "
            f"{report['decisions']} decisions — the aggregator collapse "
            "failed in-process")
        report["wire_frames_healthy"] = agg.upstream_frames

        # -- Phase B: kill mid-Zipf — burns bounded by bulk budgets -------
        repl.ship_now()
        exposure = agg.drop()
        assert exposure["pools"] > 0 and exposure["subleases"] > 0, (
            "the kill caught no live subleases; raise burns")
        burned_after_death = 0
        for lc in clients:
            for k in list(lc._leases):
                lease = lc._leases[k]
                while lease.remaining > 0:
                    clock["t"] += 1
                    assert lc.try_acquire(k), "sliced burn denied"
                    burned_after_death += 1
        assert burned_after_death <= exposure["sliced_out"] \
            <= exposure["bulk_budget"] <= bulk_budget * n_keys, (
            f"burns after death ({burned_after_death}) escaped the "
            f"dropped bulk budgets ({exposure})")
        report["burned_after_death"] = burned_after_death
        report["exposure"] = exposure

        # -- Phase C: TTL reclaim + re-granted aggregator -----------------
        expired_before = mgr.expired_total
        clock["t"] += int(mgr.ttl_ms) + 1  # past the bulk-lease TTL
        agg2 = make_aggregator()
        for lc in clients:
            # The fleet re-points at the replacement aggregator; stale
            # client-side leases renew into it, fold conservatively, and
            # re-grant from fresh bulk pools.
            lc._t = agg2.session()
        for i in range(200):
            clock["t"] += 1
            assert clients[i % n_clients].try_acquire(
                keys[draws[burns + i]]), "post-reclaim burn denied"
            report["decisions"] += 1
        assert mgr.expired_total > expired_before, (
            "the dead aggregator's bulk leases never expired")
        assert agg2._pools, "replacement aggregator took no pools"

        # -- Phase D: scoped promotion revokes only victim pools ----------
        agg2.flush()  # settle pending reports; pools now current
        pool_epochs = {key: p_.epoch
                       for (_l, key), p_ in agg2._pools.items()}
        counts = [0] * n_shards
        for key in pool_epochs:
            counts[shard_of[key]] += 1
        victim = victim_box[0] = int(np.argmax(counts))
        victim_pools = [k for k in pool_epochs if shard_of[k] == victim]
        survivor_pools = [k for k in pool_epochs if shard_of[k] != victim]
        assert victim_pools and survivor_pools, (
            "degenerate pool split; raise n_keys")
        victim_budget = sum(p_.budget for (_l, key), p_ in
                            agg2._pools.items() if key in victim_pools)
        repl.ship_now()
        epoch_before = orch.fence_epoch
        dead["flag"] = True
        ticks = 0
        while orch.fence_epoch == epoch_before and ticks < 64:
            tick()
            ticks += 1
        assert orch.fence_epoch > epoch_before, "never fenced"
        settle = 0
        while (orch.status()["shards"][victim]["state"] != "MONITORING"
               and settle < 32):
            tick()
            settle += 1
        assert orch.promotions == 1
        dead["flag"] = False
        rev_before = agg2.scoped_revocations_total
        over_core_before = mgr.over_admission_total
        over_agg_before = agg2.over_admission_total
        agg2.flush()
        assert agg2.scoped_revocations_total - rev_before \
            == len(victim_pools), (
            f"scoped fence revoked {agg2.scoped_revocations_total - rev_before} "
            f"pools; expected exactly the {len(victim_pools)} victim pools")
        for (_l, key), p_ in agg2._pools.items():
            assert shard_of[key] != victim, (
                f"victim-shard pool {key!r} survived the fence")
            assert p_.epoch == pool_epochs[key], (
                f"survivor pool {key!r} epoch bounced: "
                f"{pool_epochs[key]} -> {p_.epoch}")
        # Clients still hold slices cut from the revoked pools: burning
        # them is the bounded over-admission window, and the fold-and-
        # flush lands those burns in the core's lease.over_admission.
        post_burns = 0
        for lc in clients:
            for k in list(lc._leases):
                if shard_of[k] != victim:
                    continue
                lease = lc._leases[k]
                while lease.remaining > 0:
                    clock["t"] += 1
                    assert lc.try_acquire(k), "revoked-slice burn denied"
                    post_burns += 1
                clock["t"] += 1
                # Renew folds the burns onto the dead pool, the client
                # re-grants from a fresh pool at the NEW epoch.
                assert lc.try_acquire(k), "post-promotion re-grant failed"
                post_burns += 1
        agg2.flush()  # dead pools' final burn reports land upstream
        report["decisions"] += post_burns
        assert agg2.over_admission_total - over_agg_before <= victim_budget, (
            "aggregator-tier over-admission escaped the revoked budgets")
        assert mgr.over_admission_total - over_core_before \
            == agg2.over_admission_total - over_agg_before, (
            f"core over_admission delta "
            f"{mgr.over_admission_total - over_core_before} != aggregator "
            f"fold delta {agg2.over_admission_total - over_agg_before}")
        for (_l, key), p_ in agg2._pools.items():
            if key in victim_pools:
                assert p_.epoch == orch.fence_epoch, (
                    f"re-granted pool {key!r} does not carry the bumped "
                    f"fence epoch")
        report["scoped_revocations"] = agg2.scoped_revocations_total
        report["over_admission"] = mgr.over_admission_total
        report["burned_after_fence"] = post_burns

        # -- Phase E: drain + bit-identical reconciliation ----------------
        for lc in clients:
            lc.release_all()
        agg2.release_all()
        router.flush()
        oracle = TokenBucketOracle(cfg_tb)
        for op in mgr.ops:
            if op[0] == "reserve":
                _, _algo, _lid, key, req, granted, ws, stamp = op
                g, w = oracle.reserve(key, req, stamp)
                assert (g, w) == (granted, ws), (
                    f"replayed reserve diverged for {key!r}: oracle "
                    f"({g}, {w}) vs device ({granted}, {ws})")
            else:
                _, _algo, _lid, key, unused, ws, stamp = op
                oracle.credit(key, unused, ws, stamp)
        now = clock["t"]
        for k in keys:
            got = int(router.available_many("tb", lid, [k])[0])
            want = oracle.get_available_permits(k, now)
            assert got == want, (
                f"availability diverged for {k!r}: device {got} vs "
                f"oracle {want}")
        report["status"] = mgr.status()
        report["edge_status"] = agg2.status()
        report["promotions"] = orch.promotions
        report["fence_epoch"] = orch.fence_epoch
        return report
    finally:
        orch.close()
        repl.stop()
        router.close()
        mesh_set.close()


def orchestrator_flap_drill(
    n_shards: int = 2,
    slots_per_shard: int = 128,
    n_keys: int = 48,
    flap_cycles: int = 3,
    seed: int = 0,
    registry=None,
    probe_interval_ms: float = 50.0,
    suspect_threshold: int = 2,
    hysteresis_ms: float = 300.0,
) -> dict:
    """Flap damping: a fault that HEALS inside the hysteresis window
    must never promote — and fencing must be a clean, liftable refusal.

    The victim shard's liveness probe runs over a real TCP hop through a
    :class:`FaultInjectingProxy`; each flap cycle calls ``partition()``
    (bytes dropped both ways, no RST — the silent-partition shape) long
    enough to enter SUSPECT, then ``heal()`` before the hysteresis
    window closes.  Asserts per the ISSUE contract:

    - every flap increments ``false_alarms`` and nothing else: zero
      promotions, zero fence epochs, every shard ``active``, the state
      machine back in MONITORING;
    - traffic before/during/after flaps is bit-identical to the oracle
      (no loss, because nothing was promoted);
    - a fence installed on the primary refuses the fenced shard's
      dispatches with the typed ``FencedError`` (counted) while the
      other shard's keys still serve — and ``lift_fence`` restores the
      fenced shard to exact service (the operator path after a
      verified-quiesced false-dead).

    Returns a report dict; raises AssertionError on any violated claim.
    """
    import random
    import socket as socket_mod
    import socketserver

    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh
    from ratelimiter_tpu.parallel.sharded import shard_of_int_keys
    from ratelimiter_tpu.replication import (
        FailoverOrchestrator,
        OrchestratorConfig,
        ShardedReplicationLog,
        ShardedReplicator,
        ShardFailoverRouter,
        ShardStandbySet,
    )
    from ratelimiter_tpu.semantics.oracle import TokenBucketOracle
    from ratelimiter_tpu.storage.errors import FencedError
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = random.Random(seed)
    nrng = np.random.default_rng(seed)
    clock = {"t": 1_753_000_000_000}
    engine = ShardedDeviceEngine(
        slots_per_shard=slots_per_shard, table=LimiterTable(),
        mesh=make_mesh(n_devices=n_shards))
    primary = TpuBatchedStorage(engine=engine, clock_ms=lambda: clock["t"])
    router = ShardFailoverRouter(primary)
    cfg_tb = RateLimitConfig(max_permits=20, window_ms=2000,
                             refill_rate=8.0)
    lid_tb = primary.register_limiter("tb", cfg_tb)

    def standby_factory():
        return TpuBatchedStorage(num_slots=slots_per_shard,
                                 clock_ms=lambda: clock["t"])

    mesh_set = ShardStandbySet(n_shards, standby_factory, registry=registry)
    log = ShardedReplicationLog(primary)
    repl = ShardedReplicator(log, mesh_set.in_process_sinks(),
                             registry=registry)

    # The victim's probe is a 1-byte echo over TCP THROUGH the chaos
    # proxy: partition() makes it time out exactly like a silently-dead
    # peer; heal() restores it.
    class _Echo(socketserver.BaseRequestHandler):
        def handle(self):
            try:
                if self.request.recv(1):
                    self.request.sendall(b"o")
            except OSError:
                pass

    class _EchoServer(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    echo = _EchoServer(("127.0.0.1", 0), _Echo)
    echo_thread = threading.Thread(target=echo.serve_forever, daemon=True)
    echo_thread.start()
    proxy = FaultInjectingProxy(echo.server_address[1], seed=seed).start()

    key_shard = shard_of_int_keys(np.arange(n_keys, dtype=np.int64),
                                  n_shards)
    victim = int(np.bincount(key_shard, minlength=n_shards).argmax())

    def tcp_probe_ok() -> bool:
        try:
            s = socket_mod.create_connection(("127.0.0.1", proxy.port),
                                             timeout=0.25)
            s.settimeout(0.25)
            s.sendall(b"p")
            ok = s.recv(1) == b"o"
            s.close()
            return ok
        except OSError:
            return False

    def probe(q):
        return tcp_probe_ok() if q == victim else True

    sim = {"s": 0.0}
    cfg = OrchestratorConfig(probe_interval_ms=probe_interval_ms,
                             suspect_threshold=suspect_threshold,
                             hysteresis_ms=hysteresis_ms)
    orch = FailoverOrchestrator(
        router, mesh_set, repl, standby_factory=standby_factory,
        config=cfg, probe=probe, registry=registry,
        clock=lambda: sim["s"], sleep=lambda s: None)

    def tick(n=1):
        for _ in range(n):
            sim["s"] += cfg.probe_interval_ms / 1000.0
            orch.tick()

    oracle_tb = TokenBucketOracle(cfg_tb)
    report = {"decisions": 0, "mismatches": 0, "false_alarms": 0,
              "fence_rejected": 0}

    def wave():
        clock["t"] += rng.choice([1, 7, 250, 999, 2000])
        now = clock["t"]
        keys = (nrng.zipf(1.3, size=384) - 1) % n_keys
        out = router.acquire_stream_ids(
            "tb", lid_tb, np.asarray(keys, dtype=np.int64))
        for k, got in zip(keys, out):
            d = oracle_tb.try_acquire(int(k), 1, now)
            report["decisions"] += 1
            if bool(got) != d.allowed:
                report["mismatches"] += 1

    try:
        # Healthy baseline.
        for _ in range(2):
            wave()
            repl.ship_now()
            tick()
        assert orch.false_alarms == 0

        # Flap cycles: partition long enough to enter SUSPECT, heal
        # before the hysteresis window closes.  The suspect window in
        # simulated time must stay strictly under hysteresis_ms.
        suspect_ticks = max(
            1, int(hysteresis_ms / probe_interval_ms) - suspect_threshold - 1)
        for cycle in range(flap_cycles):
            proxy.partition()
            tick(suspect_threshold)          # consecutive failures: SUSPECT
            state = orch.status()["shards"][victim]["state"]
            assert state == "SUSPECT", (cycle, state)
            tick(suspect_ticks)              # inside the window, still bad
            assert orch.status()["shards"][victim]["state"] == "SUSPECT"
            proxy.heal()                     # fault clears BEFORE hysteresis
            tick()
            assert orch.status()["shards"][victim]["state"] == "MONITORING"
            assert orch.false_alarms == cycle + 1
            wave()                           # serving throughout, exact
            repl.ship_now()
        assert orch.promotions == 0, "a transient fault was promoted"
        assert orch.fence_epoch == 0, "a transient fault installed a fence"
        assert all(v == "active" for v in router.shard_health().values())

        # Fence round-trip on the primary: the fenced shard's keys are
        # refused with the typed error (zombie shape), the other
        # shard's keys keep serving, and lift_fence restores exact
        # service.
        victim_keys = np.nonzero(key_shard == victim)[0].astype(np.int64)
        other_keys = np.nonzero(key_shard != victim)[0].astype(np.int64)
        primary.fence(1, shards=(victim,))
        try:
            primary.acquire_stream_ids("tb", lid_tb, victim_keys[:8])
            raise AssertionError("fenced shard served a direct dispatch")
        except FencedError:
            pass
        assert primary.fence_rejected >= 1
        report["fence_rejected"] = primary.fence_rejected
        clock["t"] += 7
        got = primary.acquire_stream_ids("tb", lid_tb, other_keys[:8])
        for k, g in zip(other_keys[:8], got):
            d = oracle_tb.try_acquire(int(k), 1, clock["t"])
            report["decisions"] += 1
            if bool(g) != d.allowed:
                report["mismatches"] += 1
        primary.lift_fence(1)
        wave()                               # victim keys serve again, exact

        report["false_alarms"] = orch.false_alarms
        report["victim"] = victim
        if report["mismatches"]:
            raise AssertionError(
                f"flap drill diverged from the oracle: {report}")
        return report
    finally:
        orch.close()
        repl.stop()
        proxy.stop()
        echo.shutdown()
        echo.server_close()
        router.close()
        mesh_set.close()


# ---------------------------------------------------------------------------
# Cross-host failover drill: real OS processes, injected partitions
# ---------------------------------------------------------------------------

def cross_host_failover_drill(
    num_slots: int = 512,
    n_keys: int = 24,
    waves: int = 3,
    pipeline: int = 16,
    seed: int = 0,
    probe_interval_ms: float = 100.0,
    suspect_threshold: int = 3,
    hysteresis_ms: float = 300.0,
    lease_ttl_ms: float = 1200.0,
    witness_fresh_ms: float = 500.0,
    lease_budget: int = 12,
    boot_timeout_s: float = 180.0,
    registry=None,
) -> dict:
    """Cross-host failover with shard primary, standby, and orchestrator
    in SEPARATE OS PROCESSES (ARCHITECTURE §10c) — this process plays
    the orchestrator; the primary and standby are real subprocesses
    (``replication/hostproc.py``) joined by TCP through
    :class:`FaultInjectingProxy` links, so a ``partition()`` is a real
    silent byte-drop between processes, not a mock.

    Proves the ISSUE 14 contract:

    - **orchestrator-partitioned-from-healthy-shard -> nothing happens**:
      with only the orchestrator->primary control link cut, the standby
      witness (replication heartbeats still landing) VETOES fencing, the
      serving lease keeps renewing via the standby relay path (deposit
      -> mailbox -> primary's lease keeper), and after longer than a
      full lease TTL the primary is still serving bit-identically: zero
      promotions, zero fences, zero self-fences.
    - **partitioned primary self-fences within one lease TTL**: with the
      primary fully isolated (control + replication + relay links all
      cut) its lease runs down and the first decision past the deadline
      self-fences — measured from the partition instant by a
      partition-side client (the zombie's own clients), within one TTL
      plus slack.  Decisions it admitted before that are the documented
      over-admission window: per key at most ``max_permits`` per window
      (storage/degraded.py's bound), and a leased client's local burns
      are bounded by its outstanding budget at the cut.
    - **promotion waits out the zombie's lease, then lands**: the fence
      RPC cannot be delivered, so the orchestrator holds FENCING until
      every grant it issued has provably expired, then drives the
      remote-promotion RPC; the promoted standby opens a sidecar and
      serves the SAME keyspace bit-identical to ``semantics/oracle.py``.
    - **token leases are revoked-or-honored**: a renewal of the zombie-
      era lease against the promoted server is REVOKED (it carries a
      strictly higher fence epoch) and the re-grant lands with that
      higher epoch — never honored across the promotion boundary.

    Bit-identity across processes uses TIME-INSENSITIVE policies (token
    bucket with ``refill_rate=0``, sliding window with a multi-decade
    window) so wall-clock skew between the subprocesses and this
    process's oracle cannot change any decision.

    Returns a report dict; raises AssertionError on any violated claim.
    """
    import json as json_mod
    import os
    import subprocess
    import sys

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.replication.control import ControlClient
    from ratelimiter_tpu.replication.orchestrator import (
        FailoverOrchestrator,
        OrchestratorConfig,
    )
    from ratelimiter_tpu.replication.remote import (
        FanoutLeaseChannel,
        RemoteBackend,
        RemoteReceiver,
        RemoteShardDirectory,
        RemoteStandbySet,
        standby_witness,
    )
    from ratelimiter_tpu.semantics.oracle import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.service import sidecar as sc

    rng = random.Random(seed)
    # Time-insensitive policies (docstring): decisions depend only on
    # arrival ORDER, so the oracle needs no cross-process clock.  2^30
    # ms (~12.4 days, the config ceiling) means the drill runs inside
    # one never-rolling window with a fresh (zero) previous window —
    # sliding-window position weighting contributes exactly 0 on both
    # sides regardless of stamp skew.
    GIANT_WINDOW = 1 << 30
    # A refill rate whose FIXED-POINT form is exactly 0 fp-units/ms:
    # positive for the oracle's validation, but both sides add exactly
    # zero tokens per elapsed ms — the bucket is order-only.
    cfg_tb = RateLimitConfig(max_permits=30, window_ms=GIANT_WINDOW,
                             refill_rate=1e-9)
    assert cfg_tb.refill_rate_fp == 0, "drill needs an order-only bucket"
    cfg_sw = RateLimitConfig(max_permits=18, window_ms=GIANT_WINDOW,
                             enable_local_cache=False)
    limiters_spec = json_mod.dumps([
        {"algo": "tb", "max_permits": cfg_tb.max_permits,
         "window_ms": cfg_tb.window_ms, "refill_rate": cfg_tb.refill_rate},
        {"algo": "sw", "max_permits": cfg_sw.max_permits,
         "window_ms": cfg_sw.window_ms},
    ])
    NOW = 1_753_000_000_000  # fixed oracle stamp (its window never rolls)

    procs: list = []
    proxies: list = []
    clients: list = []
    orch = None

    def spawn(args):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ratelimiter_tpu.replication.hostproc",
             *args],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            env=env)
        procs.append(proc)
        box: dict = {}

        def rd():
            box["line"] = proc.stdout.readline()

        t = threading.Thread(target=rd, daemon=True)
        t.start()
        t.join(boot_timeout_s)
        line = box.get("line")
        if not line:
            proc.terminate()
            raise RuntimeError(
                f"hostproc {args} did not become ready within "
                f"{boot_timeout_s}s")
        return proc, json_mod.loads(line)

    def proxy_for(port):
        p = FaultInjectingProxy(port, seed=seed).start()
        proxies.append(p)
        return p

    def poll(pred, timeout_s, what):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.02)
        raise AssertionError(f"timed out waiting for {what}")

    report = {"decisions": 0, "mismatches": 0, "zombie_allows": {}}
    try:
        # -- topology -----------------------------------------------------
        _, standby_info = spawn(["--role", "standby",
                                 "--num-slots", str(num_slots), "--lease"])
        p_repl = proxy_for(standby_info["repl_port"])      # primary->standby data
        p_relay = proxy_for(standby_info["control_port"])  # primary->standby relay
        _, primary_info = spawn([
            "--role", "primary", "--num-slots", str(num_slots), "--lease",
            "--limiters", limiters_spec,
            "--repl-target", f"127.0.0.1:{p_repl.port}",
            "--standby-control", f"127.0.0.1:{p_relay.port}",
            "--repl-interval-ms", "100",
        ])
        lid_tb, lid_sw = primary_info["lids"]
        p_ctl = proxy_for(primary_info["control_port"])    # orch->primary control

        def ctl(port, timeout=0.5):
            c = ControlClient("127.0.0.1", port, timeout=timeout)
            clients.append(c)
            return c

        # The orchestrator's view: primary through ITS (cuttable) link,
        # standby direct (that link is never the one partitioned here).
        primary_backend = RemoteBackend(ctl(p_ctl.port))
        directory = RemoteShardDirectory({0: primary_backend})
        rx = RemoteReceiver(ctl(standby_info["control_port"], timeout=2.0),
                            promote_timeout_s=60.0)
        standby_set = RemoteStandbySet([rx])
        witness = standby_witness({0: ctl(standby_info["control_port"])},
                                  fresh_ms=witness_fresh_ms)
        lease_channels = {0: FanoutLeaseChannel(
            primary_backend, ctl(standby_info["control_port"]))}
        # Drill-side DIRECT taps (assertions only, never partitioned).
        prim_direct = ctl(primary_info["control_port"], timeout=2.0)

        def probe(q):
            backend = directory.serving(q)
            return backend is not None and backend.is_available()

        orch = FailoverOrchestrator(
            directory, standby_set, None, standby_factory=None,
            config=OrchestratorConfig(
                probe_interval_ms=probe_interval_ms,
                suspect_threshold=suspect_threshold,
                hysteresis_ms=hysteresis_ms,
                promote_retries=2, promote_backoff_ms=100.0,
                reseed=False,
                fence_lease_ttl_ms=lease_ttl_ms,
                fence_wait_slack_ms=150.0),
            probe=probe, witness=witness, lease_channels=lease_channels,
            registry=registry).start()

        # -- healthy phase ------------------------------------------------
        oracle_tb = TokenBucketOracle(cfg_tb)
        oracle_sw = SlidingWindowOracle(cfg_sw)
        client = sc.SidecarClient("127.0.0.1", primary_info["sidecar_port"])
        assert client.server_version >= 3, "primary handshake failed"

        def wave(via, n=None):
            """One pipelined oracle-checked wave on the main keyspace."""
            keys = [f"k{rng.randrange(n_keys)}"
                    for _ in range(n or pipeline)]
            perms = [rng.choice([1, 1, 2, 3]) for _ in keys]
            for lid, oracle in ((lid_tb, oracle_tb), (lid_sw, oracle_sw)):
                got = via.acquire_batch(lid, keys, perms)
                for j, (status, allowed, rem) in enumerate(got):
                    assert status == sc.ST_OK, (lid, j, status, rem)
                    d = oracle.try_acquire(keys[j], perms[j], NOW)
                    report["decisions"] += 1
                    if allowed != d.allowed or (
                            lid == lid_tb and int(rem) != d.remaining_hint):
                        report["mismatches"] += 1

        for _ in range(max(waves, 1)):
            wave(client)
        poll(lambda: prim_direct.call_ok("probe")["lease"]["installed"],
             10.0, "the orchestrator's first serving-lease grant")
        assert not prim_direct.call_ok("probe")["lease"]["expired"]
        # Let replication settle (the standby's first frame apply pays
        # the write_rows compile) before any partition goes in — the
        # witness freshness signal must be steady from here on.
        poll(lambda: rx.consistent and rx.last_epoch >= 1, 60.0,
             "standby consistency after the healthy phase")

        # -- scenario A: orchestrator partitioned from a HEALTHY shard ----
        fences_before = orch.fence_epoch
        p_ctl.partition()
        t_cut_a = time.monotonic()
        # Hold the partition past a full lease TTL (only the standby-
        # relayed renewals can then be keeping the primary leased) AND
        # past at least one full veto cycle — each failing probe blocks
        # for the control timeout, so a SUSPECT->veto round is several
        # times the nominal probe cadence.
        need_s = lease_ttl_ms / 1000.0 * 1.5
        while (time.monotonic() - t_cut_a < need_s
               or (orch.witness_vetoes < 1
                   and time.monotonic() - t_cut_a < 20.0)):
            time.sleep(0.1)
            wave(client, n=4)  # the healthy primary keeps serving, exact
        hold_s = time.monotonic() - t_cut_a
        st = orch.status()
        assert st["promotions"] == 0, (
            "orchestrator promoted against a healthy-but-unreachable "
            f"shard: {st}")
        assert orch.fence_epoch == fences_before, (
            "orchestrator fenced a healthy-but-unreachable shard")
        assert st["witness_vetoes"] >= 1, (
            f"no witness veto recorded during the control partition: {st}")
        lease_a = prim_direct.call_ok("probe")["lease"]
        assert lease_a["installed"] and not lease_a["expired"], (
            f"relay renewals did not keep the healthy primary leased: "
            f"{lease_a}")
        assert not lease_a["self_fenced"]
        report["scenario_a"] = {
            "held_s": round(hold_s, 2),
            "witness_vetoes": st["witness_vetoes"],
            "lease": lease_a,
        }
        p_ctl.heal()
        poll(lambda: orch.status()["shards"][0]["state"] == "MONITORING"
             and directory.shard_health()[0] == "active", 10.0,
             "recovery after the control partition healed")
        wave(client)

        # -- scenario B: the primary is PARTITIONED (fully isolated) ------
        # Token lease: grant + local burns, THEN the pre-cut sync, so the
        # reserve charge is in the replica when the partition hits; the
        # cut follows immediately, well inside the lease's server TTL.
        from ratelimiter_tpu.leases.client import LeaseClient

        lease_transport = sc.SidecarClient("127.0.0.1",
                                           primary_info["sidecar_port"])
        burner = LeaseClient(lease_transport, lid_tb, budget=lease_budget,
                             direct_fallback=False, telemetry=False)
        for _ in range(3):
            assert burner.try_acquire("lz") is True
        old_epoch = burner._leases["lz"].epoch
        assert old_epoch >= 1, "grant carried no fence-generation epoch"
        prim_direct.call_ok("ship")  # pin the replica byte-exact
        poll(lambda: rx.consistent and rx.last_epoch >= 1, 10.0,
             "standby consistency before the kill")
        outstanding = burner._leases["lz"].remaining
        p_ctl.partition()
        p_repl.partition()
        p_relay.partition()
        t_cut = time.monotonic()

        # The zombie's own clients (this drill, on direct connections)
        # keep hitting it: fresh z-keys so the zombie's post-cut state
        # never touches the replicated keyspace the oracle tracks.
        zombie_allows: dict = {}
        burns_after_cut = 0
        while burner._leases.get("lz") is not None \
                and burner._leases["lz"].remaining > 0:
            assert burner.try_acquire("lz") is True
            burns_after_cut += 1
        assert burns_after_cut <= outstanding, (
            "a leased client burned past its outstanding budget")
        t_fence = None
        zi = 0
        while time.monotonic() - t_cut < lease_ttl_ms / 1000.0 + 2.0:
            zkey = f"z{zi % 8}"
            zi += 1
            try:
                if client.try_acquire(lid_tb, zkey):
                    zombie_allows[zkey] = zombie_allows.get(zkey, 0) + 1
            except (RuntimeError, ConnectionError, sc.SidecarShedError,
                    sc.SidecarSendError):
                t_fence = time.monotonic()
                break
            time.sleep(0.02)
        assert all(proc.poll() is None for proc in procs), (
            "a node process died during the partition — the refusal "
            "below would be a crash, not a self-fence")
        assert t_fence is not None, (
            "the isolated primary never self-fenced (lease expiry did "
            "not bite)")
        fence_after_s = t_fence - t_cut
        assert fence_after_s <= lease_ttl_ms / 1000.0 + 0.75, (
            f"self-fence took {fence_after_s:.2f}s; lease TTL is "
            f"{lease_ttl_ms / 1000.0:.2f}s")
        assert all(n <= cfg_tb.max_permits
                   for n in zombie_allows.values()), (
            f"zombie over-admitted past the per-key bound: "
            f"{zombie_allows}")
        report["zombie_allows"] = zombie_allows
        lease_b = prim_direct.call_ok("probe")["lease"]
        assert lease_b["self_fenced"], f"zombie not self-fenced: {lease_b}"

        # The orchestrator: SUSPECT -> (witness dead, no veto) ->
        # FENCING (fence RPC undeliverable -> wait out the lease) ->
        # PROMOTING -> remote promotion.
        poll(lambda: orch.promotions >= 1
             and directory.shard_health()[0] == "promoted",
             60.0, "the remote promotion")
        t_promoted = time.monotonic()
        assert t_promoted >= t_fence, (
            "replacement installed before the zombie's lease expired")
        assert orch.fence_epoch == fences_before + 1
        serve_port = standby_set.receivers[0].serve_port
        assert serve_port, "promoted standby opened no serving port"

        # Post-promotion: same keyspace, same oracle, bit-identical.
        promoted_client = sc.SidecarClient("127.0.0.1", serve_port)
        for _ in range(max(waves, 1)):
            wave(promoted_client)

        # Token leases across the boundary: the zombie-era lease is
        # REVOKED by the promoted server (strictly higher epoch), and
        # the re-grant carries that higher epoch.
        lease_wire = sc.SidecarClient("127.0.0.1", serve_port)
        revoked = lease_wire.lease_renew(lid_tb, "lz", used=0,
                                         requested=lease_budget)
        assert revoked is None, (
            "promoted server honored a zombie-era lease renewal")
        fresh = lease_wire.lease_grant(lid_tb, "lz",
                                       requested=lease_budget)
        assert fresh is not None and fresh.epoch > old_epoch, (
            f"re-grant epoch {fresh and fresh.epoch} not past the "
            f"zombie-era epoch {old_epoch}")
        promoted_lease = RemoteBackend(
            ctl(standby_info["control_port"])).serving_lease_info()
        assert promoted_lease["installed"] \
            and not promoted_lease["expired"], promoted_lease

        report["scenario_b"] = {
            "self_fence_after_s": round(fence_after_s, 3),
            "promotion_after_s": round(t_promoted - t_cut, 3),
            "lease_ttl_s": lease_ttl_ms / 1000.0,
            "burns_after_cut": burns_after_cut,
            "outstanding_at_cut": outstanding,
            "old_epoch": old_epoch,
            "new_epoch": fresh.epoch,
        }
        report["status"] = orch.status()
        if report["mismatches"]:
            raise AssertionError(
                f"cross-host drill diverged from the oracle: {report}")
        return report
    finally:
        if orch is not None:
            orch.close()
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for p in proxies:
            try:
                p.stop()
            except Exception:  # noqa: BLE001
                pass
        for proc in procs:
            try:
                proc.stdin.close()
            except Exception:  # noqa: BLE001
                pass
        for proc in procs:
            try:
                proc.wait(timeout=10.0)
            except Exception:  # noqa: BLE001
                proc.terminate()
                try:
                    proc.wait(timeout=5.0)
                except Exception:  # noqa: BLE001
                    proc.kill()


def rolling_upgrade_drill(
    num_slots: int = 256,
    n_keys: int = 24,
    waves: int = 2,
    pipeline: int = 12,
    seed: int = 0,
    zipf_s: float = 1.1,
    probe_interval_ms: float = 100.0,
    suspect_threshold: int = 3,
    hysteresis_ms: float = 300.0,
    lease_ttl_ms: float = 1200.0,
    witness_fresh_ms: float = 500.0,
    reseed_deadline_s: float = 90.0,
    boot_timeout_s: float = 180.0,
    full: bool = False,
    registry=None,
) -> dict:
    """Zero-loss rolling upgrade of a LIVE 2-shard cross-host cell
    (ARCHITECTURE §16): every node is replaced one at a time while
    Zipf-distributed traffic keeps flowing, with a mid-upgrade hard
    kill of the serving node thrown in — and every decision the cell
    emits stays bit-identical to ``semantics/oracle.py``.

    Topology (``full=False``, the fast CI shape): one 2-shard primary
    node ``P`` and one 2-shard standby node ``S``, both at ``--version
    v1``, run as real ``hostproc`` subprocesses under a
    :class:`~ratelimiter_tpu.fleet.manager.NodeManager`; this process
    plays the orchestrator + FleetAutopilot.  ``full=True`` (the slow
    soak) splits the primaries onto two single-shard nodes — a 3-node
    cell, drained one node at a time.

    The ladder:

    1. **Graceful standby swap** — spawn ``S2`` at v2, RETARGET both
       shards' replication streams at it (control-RPC full re-baseline,
       no restart of the primary), hand the consistent v2 replicas to
       the orchestrator (StandbySet + witness + lease-relay rewire via
       ``FleetAutopilot.install_standby``), retire ``S``.  Traffic
       never pauses.
    2. **Drain the serving node(s)** — ``mark_draining`` flips the
       drain-aware probe/witness: the orchestrator fences (deliverable
       — the node is healthy, just scheduled out) and promotes each
       shard onto the v2 standby.  The autopilot notices each consumed
       standby and — with ZERO operator calls — spawns a fresh v2
       node, re-targets the new serving side's stream at it, and hands
       the consistent replica back: the cell is N+1 again, inside
       ``reseed_deadline_s`` (asserted per job).
    3. **Mid-upgrade primary kill** — SIGKILL the node that now serves
       both shards.  Fence undeliverable -> the orchestrator waits out
       the serving lease TTL, promotes the re-seeded standbys, and the
       autopilot re-seeds AGAIN.  Decisions pinned before the kill
       (explicit ``ship``) are all in the replicas: zero decision loss.

    End state: every live node is at v2, every shard is promoted with
    a consistent unpromoted standby (N+1), and the full decision
    stream — across two handovers per shard — matched the oracle
    bit-for-bit.  Raises AssertionError on any violated claim.
    """
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.fleet import (
        DRAINING as NODE_DRAINING,
        FleetAutopilot,
        LocalExecutor,
        NodeManager,
    )
    from ratelimiter_tpu.replication.control import ControlClient
    from ratelimiter_tpu.replication.orchestrator import (
        FailoverOrchestrator,
        OrchestratorConfig,
    )
    from ratelimiter_tpu.replication.remote import (
        FanoutLeaseChannel,
        RemoteBackend,
        RemoteReceiver,
        RemoteShardDirectory,
        RemoteStandbySet,
        standby_witness,
    )
    from ratelimiter_tpu.semantics.oracle import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.service import sidecar as sc

    rng = random.Random(seed)
    # Same order-only policies as cross_host_failover_drill: decisions
    # depend only on arrival ORDER, so subprocess clock skew cannot
    # move a single verdict.
    GIANT_WINDOW = 1 << 30
    cfg_tb = RateLimitConfig(max_permits=30, window_ms=GIANT_WINDOW,
                             refill_rate=1e-9)
    assert cfg_tb.refill_rate_fp == 0, "drill needs an order-only bucket"
    cfg_sw = RateLimitConfig(max_permits=18, window_ms=GIANT_WINDOW,
                             enable_local_cache=False)
    limiters = [
        {"algo": "tb", "max_permits": cfg_tb.max_permits,
         "window_ms": cfg_tb.window_ms, "refill_rate": cfg_tb.refill_rate},
        {"algo": "sw", "max_permits": cfg_sw.max_permits,
         "window_ms": cfg_sw.window_ms},
    ]
    NOW = 1_753_000_000_000  # fixed oracle stamp (its window never rolls)
    # Zipf(s) traffic over the keyspace; keys land on shards by parity.
    zipf_w = [1.0 / float(r + 1) ** zipf_s for r in range(n_keys)]

    clients: list = []
    mgr = None
    orch = None

    def ctl(port, timeout=0.5):
        c = ControlClient("127.0.0.1", port, timeout=timeout)
        clients.append(c)
        return c

    def poll(pred, timeout_s, what):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.02)
        raise AssertionError(f"timed out waiting for {what}")

    report = {"decisions": 0, "mismatches": 0,
              "mode": "full" if full else "fast"}
    try:
        # -- topology: the v1 cell under fleet management -----------------
        mgr = NodeManager(
            executor=LocalExecutor(boot_timeout_s=boot_timeout_s),
            probe_interval_ms=probe_interval_ms,
            probe_timeout_s=1.0, registry=registry)
        s_node = mgr.spawn("S", "standby", shards=2, version="v1",
                           num_slots=num_slots, repl_interval_ms=100.0,
                           boot_timeout_s=boot_timeout_s)
        placements = {}
        if full:
            for q in (0, 1):
                p_node = mgr.spawn(
                    f"P{q}", "primary", shards=1, version="v1",
                    num_slots=num_slots, limiters=limiters,
                    repl_targets=[f"127.0.0.1:{s_node.repl_ports()[q]}"],
                    repl_interval_ms=100.0, boot_timeout_s=boot_timeout_s)
                placements[q] = (p_node.name, 0)
                mgr.mark_serving(p_node.name)
        else:
            p_node = mgr.spawn(
                "P", "primary", shards=2, version="v1",
                num_slots=num_slots, limiters=limiters,
                repl_targets=[f"127.0.0.1:{pt}"
                              for pt in s_node.repl_ports()],
                repl_interval_ms=100.0, boot_timeout_s=boot_timeout_s)
            placements = {0: ("P", 0), 1: ("P", 1)}
            mgr.mark_serving("P")

        def lids_of(node, shard_on_node):
            v = node.ready["lids"]
            if v and isinstance(v[0], list):
                return list(v[shard_on_node])
            return list(v)

        lids, cli, backends = {}, {}, {}
        for q, (pname, pshard) in placements.items():
            node = mgr.node(pname)
            lids[q] = lids_of(node, pshard)
            cli[q] = sc.SidecarClient("127.0.0.1",
                                      node.sidecar_ports()[pshard])
            assert cli[q].server_version >= 3, "primary handshake failed"
            backends[q] = RemoteBackend(ctl(node.control_port),
                                        label=pname, shard=pshard)

        directory = RemoteShardDirectory(backends)
        rxs = [RemoteReceiver(ctl(s_node.control_port, timeout=2.0),
                              promote_timeout_s=60.0, shard=q)
               for q in (0, 1)]
        standby_set = RemoteStandbySet(rxs)
        witness_ctls = {q: (ctl(s_node.control_port), q) for q in (0, 1)}
        inner_witness = standby_witness(witness_ctls,
                                        fresh_ms=witness_fresh_ms)
        lease_channels = {
            q: FanoutLeaseChannel(backends[q],
                                  ctl(s_node.control_port), shard=q)
            for q in (0, 1)}

        pilot = FleetAutopilot(
            mgr, None, standby_set, witness_ctls,
            node_defaults=dict(host="127.0.0.1", num_slots=num_slots,
                               repl_interval_ms=100.0,
                               boot_timeout_s=boot_timeout_s),
            version="v2", reseed_deadline_s=reseed_deadline_s)
        witness = pilot.witness_wrap(inner_witness)

        def probe(q):
            # Drain-fold: a shard still on its ORIGINAL backend whose
            # serving node is DRAINING probes "down" so the
            # orchestrator promotes away.  Once a replacement is
            # installed the fold is bypassed — the autopilot's binding
            # swap may trail the promotion by a manager tick, and the
            # stale DRAINING read must not re-suspect a shard that
            # already moved.
            if directory.replacements.get(q) is None:
                entry = pilot.serving_placement(q)
                if entry is not None:
                    node = mgr.nodes.get(entry[0])
                    if node is not None and node.state == NODE_DRAINING:
                        return False
            backend = directory.serving(q)
            return backend is not None and backend.is_available()

        orch = FailoverOrchestrator(
            directory, standby_set, None, standby_factory=None,
            config=OrchestratorConfig(
                probe_interval_ms=probe_interval_ms,
                suspect_threshold=suspect_threshold,
                hysteresis_ms=hysteresis_ms,
                promote_retries=2, promote_backoff_ms=100.0,
                reseed=False,
                fence_lease_ttl_ms=lease_ttl_ms,
                fence_wait_slack_ms=150.0),
            probe=probe, witness=witness, lease_channels=lease_channels,
            witness_fresh_ms=witness_fresh_ms,
            repl_heartbeat_ms=100.0,
            registry=registry).start()
        pilot.orch = orch
        for q, placement in placements.items():
            pilot.bind(q, placement, ("S", q))
        mgr.attach(pilot)
        mgr.start()

        # -- oracle-checked Zipf traffic ----------------------------------
        oracles = {q: (TokenBucketOracle(cfg_tb),
                       SlidingWindowOracle(cfg_sw)) for q in (0, 1)}

        def wave(n=None):
            ids = rng.choices(range(n_keys), weights=zipf_w,
                              k=n or pipeline)
            perms = [rng.choice([1, 1, 2, 3]) for _ in ids]
            by_shard = {0: [], 1: []}
            for kid, pm in zip(ids, perms):
                by_shard[kid % 2].append((f"k{kid}", pm))
            for q, items in by_shard.items():
                if not items:
                    continue
                keys = [k for k, _ in items]
                ps = [pm for _, pm in items]
                for slot, oracle in enumerate(oracles[q]):
                    got = cli[q].acquire_batch(lids[q][slot], keys, ps)
                    for j, (status, allowed, rem) in enumerate(got):
                        assert status == sc.ST_OK, (q, slot, j, status)
                        d = oracle.try_acquire(keys[j], ps[j], NOW)
                        report["decisions"] += 1
                        if allowed != d.allowed or (
                                slot == 0
                                and int(rem) != d.remaining_hint):
                            report["mismatches"] += 1

        def ship(q):
            """Pin shard q's replica byte-exact (the zero-loss cut
            protocol: pause -> ship -> cut)."""
            pname, pshard = pilot.serving_placement(q)
            node = mgr.node(pname)
            ctl(node.control_port, timeout=15.0).call_ok(
                "ship", shard=pshard, timeout=15.0)

        # -- healthy phase ------------------------------------------------
        for _ in range(max(waves, 1)):
            wave()
        poll(lambda: all(r.consistent and r.last_epoch >= 1 for r in rxs),
             60.0, "v1 standby consistency after the healthy phase")
        poll(lambda: all(
            directory.serving(q).serving_lease_info()["installed"]
            for q in (0, 1)), 10.0, "the first serving-lease grants")

        # -- step 1: graceful standby swap S -> S2 (v2) -------------------
        s2 = mgr.spawn("S2", "standby", shards=2, version="v2",
                       num_slots=num_slots, repl_interval_ms=100.0,
                       boot_timeout_s=boot_timeout_s)
        for q in (0, 1):
            backends[q].retarget("127.0.0.1", s2.repl_ports()[q],
                                 timeout_s=60.0)
            r = RemoteReceiver(ctl(s2.control_port, timeout=2.0),
                               promote_timeout_s=60.0, shard=q)
            poll(lambda r=r: r.consistent and not r.promoted, 30.0,
                 f"v2 standby consistency for shard {q}")
            pilot.install_standby(q, "S2", q, r,
                                  serving_backend=backends[q])
        mgr.retire("S")
        mgr.note_upgrade_step()
        for _ in range(max(waves, 1)):
            wave()

        # -- step 2: drain the serving node(s); autopilot re-seeds --------
        drain_list = ["P0", "P1"] if full else ["P"]
        for pname in drain_list:
            qs = [q for q in (0, 1)
                  if pilot.serving_placement(q)[0] == pname]
            for q in qs:
                ship(q)
            cur_rx = {q: standby_set.receivers[q] for q in qs}
            poll(lambda: all(cur_rx[q].consistent for q in qs), 10.0,
                 f"replicas pinned before draining {pname}")
            promos_before = orch.promotions
            reseeds_before = mgr.reseeds
            t_drain = time.monotonic()
            mgr.mark_draining(pname)
            poll(lambda: orch.promotions >= promos_before + len(qs)
                 and all(directory.shard_health()[q] == "promoted"
                         for q in qs),
                 30.0, f"graceful promote-away from {pname}")
            promote_s = time.monotonic() - t_drain
            for q in qs:
                poll(lambda q=q: cur_rx[q].serve_port, 10.0,
                     f"promoted serve port for shard {q}")
                cli[q] = sc.SidecarClient("127.0.0.1",
                                          cur_rx[q].serve_port)
            for _ in range(max(waves, 1)):
                wave()
            poll(lambda: mgr.reseeds >= reseeds_before + len(qs),
                 reseed_deadline_s + 60.0,
                 f"automated re-seed to N+1 after draining {pname}")
            for q in qs:
                r = standby_set.receivers[q]
                assert r.consistent and not r.promoted, (
                    f"shard {q} re-seed handed back an unusable standby")
            mgr.retire(pname)
            mgr.note_upgrade_step()
            report[f"drain_{pname}"] = {"promote_s": round(promote_s, 3)}
            for _ in range(max(waves, 1)):
                wave()

        # -- step 3: mid-upgrade hard kill of the serving node ------------
        victim = pilot.serving_placement(0)[0]
        assert victim == "S2" \
            and pilot.serving_placement(1)[0] == victim, (
                "upgrade ladder did not converge on the v2 node")
        for q in (0, 1):
            ship(q)
        cur_rx = {q: standby_set.receivers[q] for q in (0, 1)}
        poll(lambda: all(cur_rx[q].consistent for q in (0, 1)), 10.0,
             "fresh standbys pinned before the kill")
        promos_before = orch.promotions
        reseeds_before = mgr.reseeds
        t_kill = time.monotonic()
        mgr.kill(victim)
        poll(lambda: orch.promotions >= promos_before + 2
             and all(directory.shard_health()[q] == "promoted"
                     for q in (0, 1)),
             60.0, "promotion after the mid-upgrade primary kill")
        kill_promote_s = time.monotonic() - t_kill
        # The fence was undeliverable, so the promotion must have
        # waited out the serving lease the dead node still held.
        assert kill_promote_s >= lease_ttl_ms / 1000.0 * 0.5, (
            f"promotion after the kill landed in {kill_promote_s:.2f}s "
            f"— inside the {lease_ttl_ms / 1000.0:.2f}s lease TTL the "
            f"dead node could still have been serving under")
        for q in (0, 1):
            poll(lambda q=q: cur_rx[q].serve_port, 10.0,
                 f"post-kill serve port for shard {q}")
            cli[q] = sc.SidecarClient("127.0.0.1", cur_rx[q].serve_port)
        for _ in range(max(waves, 1)):
            wave()
        poll(lambda: mgr.reseeds >= reseeds_before + 2,
             reseed_deadline_s + 60.0,
             "automated re-seed to N+1 after the kill")
        for _ in range(max(waves, 1)):
            wave()

        # -- end state: v2 fleet, N+1 everywhere, zero divergence ---------
        for name in mgr.live_nodes():
            node = mgr.node(name)
            assert node.version == "v2", (
                f"live node {name} still at {node.version}")
        for q in (0, 1):
            r = standby_set.receivers[q]
            assert r.consistent and not r.promoted, (
                f"shard {q} ended without a consistent standby (N+0)")
            assert directory.shard_health()[q] == "promoted"
        assert not pilot.failed_jobs, pilot.failed_jobs
        assert pilot.completed and all(
            c["elapsed_s"] <= reseed_deadline_s
            for c in pilot.completed), (
            f"a re-seed job overran its deadline: {pilot.completed}")
        expected_steps = 3 if full else 2
        assert mgr.upgrade_steps == expected_steps, mgr.upgrade_steps
        assert orch.promotions == 4, orch.status()
        assert mgr.reseeds == 4 and mgr.respawns == 4, mgr.status()
        report.update(
            promotions=orch.promotions, respawns=mgr.respawns,
            reseeds=mgr.reseeds, upgrade_steps=mgr.upgrade_steps,
            kill_promote_s=round(kill_promote_s, 3),
            reseed_elapsed_s=[c["elapsed_s"] for c in pilot.completed],
            fleet=mgr.status(), orchestrator=orch.status())
        if report["mismatches"]:
            raise AssertionError(
                f"rolling upgrade diverged from the oracle: {report}")
        return report
    finally:
        if orch is not None:
            orch.close()
        if mgr is not None:
            mgr.close()
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


# ---------------------------------------------------------------------------
# Partitioned-controller drill (epoch-fenced leadership under partition)
# ---------------------------------------------------------------------------

def partitioned_controller_drill(
    num_slots: int = 256,
    n_keys: int = 12,
    pipeline: int = 40,
    pre_waves: int = 3,
    storm_waves: int = 3,
    seed: int = 0,
    zipf_s: float = 1.1,
    ttl_ms: float = 900.0,
    tick_ms: float = 50.0,
    detection_budget_s: float = 10.0,
    goodput_floor: float = 0.8,
    boot_timeout_s: float = 180.0,
    registry=None,
) -> dict:
    """Partition the controller LEADER mid-storm and prove the
    epoch-fence claims (ARCHITECTURE §15): two real ``hostproc`` cells
    under live Zipf traffic, an AIMD controller actuating over the
    epoch-fenced :class:`~ratelimiter_tpu.control.FleetControlPlane`,
    and a :class:`FaultInjectingProxy` cutting the leader's every
    member link at the worst moment.

    Topology: two single-shard primary nodes ``N0``/``N1`` (same
    limiter registrations, so lids and policy rows line up) under a
    :class:`~ratelimiter_tpu.fleet.manager.NodeManager`; controller
    candidate ``ctrl-a`` reaches the nodes THROUGH partitionable
    proxies, rival ``ctrl-b`` directly; a
    :class:`~ratelimiter_tpu.control.ControllerElection` attached to
    the manager re-elects from the probe tick (driven manually here
    for a deterministic timeline).

    The ladder:

    1. **Healthy baseline** — ``ctrl-a`` wins epoch 1 with a majority
       of seats; well-tenant Zipf waves flow to BOTH nodes and every
       decision is checked bit-identical against a generation-aware
       oracle (rebuilt from ``policy_info`` rows, fresh keys per wave
       so order-only configs stay exact); per-wave goodput recorded.
    2. **Storm + fleet-true cut** — a storm tenant hammers its sliding
       window far past the limit on both nodes; the leader's AIMD tick
       observes the FLEET-SUMMED signals and broadcasts a
       generation-stamped cut that must land on every node (one
       generation cell-wide), visible in the next wave's decisions.
    3. **Partition mid-storm** — both of ``ctrl-a``'s member links are
       silently cut (no RST, no FIN).  Its renewals stop landing a
       majority, so the OWN-CLOCK lease rule demotes it within
       ``ttl_ms``; the election then seats ``ctrl-b`` at epoch 2 and
       converges every node to one generation — all inside
       ``detection_budget_s``.
    4. **Zombie writes die at the seats** — the demoted ``ctrl-a``
       refuses to actuate (:class:`~ratelimiter_tpu.control.NotLeader`
       BEFORE any frame leaves it), and a forced ``set_policy`` frame
       carried at its stale epoch — after the partition heals — is
       refused by every seat (``stale_rejected``) with ZERO rows
       moved: policy generations and rows are byte-compared around
       the attempt.
    5. **Storm continues under the successor** — ``ctrl-b`` keeps
       cutting the storm tenant at monotone generations; the
       well tenant's storm-phase goodput stays >= ``goodput_floor`` x
       its pre-storm mean (the control-plane failover never dents the
       data plane).

    Raises AssertionError on any violated claim; returns the report.
    """
    from ratelimiter_tpu.control import (
        AdaptivePolicyController,
        ControlConfig,
        ControllerElection,
        FleetControlPlane,
        NotLeader,
    )
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.fleet import LocalExecutor, NodeManager
    from ratelimiter_tpu.replication.control import ControlClient
    from ratelimiter_tpu.replication.remote import RemoteBackend
    from ratelimiter_tpu.semantics.oracle import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.service import sidecar as sc

    rng = random.Random(seed)
    # Order-only policies (the cross-host drill idiom): decisions
    # depend only on arrival ORDER, so subprocess clock skew cannot
    # move a verdict — and a FRESH key under any policy row behaves
    # exactly like a fresh oracle built from that row.
    GIANT_WINDOW = 1 << 30
    cfg_well = RateLimitConfig(max_permits=30, window_ms=GIANT_WINDOW,
                               refill_rate=1e-9)
    assert cfg_well.refill_rate_fp == 0, "drill needs an order-only bucket"
    cfg_storm = RateLimitConfig(max_permits=18, window_ms=GIANT_WINDOW,
                                enable_local_cache=False)
    limiters = [
        {"algo": "tb", "max_permits": cfg_well.max_permits,
         "window_ms": cfg_well.window_ms,
         "refill_rate": cfg_well.refill_rate},
        {"algo": "sw", "max_permits": cfg_storm.max_permits,
         "window_ms": cfg_storm.window_ms},
    ]
    NOW = 1_753_000_000_000  # fixed oracle stamp (its window never rolls)
    zipf_w = [1.0 / float(r + 1) ** zipf_s for r in range(n_keys)]

    clients: list = []
    proxies: dict = {}
    controllers: dict = {}
    planes: list = []
    mgr = None
    election = None
    node_names = ("N0", "N1")

    def ctl(port, timeout=0.5):
        c = ControlClient("127.0.0.1", port, timeout=timeout)
        clients.append(c)
        return c

    def poll(pred, timeout_s, what):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if mgr is not None:
                # The probe/election heartbeat rides every wait: the
                # leader's own-clock lease must keep renewing or
                # self_check() would demote it for OUR idleness.
                mgr.tick()
            if pred():
                return
            time.sleep(0.02)
        raise AssertionError(f"timed out waiting for {what}")

    report = {"decisions": 0, "mismatches": 0, "waves": 0}
    wave_seq = [0]
    try:
        # -- topology: two single-shard cells under fleet management ------
        mgr = NodeManager(
            executor=LocalExecutor(boot_timeout_s=boot_timeout_s),
            probe_interval_ms=tick_ms, probe_timeout_s=1.0,
            registry=registry)
        nodes, cli = {}, {}
        for name in node_names:
            nodes[name] = mgr.spawn(
                name, "primary", shards=1, version="v1",
                num_slots=num_slots, limiters=limiters,
                boot_timeout_s=boot_timeout_s)
            mgr.mark_serving(name)
            cli[name] = sc.SidecarClient(
                "127.0.0.1", nodes[name].sidecar_ports()[0])
            clients.append(cli[name])
            assert cli[name].server_version >= 3, "node handshake failed"

        def lids_of(node):
            v = node.ready["lids"]
            return list(v[0]) if v and isinstance(v[0], list) else list(v)

        assert lids_of(nodes["N0"]) == lids_of(nodes["N1"]), (
            "cells must register identical lids for fleet-wide rows")
        lid_well, lid_storm = lids_of(nodes["N0"])

        # ctrl-a reaches every member THROUGH a partitionable proxy;
        # ctrl-b's links are direct — the partition cuts exactly one
        # controller's world.
        for name in node_names:
            proxies[name] = FaultInjectingProxy(
                nodes[name].control_port).start()
        # Short timeouts on the proxied links: during the partition the
        # leader's every call burns its full deadline (bytes vanish, no
        # RST), and detection latency stacks those timeouts.
        members_a = {
            name: RemoteBackend(ctl(proxies[name].port, timeout=0.3),
                                label=f"{name}-via-proxy", shard=0)
            for name in node_names}
        members_b = {
            name: RemoteBackend(ctl(nodes[name].control_port),
                                label=name, shard=0)
            for name in node_names}
        ceilings = {int(lid_well): ("tb", cfg_well),
                    int(lid_storm): ("sw", cfg_storm)}
        plane_a = FleetControlPlane("ctrl-a", members_a,
                                    limiters=ceilings, ttl_ms=ttl_ms)
        plane_b = FleetControlPlane("ctrl-b", members_b,
                                    limiters=ceilings, ttl_ms=ttl_ms)
        planes[:] = [plane_a, plane_b]
        election = ControllerElection([plane_a, plane_b],
                                      interval_ms=tick_ms,
                                      registry=registry)
        mgr.attach(election)
        ctrl_cfg = ControlConfig(
            interval_ms=tick_ms, window_ms=3000, target_excess=0.5,
            decrease_factor=0.5, floor_fraction=0.1)
        controllers["ctrl-a"] = AdaptivePolicyController(plane_a, ctrl_cfg)
        controllers["ctrl-b"] = AdaptivePolicyController(plane_b, ctrl_cfg)

        def node_info(name):
            return members_b[name].policy_info()

        def row_of(name, lid):
            return node_info(name)["lids"][str(lid)]

        def gens():
            return {name: int(node_info(name)["generation"])
                    for name in node_names}

        # -- step 1: ctrl-a wins the cell ---------------------------------
        mgr.tick()
        assert plane_a.is_leader and plane_a.epoch == 1, (
            plane_a.fleet_status())
        assert not plane_b.is_leader
        assert election.leader() is plane_a

        def wave(goodput_log=None):
            """One well-tenant Zipf wave against BOTH nodes, every
            decision checked against a fresh generation-aware oracle
            (rebuilt from the node's live policy row, fresh keys)."""
            mgr.tick()  # keep the leader lease + election heartbeat live
            wave_seq[0] += 1
            report["waves"] += 1
            ids = rng.choices(range(n_keys), weights=zipf_w, k=pipeline)
            perms = [rng.choice([1, 1, 2, 3]) for _ in ids]
            keys = [f"w{wave_seq[0]}:k{kid}" for kid in ids]
            admitted = offered = 0
            for name in node_names:
                row = row_of(name, lid_well)
                ocfg = RateLimitConfig(
                    max_permits=int(row["max_permits"]),
                    window_ms=int(row["window_ms"]),
                    refill_rate=float(row["refill_rate"]))
                oracle = TokenBucketOracle(ocfg)
                got = cli[name].acquire_batch(lid_well, keys, perms)
                for j, (status, allowed, rem) in enumerate(got):
                    assert status == sc.ST_OK, (name, j, status)
                    d = oracle.try_acquire(keys[j], perms[j], NOW)
                    report["decisions"] += 1
                    offered += 1
                    admitted += 1 if allowed else 0
                    if allowed != d.allowed \
                            or int(rem) != d.remaining_hint:
                        report["mismatches"] += 1
            if goodput_log is not None:
                goodput_log.append(admitted / max(offered, 1))

        def storm_wave():
            """Hammer the storm tenant far past its window on both
            nodes (denied >> admitted: the AIMD overload verdict)."""
            mgr.tick()  # keep the leader lease + election heartbeat live
            wave_seq[0] += 1
            keys = [f"s{wave_seq[0]}:hot"] * 50
            perms = [1] * len(keys)
            for name in node_names:
                row = row_of(name, lid_storm)
                ocfg = RateLimitConfig(
                    max_permits=int(row["max_permits"]),
                    window_ms=int(row["window_ms"]),
                    enable_local_cache=False)
                oracle = SlidingWindowOracle(ocfg)
                got = cli[name].acquire_batch(lid_storm, keys, perms)
                for j, (status, allowed, _rem) in enumerate(got):
                    assert status == sc.ST_OK, (name, j, status)
                    d = oracle.try_acquire(keys[j], perms[j], NOW)
                    report["decisions"] += 1
                    if allowed != d.allowed:
                        report["mismatches"] += 1

        pre_goodput: list = []
        storm_goodput: list = []
        for _ in range(max(pre_waves, 1)):
            wave(goodput_log=pre_goodput)

        # -- step 2: storm -> fleet-true AIMD cut at one generation -------
        for _ in range(max(storm_waves, 1)):
            storm_wave()
        mgr.tick()  # renew the lease right before actuation self_check
        controllers["ctrl-a"].tick()
        assert plane_a.last_broadcast_generation >= 1, (
            "the leader's AIMD tick observed a fleet-wide storm but "
            "broadcast nothing")
        cut_gen = plane_a.last_broadcast_generation
        poll(lambda: all(g == cut_gen for g in gens().values()), 5.0,
             "the storm cut to land on every node at one generation")
        for name in node_names:
            row = row_of(name, lid_storm)
            assert int(row["max_permits"]) < cfg_storm.max_permits, (
                f"{name} still serves the uncut storm policy: {row}")
            assert int(row["generation"]) == cut_gen
        wave(goodput_log=storm_goodput)  # the cut must not dent the well

        # -- step 3: partition the leader mid-storm -----------------------
        storm_wave()
        t_cut = time.monotonic()
        for proxy in proxies.values():
            proxy.partition()
        deadline = t_cut + detection_budget_s
        while time.monotonic() < deadline:
            mgr.tick()  # probe + election ride the SAME manager tick
            if plane_b.is_leader and not plane_a.is_leader:
                break
            time.sleep(tick_ms / 1000.0)
        detect_s = time.monotonic() - t_cut
        assert plane_b.is_leader and not plane_a.is_leader, (
            f"leadership not repaired within {detection_budget_s}s: "
            f"{election.status()}")
        assert detect_s <= detection_budget_s
        # Own-clock demotion: the partitioned leader could not tell a
        # rival from a dead network, so it had to assume the worst
        # within one TTL — before ctrl-b's epoch ever reached it.
        assert plane_a.demote_reason == "lease_expired", (
            plane_a.demote_reason)
        assert plane_b.epoch == plane_a.epoch + 1
        assert detect_s * 1000.0 >= ttl_ms * 0.5, (
            f"demotion landed in {detect_s * 1000:.0f}ms — inside half "
            f"the {ttl_ms:.0f}ms lease TTL, which smells like a rigged "
            f"clock, not an expiry")
        poll(lambda: len(set(gens().values())) == 1, 5.0,
             "generation convergence under the successor")
        wave(goodput_log=storm_goodput)  # traffic never paused

        # -- step 4: zombie writes die at the seats -----------------------
        # (a) The demoted plane self-fences BEFORE any frame leaves it.
        try:
            plane_a.set_policy(int(lid_storm), cfg_storm)
            raise AssertionError(
                "a demoted controller actuated a policy write")
        except NotLeader:
            pass
        # (b) The partition heals and the zombie's frames arrive late,
        # carried at its superseded epoch: every seat must refuse them
        # with ZERO rows moved.
        for proxy in proxies.values():
            proxy.heal()
        before = {name: node_info(name) for name in node_names}
        zombie_row = {str(lid_storm): {
            "algo": "sw", "max_permits": 999,
            "window_ms": cfg_storm.window_ms, "refill_rate": 0.0,
            "gen": max(before[n]["generation"]
                       for n in node_names) + 5}}
        stale_refused = 0
        for name in node_names:
            resp = members_a[name].set_policy_rows(
                zombie_row, plane_a.epoch, "ctrl-a")
            assert resp.get("stale_epoch") and not resp.get("applied"), (
                f"{name} accepted a write at the superseded epoch "
                f"{plane_a.epoch}: {resp}")
            stale_refused += 1
        after = {name: node_info(name) for name in node_names}
        for name in node_names:
            assert after[name]["generation"] == \
                before[name]["generation"], name
            assert after[name]["lids"] == before[name]["lids"], (
                f"{name} rows moved under a stale-epoch write")
            seat = after[name]["controller"]
            assert int(seat["stale_rejected"]) >= 1, seat
            assert seat["node"] == "ctrl-b" \
                and int(seat["epoch"]) == plane_b.epoch, seat

        # -- step 5: the storm continues under the successor --------------
        for _ in range(max(storm_waves, 1)):
            storm_wave()
        mgr.tick()  # renew the lease right before actuation self_check
        controllers["ctrl-b"].tick()
        assert plane_b.last_broadcast_generation > cut_gen, (
            "the successor's AIMD tick did not advance the generation")
        final_gen = plane_b.last_broadcast_generation
        poll(lambda: all(g == final_gen for g in gens().values()), 5.0,
             "the successor's cut to land on every node")
        for _ in range(2):
            wave(goodput_log=storm_goodput)
        storm_wave()

        # -- end state ----------------------------------------------------
        pre_mean = sum(pre_goodput) / len(pre_goodput)
        storm_mean = sum(storm_goodput) / len(storm_goodput)
        ratio = storm_mean / max(pre_mean, 1e-9)
        report.update(
            detect_s=round(detect_s, 3),
            epochs={"ctrl-a": plane_a.epoch, "ctrl-b": plane_b.epoch},
            demote_reason=plane_a.demote_reason,
            cut_generation=cut_gen, final_generation=final_gen,
            stale_refused=stale_refused,
            stale_rejected_total=sum(
                int(node_info(n)["controller"]["stale_rejected"])
                for n in node_names),
            pre_goodput=round(pre_mean, 4),
            storm_goodput=round(storm_mean, 4),
            goodput_ratio=round(ratio, 4),
            elections=election.elections,
            fleet=plane_b.fleet_status())
        assert ratio >= goodput_floor, (
            f"well-tenant goodput fell to {ratio:.2f}x its pre-storm "
            f"mean (floor {goodput_floor}x): the controller failover "
            f"dented the data plane: {report}")
        assert election.elections == 2 and plane_a.elections == 1 \
            and plane_b.elections == 1, election.status()
        if report["mismatches"]:
            raise AssertionError(
                f"decisions diverged from the generation-aware oracle: "
                f"{report}")
        return report
    finally:
        for controller in controllers.values():
            controller.stop()
        if election is not None:
            election.close()
        for plane in planes:
            try:
                plane.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for proxy in proxies.values():
            try:
                proxy.stop()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        if mgr is not None:
            mgr.close()
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


# ---------------------------------------------------------------------------
# Sustained-outage drill (breaker open -> degraded -> resync -> bit-identical)
# ---------------------------------------------------------------------------

def outage_drill(
    num_slots: int = 512,
    n_keys: int = 24,
    healthy_waves: int = 3,
    outage_waves: int = 4,
    post_waves: int = 3,
    batch: int = 24,
    seed: int = 0,
    failure_threshold: int = 4,
    max_retries: int = 2,
    open_ms: float = 5000.0,
    registry=None,
) -> dict:
    """Deterministic sustained-outage drill over the production composition
    ``retry(breaker(chaos(storage)))``, differential vs the oracle.

    Phases, all under a controlled clock:

    1. **Healthy** — mixed sw/tb waves through single ``acquire``; every
       decision checked bit-exact against ``semantics/oracle.py`` (and the
       breaker's healthy path snapshots each key's last counter into the
       degraded limiter's seed cache).
    2. **Outage** — every backend op is forced to fail.  The drill proves
       the breaker opens within ``ceil(threshold / attempts)`` requests
       (each retry attempt counts), then that decisions are served by the
       degraded host limiter — marked ``degraded``, ZERO backend calls
       (the short-circuit claim, checked against the injector's op log),
       and per-key-per-window admission never exceeds ``max_permits``
       (bounded over-admission: fail-*approximate*, not fail-open).
    3. **Recovery** — the fault is healed and the clock advanced past
       ``open_ms``; a half-open probe on a dedicated key closes the
       breaker, which resyncs: every key the degraded limiter mutated is
       reset on the device.  The drill mirrors those resets in the oracle.
    4. **Post-resync** — waves again, bit-identical vs the oracle.

    Returns a report dict; raises AssertionError on any violated claim.
    """
    import math
    import random

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.semantics.oracle import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.storage.breaker import (
        CLOSED,
        OPEN,
        CircuitBreakerStorage,
    )
    from ratelimiter_tpu.storage.degraded import DegradedHostLimiter
    from ratelimiter_tpu.storage.errors import RetryPolicy, StorageException
    from ratelimiter_tpu.storage.retry import RetryingStorage
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    from ratelimiter_tpu.observability import flight_recorder

    frec = flight_recorder()
    fmark = frec.mark()
    rng = random.Random(seed)
    clock = {"t": 1_753_000_000_000}
    inner = TpuBatchedStorage(num_slots=num_slots, clock_ms=lambda: clock["t"])
    chaos = FaultInjectingStorage(inner)
    fallback = DegradedHostLimiter(clock_ms=lambda: clock["t"],
                                   registry=registry)
    breaker = CircuitBreakerStorage(
        chaos, failure_threshold=failure_threshold, open_ms=open_ms,
        half_open_probes=1, clock_ms=lambda: clock["t"], fallback=fallback,
        registry=registry)
    storage = RetryingStorage(breaker, RetryPolicy(
        max_retries=max_retries, retry_delay_ms=0.01))

    cfg_sw = RateLimitConfig(max_permits=12, window_ms=2000,
                             enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=20, window_ms=2000, refill_rate=8.0)
    lid_sw = storage.register_limiter("sw", cfg_sw)
    lid_tb = storage.register_limiter("tb", cfg_tb)
    oracle_sw = SlidingWindowOracle(cfg_sw)
    oracle_tb = TokenBucketOracle(cfg_tb)

    report = {"decisions": 0, "mismatches": 0, "requests_to_open": 0,
              "degraded_decisions": 0, "over_admissions": 0,
              "touched_keys": 0, "shorted_backend_calls": 0}

    def one(algo, lid, oracle, key, permits, check=True):
        now = clock["t"]
        out = storage.acquire(algo, lid, key, permits)
        if not check:
            return out
        d = oracle.try_acquire(key, permits, now)
        report["decisions"] += 1
        hint = out.get("cache_value", out.get("remaining"))
        if (bool(out["allowed"]) != d.allowed
                or int(out["observed"]) != d.observed
                or int(hint) != d.remaining_hint):
            report["mismatches"] += 1
        return out

    def wave(check=True):
        clock["t"] += rng.choice([3, 17, 250, 999, 2000])
        for _ in range(batch):
            key = f"u{rng.randrange(n_keys)}"
            permits = rng.choice([1, 1, 1, 2, 5])
            one("sw", lid_sw, oracle_sw, key, permits, check=check)
            one("tb", lid_tb, oracle_tb, key, permits, check=check)

    try:
        # Phase 1: healthy, bit-identical.
        for _ in range(healthy_waves):
            wave()
        assert report["mismatches"] == 0, (
            f"healthy phase diverged from the oracle: {report}")

        # Phase 2: sustained outage.
        chaos.fail_next(10_000_000)
        budget = math.ceil(failure_threshold / max(max_retries, 1)) + 1
        opened_after = None
        for i in range(budget):
            try:
                storage.acquire("sw", lid_sw, f"u{i % n_keys}", 1)
            except StorageException:
                pass
            if breaker.state == OPEN:
                opened_after = i + 1
                break
        assert opened_after is not None, (
            f"breaker failed to open within {budget} requests of a "
            f"sustained outage (threshold={failure_threshold}, "
            f"attempts/request={max_retries})")
        report["requests_to_open"] = opened_after

        # Degraded service: no exceptions, no backend traffic, admission
        # bounded per key per window by the policy ceiling.
        backend_calls_at_open = len(chaos.calls)
        admitted: dict = {}
        for _ in range(outage_waves):
            clock["t"] += rng.choice([3, 17, 250, 999])
            for _ in range(batch):
                key = f"u{rng.randrange(n_keys)}"
                permits = rng.choice([1, 1, 2, 5])
                out = storage.acquire("sw", lid_sw, key, permits)
                assert out.get("degraded"), (
                    "breaker open but the decision did not come from the "
                    f"degraded host limiter: {out}")
                report["degraded_decisions"] += 1
                if out["allowed"]:
                    # The sw bucket counts REQUESTS (one increment per
                    # acquire regardless of permits — reference quirk
                    # Q1/Q2), so the per-bucket admission ceiling is
                    # max_permits requests.
                    win = clock["t"] // cfg_sw.window_ms
                    admitted[key, win] = admitted.get((key, win), 0) + 1
        report["shorted_backend_calls"] = (
            len(chaos.calls) - backend_calls_at_open)
        assert report["shorted_backend_calls"] == 0, (
            "degraded decisions still reached the backend: "
            f"{report['shorted_backend_calls']} op(s) after open")
        report["over_admissions"] = sum(
            1 for count in admitted.values() if count > cfg_sw.max_permits)
        assert report["over_admissions"] == 0, (
            f"degraded mode over-admitted past the policy ceiling: {admitted}")

        # Phase 3: heal, half-open probe, close + resync.
        chaos.heal()
        clock["t"] += int(open_ms) + 1
        touched = fallback.touched()
        report["touched_keys"] = len(touched)
        assert report["touched_keys"] > 0, "outage phase mutated no keys?"
        probe = storage.acquire("sw", lid_sw, "__probe__", 1)
        assert not probe.get("degraded") and breaker.state == CLOSED, (
            f"half-open probe did not close the breaker: state="
            f"{breaker.state}")
        assert breaker.resyncs_total == 1
        # Mirror the resync in the oracle: reset exactly the touched keys.
        oracle_sw.try_acquire("__probe__", 1, clock["t"])
        for algo, _lid, key in touched:
            (oracle_sw if algo == "sw" else oracle_tb).reset(key, clock["t"])

        # Phase 4: post-resync, bit-identical again.
        for _ in range(post_waves):
            wave()
        assert report["mismatches"] == 0, (
            f"post-resync decisions diverged from the oracle: {report}")

        # Flight-recorder timeline (ARCHITECTURE §13): the outage must
        # read back as open -> half_open -> close -> resync, in order.
        kinds = [e["kind"] for e in frec.events(kind="breaker",
                                                since=fmark)]
        timeline = iter(kinds)
        assert all(k in timeline for k in (
            "breaker.open", "breaker.half_open", "breaker.close",
            "breaker.resync")), (
            f"flight recorder missed the outage timeline: {kinds}")
        report["flight_timeline"] = kinds
    finally:
        storage.close()
    return report


# ---------------------------------------------------------------------------
# Overload drill (bounded queue depth, shed-not-hang, p99 under load)
# ---------------------------------------------------------------------------

def overload_drill(
    load_multipliers=(1.0, 2.0),
    max_pending: int = 256,
    deadline_ms: float = 1000.0,
    dispatch_ms: float = 5.0,
    max_batch: int = 32,
    bursts: int = 40,
    burst_interval_ms: float = 10.0,
    p99_slack_ms: float = 250.0,
) -> dict:
    """Drive a MicroBatcher over a fixed-rate synthetic device at 1x..Nx
    its capacity and prove the admission-control claims:

    - pending queue depth never exceeds ``max_pending`` (hard bound),
    - overload is SHED (typed ``OverloadedError`` with a positive
      Retry-After hint), never queued forever,
    - p99 latency of *admitted* requests stays within the queue-deadline
      budget plus a dispatch cycle (shedding protects the admitted).

    The synthetic device resolves a batch in ``dispatch_ms`` regardless of
    size, so capacity = ``max_batch / dispatch_ms`` requests/s and the
    offered load is ``multiplier * capacity`` submitted in bursts.  The
    defaults are deliberately coarse (deep queue, 1 s deadline) so that
    scheduler stalls on a loaded CI box do not read as overload; tighten
    them when measuring, not when gating.
    Returns per-multiplier stats; raises AssertionError on any violation.
    """
    import statistics

    from ratelimiter_tpu.engine.batcher import MicroBatcher
    from ratelimiter_tpu.engine.errors import OverloadedError

    capacity_rps = max_batch / (dispatch_ms / 1000.0)
    report = {"capacity_rps": capacity_rps, "runs": []}

    for mult in load_multipliers:
        def dispatch(slots, lids, permits):
            # Cost scales with the number of max_batch-sized device steps:
            # the flusher hands over whatever accumulated, and an elastic
            # single-sleep model would let a deep queue raise capacity.
            n = len(slots)
            time.sleep(-(-n // max_batch) * dispatch_ms / 1000.0)
            return {"allowed": [True] * n}

        batcher = MicroBatcher(
            dispatch={"sw": dispatch}, clear={"sw": lambda slots: None},
            max_batch=max_batch, max_delay_ms=0.0, max_inflight=1,
            max_pending=max_pending, deadline_ms=deadline_ms)
        done_ms: dict = {}  # future -> completion latency (done callback,
        shed = deadline = admitted = 0  # so collection order can't inflate)
        per_burst = max(int(capacity_rps * burst_interval_ms / 1000.0
                            * mult), 1)
        pending: list = []

        def stamp(fut, born):
            fut.add_done_callback(
                lambda f: done_ms.setdefault(
                    f, (time.monotonic() - born) * 1000.0))
            return fut

        try:
            start = time.monotonic()
            for k in range(bursts):
                # Absolute schedule: a late burst fires immediately rather
                # than sliding every later burst (which would quietly lower
                # the offered rate on a loaded box).
                delay = start + k * burst_interval_ms / 1000.0 \
                    - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                born = time.monotonic()
                for i in range(per_burst):
                    try:
                        pending.append(stamp(
                            batcher.submit("sw", i % 32, 0, 1), born))
                    except OverloadedError as exc:
                        assert exc.retry_after_ms > 0, (
                            "shed without a Retry-After hint")
                        shed += 1
            lat_ms = []
            for fut in pending:
                try:
                    fut.result(timeout=10.0)
                    lat_ms.append(done_ms[fut])
                    admitted += 1
                except OverloadedError:
                    deadline += 1
            depth_seen = batcher.max_depth_seen
        finally:
            batcher.close()

        offered = shed + len(pending)
        p99 = (statistics.quantiles(lat_ms, n=100)[98]
               if len(lat_ms) >= 100 else max(lat_ms, default=0.0))
        run = {"multiplier": mult, "offered": offered, "admitted": admitted,
               "shed": shed, "deadline_expired": deadline,
               "goodput_frac": admitted / max(offered, 1),
               "shed_frac": (shed + deadline) / max(offered, 1),
               "max_depth_seen": depth_seen, "p99_ms": p99}
        report["runs"].append(run)

        assert depth_seen <= max_pending, (
            f"queue depth {depth_seen} exceeded the configured bound "
            f"{max_pending} at {mult}x load")
        assert admitted + shed + deadline == offered  # nothing stranded
        budget = deadline_ms + 2 * dispatch_ms + p99_slack_ms
        assert p99 <= budget, (
            f"p99 of admitted requests {p99:.1f} ms blew the "
            f"{budget:.1f} ms budget at {mult}x load")
        if mult >= 2.0:
            assert run["shed_frac"] > 0, (
                f"{mult}x offered load shed nothing — the queue bound "
                "is not engaging")
    return report
