"""Degraded-mode host-side limiter: fail-*approximate*, not fail-open.

When the circuit breaker (storage/breaker.py) is open — the device/storage
backend is persistently failing — decisions short-circuit here instead of
fail-opening blindly.  The approximation is a coarse in-memory restatement
of each registered limiter's policy (the oracle classes from
``semantics/oracle.py`` ARE the coarse host model: token bucket and
two-bucket sliding window, exact integer arithmetic, dict state), seeded
per key from the **last counter value the device reported** before the
outage (the breaker records those on the healthy path via
:meth:`note_seen`), so a key that was near its limit stays near its limit.

Over-admission is bounded: a key's degraded budget starts from its last
known remaining count (or full capacity if never seen), so the worst case
per key per window is one extra ``max_permits`` — the permits charged on
the device after the snapshot, which the host cannot see.  Compare
fail-open, whose over-admission is unbounded for the outage's duration.

On breaker close the keys *mutated* here are reset on the device (the
resync step in ``CircuitBreakerStorage``), so post-recovery decisions are
again bit-identical to ``semantics/oracle.py`` — a key either kept its
pre-outage device state untouched, or was reset on both sides.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Sequence, Tuple

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.semantics.oracle import (
    SlidingWindowOracle,
    TokenBucketOracle,
)
from ratelimiter_tpu.storage.errors import CircuitOpenError
from ratelimiter_tpu.utils.logging import get_logger

log = get_logger("storage.degraded")


def _wall_clock_ms() -> int:
    return time.time_ns() // 1_000_000


class DegradedHostLimiter:
    """Host-side approximate decisions for the breaker's open state.

    Thread-safe (one lock — this path only runs while the device path is
    down, so a host dict under a lock is plenty).  State is per open
    episode: ``clear_state()`` (called by the breaker after resync) drops
    every oracle so the next episode re-seeds from fresh snapshots.
    """

    def __init__(self, clock_ms: Callable[[], int] = _wall_clock_ms,
                 registry=None, max_keys: int = 65536, telemetry=None):
        self._clock_ms = clock_ms
        self._lock = threading.RLock()
        # Fleet telemetry plane (observability/telemetry.py): degraded
        # decisions are decisions too — without this feed, every outage
        # would read as a drop in fleet load instead of degraded serving.
        self._telemetry = telemetry
        self._configs: Dict[int, Tuple[str, RateLimitConfig]] = {}
        self._oracles: Dict[int, object] = {}
        # Last device-reported counter per (algo, lid, key): sw -> raw
        # current-bucket count, tb -> whole tokens remaining.  Bounded
        # LRU — refreshed continuously on the healthy path.
        self._seen: "collections.OrderedDict" = collections.OrderedDict()
        self._seeded: set = set()   # keys whose oracle state was seeded
        self._touched: set = set()  # keys MUTATED here (resync must reset)
        self.max_keys = int(max_keys)
        self._decisions = (
            registry.counter(
                "ratelimiter.degraded.decisions",
                "Decisions served by the degraded host limiter "
                "(breaker open)")
            if registry is not None else None)

    # -- policy registry ------------------------------------------------------
    def register(self, lid: int, algo: str, config: RateLimitConfig) -> None:
        with self._lock:
            self._configs[int(lid)] = (algo, config)

    def update_policy(self, lid: int, algo: str, config: RateLimitConfig,
                      generation: int = 0) -> None:
        """Live policy update (control/, ARCHITECTURE §15): adopt the
        new rates so an outage DURING or AFTER a policy change seeds
        its approximation from the generation that is actually serving.
        A live oracle (mid-episode update) reconfigures in place — its
        seeded per-key state stays, exactly like the device's counters
        across the same boundary."""
        with self._lock:
            self._configs[int(lid)] = (algo, config)
            oracle = self._oracles.get(int(lid))
            if oracle is not None:
                oracle.reconfigure(config)

    def _oracle(self, algo: str, lid: int):
        entry = self._configs.get(int(lid))
        if entry is None or entry[0] != algo:
            raise CircuitOpenError(
                f"degraded limiter has no policy for ({algo!r}, lid={lid})")
        oracle = self._oracles.get(int(lid))
        if oracle is None:
            cfg = entry[1]
            oracle = (SlidingWindowOracle(cfg) if algo == "sw"
                      else TokenBucketOracle(cfg))
            self._oracles[int(lid)] = oracle
        return oracle

    # -- snapshot feed (healthy path, via the breaker) ------------------------
    def note_seen(self, algo: str, lid: int, key: str, value: int,
                  now_ms: int) -> None:
        with self._lock:
            k = (algo, int(lid), key)
            self._seen[k] = (int(value), int(now_ms))
            self._seen.move_to_end(k)
            while len(self._seen) > self.max_keys:
                self._seen.popitem(last=False)

    def _seed(self, algo: str, lid: int, key: str, oracle) -> None:
        k = (algo, int(lid), key)
        if k in self._seeded:
            return
        self._seeded.add(k)
        snap = self._seen.get(k)
        if snap is None:
            return  # never seen: lazy init to full capacity (oracle default)
        value, ts = snap
        if algo == "sw":
            oracle.seed_count(key, value, ts)
        else:
            oracle.seed_tokens(key, value, ts)

    # -- decision surface (breaker-open short circuit) ------------------------
    def acquire(self, algo: str, lid: int, key: str, permits: int) -> dict:
        """One approximate decision, in the exact dict shape the device
        path returns (plus ``degraded: True`` so callers/drills can tell)."""
        with self._lock:
            oracle = self._oracle(algo, lid)
            self._seed(algo, lid, key, oracle)
            d = oracle.try_acquire(key, int(permits), self._clock_ms())
            if d.mutated:
                self._touched.add((algo, int(lid), key))
        if self._decisions is not None:
            self._decisions.increment()
        if self._telemetry is not None:
            self._telemetry.note_degraded(int(lid), bool(d.allowed))
        if algo == "sw":
            return {"allowed": d.allowed, "mutated": d.mutated,
                    "observed": d.observed, "cache_value": d.remaining_hint,
                    "degraded": True}
        return {"allowed": d.allowed, "observed": d.observed,
                "remaining": d.remaining_hint, "degraded": True}

    def available(self, algo: str, lid: int,
                  keys: Sequence[str]) -> List[int]:
        with self._lock:
            oracle = self._oracle(algo, lid)
            now = self._clock_ms()
            out = []
            for key in keys:
                self._seed(algo, lid, key, oracle)
                out.append(int(oracle.get_available_permits(key, now)))
            return out

    def reset(self, algo: str, lid: int, key: str) -> None:
        with self._lock:
            oracle = self._oracle(algo, lid)
            oracle.reset(key, self._clock_ms())
            # An admin reset during the outage must reach the device at
            # resync too, or the device's stale pre-outage counters win.
            self._touched.add((algo, int(lid), key))

    # -- episode lifecycle ----------------------------------------------------
    def touched(self) -> List[Tuple[str, int, str]]:
        """Keys whose state diverged from the device during this episode
        (mutated or reset here) — the breaker's resync set."""
        with self._lock:
            return sorted(self._touched)

    def clear_state(self) -> None:
        """End the episode: drop every oracle and seed/touch record.  The
        ``note_seen`` snapshot cache persists — it belongs to the healthy
        path and will be fresher by the next outage anyway."""
        with self._lock:
            self._oracles.clear()
            self._seeded.clear()
            self._touched.clear()
