"""TpuBatchedStorage — the TPU-resident storage backend.

The BASELINE.json north star realized: behind the ``RateLimitStorage``
plugin boundary, ``tryAcquire()`` calls are micro-batched on the host and
dispatched to a TPU-resident counter array, replacing the reference's
per-request Redis round-trip (~800 us each, ARCHITECTURE.md latency model)
with one device step per thousands of decisions.

Two protocols on one object:

1. The **batched decision protocol** (``register_limiter`` / ``acquire`` /
   ``acquire_many`` / ``available_many`` / ``reset_key``): the hot path.
   Algorithm classes detect ``supports_device_batching`` and route whole
   decisions here; the sliding-window estimate and token-bucket refill run
   as device kernels (ops/sliding_window.py, ops/token_bucket.py) with
   decisions bit-identical to ``semantics/oracle.py``.

2. The **legacy 10-method contract** (storage/RateLimitStorage.java:10-70):
   fully implemented for interface parity.  Generic counters/zsets/ad-hoc
   scripts execute host-side against an embedded ``InMemoryStorage`` (the
   exact same decision math — the device path exists for *registered*
   limiters, just as Redis Lua scripts exist for deployed workloads).

Key -> slot assignment and eviction live in ``SlotIndex``; cleared slots are
zeroed in the dispatch stream ahead of their reuse.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Sequence, Tuple

import numpy as np

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.engine.batcher import MicroBatcher
from ratelimiter_tpu.engine.errors import (
    OverloadedError,
    consume_pending_clears,
)
from ratelimiter_tpu.engine.engine import DeviceEngine
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.storage.memory import InMemoryStorage
from ratelimiter_tpu.utils.logging import get_logger

log = get_logger("storage.tpu")


# Per-dispatch lane cap for the SORTED flat step (ops/flat.py): its
# sort/associative-scan ops compile super-linearly on XLA:TPU
# (bench/profile_compile.py), so dispatches are cut to this size and
# pipelined instead.  The unit-permit relay step (ops/relay.py) has no
# sort/scan and takes no cap.
_FLAT_MAX_LANES = 1 << 19

# Relay-path chunking: the first chunk probes the stream's duplicate
# structure at the floor size; later chunks size themselves to a
# per-dispatch wire budget at the measured bytes/request of their mode.
# Digest chunks grow until the whole pass is a couple of dispatches
# (dedup improves superlinearly with chunk size).  Per-request-words
# chunks use the same 16 MB budget: fewer dispatches = fewer ~100 ms
# round trips, and large transfers measured as fast per byte as 4 MB
# ones in r3 (the r2 "4 MB sweet spot" did not reproduce; scenario 3
# runs ~15% faster at 16 MB).
_RELAY_CHUNK = 1 << 19
# Chunks grow to 16M: Zipf dedup improves superlinearly with chunk size
# (u/cn drops), so two giant digest chunks beat five pipelined 4M ones
# even though the pipeline overlap is worse — measured both ways on the
# dev tunnel (ROUND_NOTES.md r3).
_RELAY_CHUNK_MAX = 1 << 24
_RELAY_WIRE_BUDGET_DIGEST = 16 << 20
_RELAY_WIRE_BUDGET_WORDS = 16 << 20

# Slot-sort threshold for digest dispatches: at or above this many
# uniques the C index re-sorts the chunk's uniques by slot (O(u) radix +
# O(n) uidx remap, ~2-4 ms on a 1M-unique chunk) so the device scatter
# runs as the dense presorted block sweep instead of XLA's ~45 ns/index
# generic scatter (measured 3.5x cheaper at 512K rows — ROUND_NOTES r4).
_SORT_UNIQUES_MIN = 1 << 12

# Mode-election amortization for the resident-lid delta upload: a (slot,
# lid) pair is paid ONCE and then serves every later digest chunk that
# touches the slot, so the election charges it at 1/4 — without this a
# churn-heavy pass (every lid fresh) elects words mode, words mode never
# uploads lids, and the stream is stuck paying 8.125 B/request forever
# instead of reaching the ~6 B/unique resident steady state.
_DELTA_AMORT = 4

# Weighted relay wire budget: the rank-major layout has no sort/scan
# compile ceiling and ~1.5-4 B/request wire cost, so chunks amortize
# best when the whole pass is a handful of dispatches.
_RELAY_WIRE_BUDGET_WEIGHTED = 48 << 20

# Link-adaptive pipelining (VERDICT r3 #1, reworked r5).  The dev
# tunnel's execution model, measured (bench/profile_stream_r5.py +
# ROUND_NOTES r5): dispatch enqueue is async and uploads of QUEUED
# dispatches stream back-to-back, but every result fetch is its own
# ~RTT round trip — and concurrent fetches from separate threads
# overlap (3 chunk cycles: 688 ms fetched serially, 295 ms fetched
# concurrently).  So the loop drains every dispatch CONCURRENTLY on a
# small pool (the fetch wait sleeps — it does not spin — so the C walk
# keeps the core), and a pipelined plan is a descending SCHEDULE of
# chunk sizes: a small head chunk gets the link flowing early, big
# middle chunks keep dedup strong, and a small tail chunk shrinks the
# only fetch cycle nothing can hide (the last one).  Chunk sizes stay
# pow2-aligned where the dispatch pads to pow2 (words mode pads the
# request lane; digest pads the unique lane) so schedule chunks don't
# ship padding.  _elect_chunk_plan ranks candidate schedules with a
# small discrete-event simulation fed by the giant pass's measured
# walk/host rates and dedup curve; a schedule that measures clearly
# worse than the giant pass it replaced (> _PIPELINE_REVERT x)
# reverts — sticky both ways, so chunk shapes stay deterministic
# across timed passes (ROUND_NOTES r3).
_PIPELINE_WIN_MARGIN = 0.97
_PIPELINE_REVERT = 1.1
# Per-dispatch transfers move at a fraction of the bulk device_put
# rate the link probe measures (2.6 MB moved in ~85 ms against a
# 77 MB/s bulk probe — protocol overhead per dispatch cycle).  The
# simulator derates the probed rate by this; ranking is insensitive
# to the exact value.
_DISPATCH_RATE_DERATE = 0.55
# Concurrent in-flight drains: enough to overlap every mid-schedule
# fetch cycle, small enough to bound queued result buffers.
_DRAIN_WORKERS = 4
_DRAIN_INFLIGHT = 4
# Per-shard stream pipelining (r8): how many chunks the routing pass may
# run ahead of the oldest still-assembling chunk.  Each lane additionally
# bounds its own drain queue (see _ShardLane), so total staging memory is
# O(lookahead + drain bound) chunks.
_SHARD_LOOKAHEAD = 2
# Undrained dispatches a single shard lane may hold before its submit
# blocks (and flags shard.drain_saturated to the flight recorder).
_SHARD_DRAIN_INFLIGHT = 2
# Device step cost per dispatched lane (words/weighted: per request;
# digest: per unique, sorted vs unsorted scatter).  The elections
# charge these explicitly; since r5 they are PROBED at runtime per
# (platform, device kind) and disk-cached (engine/device_rates.py,
# VERDICT r4 #5) — these module constants are only the v5e-measured
# fallback for profile-less paths and failed probes.
from ratelimiter_tpu.engine.device_rates import FALLBACK_RATES as _FB_RATES

_DEVICE_S_PER_LANE = _FB_RATES["s_per_lane"]
_DEVICE_S_PER_UNIQUE_SORTED = _FB_RATES["s_per_unique_sorted"]
_DEVICE_S_PER_UNIQUE_UNSORTED = _FB_RATES["s_per_unique_unsorted"]

# Split-digest host partition cost per unique
# (engine/native_index.py:split_layout — C path measured ~19 ns/u
# all-in at 3M uniques, output allocation included; numpy fallback
# ~46 ns/u); the split election charges it against the wire it saves.
_SPLIT_HOST_S_PER_UNIQUE = 15e-9

# Auto-elected host-parallel partitioned index (VERDICT r5 next-round
# #2): the C slot walk is DRAM-latency-bound and was the headline
# bench's largest single CPU term, while the partitioned index built to
# split it sat unused outside its own tests.  Storage construction now
# elects host_parallel = min(cores, 8) by itself when the native index
# is available, the engine is single-device, the host has more than two
# cores, and the table is large enough that streaming walks dominate
# (small tables keep the single-LRU index: interactive/test workloads
# are not walk-bound, and per-partition LRU slightly changes eviction
# order — not a trade worth making for a 4K-slot table).  An explicit
# ``host_parallel=`` kwarg always wins (0 disables).
_HOST_PARALLEL_AUTO_MIN_SLOTS = 1 << 16
_HOST_PARALLEL_AUTO_MAX = 8

# Weighted relay: longest rank-major permit matrix the scan step accepts.
# A chunk whose deepest segment exceeds this (heavy duplication — Zipf
# bursts) dispatches through the sorted flat step instead; duplicate-poor
# weighted traffic (the burst batch-acquire scenario) stays on the relay.
_WREL_MAX_R = 64

# Zipf key coalescing: chunks whose repeated keys carry segment-uniform
# permits dispatch ONE weighted decision per unique key
# (ops/relay.py:*_relay_weighted_counts) and reconstruct per-request
# booleans host-side, so device work and wire bytes scale with uniques
# instead of requests.  Opt-out knob for A/B runs (bench/coalesce_smoke.py).
_COALESCE = os.environ.get("RATELIMITER_COALESCE", "1") != "0"


def _bucket_pow2(n: int) -> int:
    from ratelimiter_tpu.parallel.sharded import _bucket

    return _bucket(n, floor=4096)


def _bucket_fine(n: int, floor: int = 4096) -> int:
    """Quarter-octave bucketing: next multiple of octave/4 (for n in
    (2^(L-1), 2^L] the step is 2^(L-3)) — 4 compile shapes per octave
    instead of 1.  Worst-case padding ~25% just above a power of two,
    ~12% at the octave top, vs ~100% for plain pow2 rounding (used where
    a lane's bytes dominate the wire)."""
    if n <= floor:
        return floor
    step = 1 << (int(n - 1).bit_length() - 3)
    return -(-n // step) * step


# Injectable per-process clock offset (chaos conductor, ARCHITECTURE
# §17): every default now-source in this process reads wall time PLUS
# this skew, so cross-cell clock skew and step jumps are testable
# against a real clock instead of dodged with order-only policies.
# Seeded from RATELIMITER_CLOCK_SKEW_MS so a spawned hostproc/edgeproc
# can boot skewed; mutable at runtime via set_clock_skew_ms (a control
# op or an in-process actor).  Storages built with an explicit
# ``clock_ms=`` are unaffected — their clock is the caller's problem.
_CLOCK_SKEW_MS: int = int(os.environ.get("RATELIMITER_CLOCK_SKEW_MS",
                                         "0") or "0")


def set_clock_skew_ms(skew_ms: int) -> int:
    """Set this process's injected clock offset (ms, may be negative);
    returns the previous value.  Takes effect on the next clock read —
    a forward step is a "jump", a standing offset is "skew"."""
    global _CLOCK_SKEW_MS
    prev = _CLOCK_SKEW_MS
    _CLOCK_SKEW_MS = int(skew_ms)
    return prev


def clock_skew_ms() -> int:
    return _CLOCK_SKEW_MS


def _wall_clock_ms() -> int:
    return time.time_ns() // 1_000_000 + _CLOCK_SKEW_MS


def _elect_digest_mode(link_profile, u: int, cn: int, n_delta: int,
                       digest_bpu: float, words_bpr: float,
                       srt_ok: bool, cdt_size: int = 1,
                       rates: dict | None = None) -> bool:
    """Words-vs-digest election for one chunk.  With a link profile the
    comparison is TOTAL per-side seconds — wire charged PER DIRECTION
    (digest uploads 4 B/unique but downloads a cdt_size count per
    unique, words uploads 4 B/request but downloads 1 BIT per request;
    on a download-degraded tunnel that asymmetry decides high-u/n
    chunks — r5) plus device seconds (the digest rate depending on
    whether the slot-sorted sweep engages).  Without a profile it falls
    back to the blended wire-byte constants.  cdt presence is the
    caller's gate."""
    if link_profile is not None:
        up = max(link_profile[0], 1.0)
        down = max(link_profile[2], 1.0) if len(link_profile) > 2 else up
        if rates is None:
            rates = _FB_RATES
        dev_u = rates["s_per_unique_sorted" if srt_ok
                      else "s_per_unique_unsorted"]
        # digest_bpu/words_bpr carry the blended per-lane bytes (incl.
        # the multi-tenant lid lane when not resident); split out the
        # known download component and charge it at the download rate.
        dig_cost = (u * ((digest_bpu - cdt_size) / up + cdt_size / down
                         + dev_u)
                    + (8 * n_delta / _DELTA_AMORT) / up)
        words_cost = cn * ((words_bpr - 0.125) / up + 0.125 / down
                           + rates["s_per_lane"])
        return dig_cost <= words_cost
    return digest_bpu * u + 8 * n_delta / _DELTA_AMORT <= words_bpr * cn


# Host-side cost of the slot re-sort a sorted-digest dispatch needs
# (native rl_sort_uniques; ~48 ns/unique measured at 2.7M uniques on
# the bench host, r5).  The sort buys DEVICE time (52 -> 25 ns/unique,
# ROUND_NOTES r4) — worth real host CPU only where the device is on
# the critical path or host CPU is idle anyway.
_SORT_HOST_S_PER_UNIQUE = 50e-9


def _sort_affordable(link_profile, u: int) -> bool:
    """Whether to spend host CPU slot-sorting a digest chunk's uniques.

    ``RATELIMITER_SORT_UNIQUES=always|never|auto`` (default auto, read
    per call so tests and config reloads take effect immediately): on
    a multi-core host the sort overlaps other cores' work, and with no
    link profile the device is assumed local-attached (device time is
    the scarce resource) — sort.  On a single-core host with a
    profiled link, the chunk's upload seconds (4 B/unique / rate) must
    comfortably exceed the sort's host seconds (~50 ns/unique) — both
    sides scale with u, so this reduces to a ~40 MB/s link threshold:
    below it the pass is wire-bound and the host idles through the
    sort anyway; above it the pass is CPU-bound and the device pays
    the unsorted scatter instead — that time rides under the link wait
    (r5: scenario 3 spent 0.9 s/pass sorting to save device time that
    was never on the critical path)."""
    import os

    policy = os.environ.get("RATELIMITER_SORT_UNIQUES", "auto")
    if policy == "always":
        return True
    if policy == "never":
        return False
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # pragma: no cover - non-linux
        cores = os.cpu_count() or 1
    if cores > 2 or link_profile is None:
        return True
    rate = max(link_profile[0], 1.0)
    return 4.0 / rate > 2.0 * _SORT_HOST_S_PER_UNIQUE


class _DrainSet:
    """In-flight drain tracker: every dispatched chunk's drain is
    submitted to the storage's drain pool IMMEDIATELY, so the ~RTT-sized
    fetch cycles of consecutive chunks overlap instead of serializing
    (measured on the dev tunnel: 3 chunk cycles fetched serially
    688 ms, concurrently 295 ms — the fetch wait sleeps, it does not
    spin, so the C walk keeps the core).  ``finish()`` blocks until
    every drain has landed and re-raises the first drain error;
    ``finish(swallow=True)`` is for paths already propagating a primary
    exception (drain errors are then secondary)."""

    __slots__ = ("_pool", "_futs", "_inflight", "_on_block")

    def __init__(self, pool, inflight: int = _DRAIN_INFLIGHT,
                 on_block=None):
        self._pool = pool
        self._futs: list = []
        self._inflight = inflight
        # Saturation hook (r8): called once each time submit must wait
        # out an old drain — the per-shard lanes feed it to the flight
        # recorder so a drain-bound shard is diagnosable.
        self._on_block = on_block

    def submit(self, fn, *args) -> None:
        self._futs.append(self._pool.submit(fn, *args))
        # Backpressure: bound queued result buffers (and tunnel credit)
        # by waiting out the oldest live drain past the cap.
        live = [f for f in self._futs if not f.done()]
        if len(live) > self._inflight:
            if self._on_block is not None:
                self._on_block()
            live[0].result()

    def finish(self, swallow: bool = False) -> None:
        err = None
        for f in self._futs:
            try:
                f.result()
            except Exception as exc:  # noqa: BLE001 — re-raised below
                if err is None:
                    err = exc
        self._futs.clear()
        if err is not None and not swallow:
            raise err


class _StagingPool:
    """Reusable host staging buffers for dispatch uploads (r6).

    Streaming chunks used to allocate AND memset a fresh padded numpy
    buffer per dispatch (``np.full`` of up to 64 MB — real milliseconds
    of page faults + fill per chunk on the 1-core bench host).  The pool
    recycles them: ``take`` returns a C-contiguous array of the exact
    requested shape with UNSPECIFIED contents — the caller overwrites
    its valid region and re-fills only its own padding; ``give``
    returns a buffer once the dispatch that consumed it has been
    DRAINED (results fetched => the upload was consumed; handing it
    back earlier could race the async host->device transfer).  Shapes
    recur because every dispatch lane count is bucketed.  Bounded by
    retained bytes; a miss just allocates."""

    __slots__ = ("_free", "_lock", "_bytes", "_max_bytes")

    def __init__(self, max_bytes: int = 256 << 20):
        self._free: Dict[tuple, list] = {}
        self._lock = threading.Lock()
        self._bytes = 0
        self._max_bytes = int(max_bytes)

    def take(self, shape, dtype) -> np.ndarray:
        shape = (shape,) if isinstance(shape, int) else tuple(shape)
        key = (shape, np.dtype(dtype).str)
        with self._lock:
            lst = self._free.get(key)
            if lst:
                arr = lst.pop()
                self._bytes -= arr.nbytes
                return arr
        return np.empty(shape, dtype=dtype)

    def give(self, arr) -> None:
        if arr is None:
            return
        key = (arr.shape, arr.dtype.str)
        with self._lock:
            if self._bytes + arr.nbytes > self._max_bytes:
                return  # over budget: let the GC have it
            self._free.setdefault(key, []).append(arr)
            self._bytes += arr.nbytes


class _ShardLane:
    """One shard's fully independent dispatch pipeline (r8).

    The pre-r8 sharded stream prepared ALL shards' host work on one
    worker and barriered them into a single mesh-wide dispatch per
    chunk — every shard waited for the slowest sibling's layout, the
    multi-device launch rendezvoused all devices, and the request lane
    padded to the BUSIEST shard's bucket.  A lane decomposes that: it
    owns

    - ``pipe``  — one FIFO worker running assign -> eviction-clear ->
      layout -> per-shard dispatch.  FIFO == per-shard stream order, so
      a shard's clears always enter its device stream ahead of the
      dispatch that reuses the slots, with NO cross-shard barrier (a
      key never migrates shards, so nothing else needs one);
    - ``staging`` — the shard's own staging-buffer pool (per-shard
      upload shapes recur per lane, and sibling lanes never contend on
      its lock);
    - ``drains`` — the shard's own bounded drain queue on its own
      fetch worker; past the in-flight bound, submit blocks THIS lane
      only and flags saturation to the flight recorder.

    Chunk N+1 of shard A assembles while chunk N of shard B is still in
    flight — the inversion fix for BENCH_r05's sharded_scaling curve.
    """

    __slots__ = ("shard", "pipe", "drain_pool", "staging", "drains",
                 "saturated")

    def __init__(self, shard: int, recorder=None, inflight: int | None = None):
        import concurrent.futures as cf

        if inflight is None:
            inflight = _SHARD_DRAIN_INFLIGHT

        self.shard = shard
        self.pipe = cf.ThreadPoolExecutor(
            1, thread_name_prefix=f"shard{shard}-pipe")
        self.drain_pool = cf.ThreadPoolExecutor(
            1, thread_name_prefix=f"shard{shard}-drain")
        self.staging = _StagingPool(max_bytes=64 << 20)
        self.saturated = 0

        def on_block():
            self.saturated += 1
            if recorder is not None:
                recorder.record("shard.drain_saturated",
                                coalesce_ms=1000.0, shard=self.shard)

        self.drains = _DrainSet(self.drain_pool, inflight=inflight,
                                on_block=on_block)

    def close(self) -> None:
        self.pipe.shutdown(wait=False)
        self.drain_pool.shutdown(wait=False)


class _ChunkCursor:
    """Chunk sizing shared by the relay and weighted streaming loops:
    either walks a plan's fixed SCHEDULE (the last entry sizes any
    overflow when a longer stream reuses a banded plan) or runs the
    mutable growth chunk.  ``next_size`` consumes an entry; ``peek``
    sizes the prefetch for the following chunk without consuming."""

    __slots__ = ("sched", "chunk", "ci")

    def __init__(self, plan, pipelined: bool):
        self.sched = plan.get("schedule") if pipelined else None
        self.chunk = (plan["chunk"] if pipelined and not self.sched
                      else _RELAY_CHUNK)
        self.ci = 0

    def _cur(self) -> int:
        if self.sched:
            return (self.sched[self.ci] if self.ci < len(self.sched)
                    else self.sched[-1])
        return self.chunk

    def next_size(self, remaining: int) -> int:
        c = min(self._cur(), remaining)
        if self.sched:
            self.ci += 1
        return c

    def peek(self, remaining: int) -> int:
        return min(self._cur(), remaining)

    def grow(self, chunk: int) -> None:
        self.chunk = chunk


def _schedule_candidates(n: int, head: int, words_pow2: bool) -> list:
    """Candidate chunk schedules for a pipelined stream pass.

    Shape: small HEAD chunk (the link starts moving after one cheap
    walk), big MIDDLE chunks (dedup and per-dispatch overhead
    amortize), small descending TAIL (the last fetch cycle is the only
    one nothing can hide — make it cheap).  All sizes are pow2 when
    ``words_pow2`` (the words dispatch pads its request lane to pow2 —
    a non-pow2 chunk would ship up to 2x padding); digest chunks pad
    the UNIQUE lane instead, so their sizes are free-form."""
    floor = _RELAY_CHUNK
    if n < 4 * floor:
        return []
    cands = []
    # pow2 halving cascade: [head, biggest pow2 <= rest, halving...].
    # Chunks respect the growth path's _RELAY_CHUNK_MAX lane ceiling,
    # and a sub-floor remainder folds into its predecessor: the last
    # entry also SIZES every overflow chunk when a longer stream in the
    # same banded plan reuses this schedule — a tiny tail entry would
    # make that overflow drain RTT-sized crumbs.
    sizes = [head]
    rem = n - head
    while rem >= floor:
        c = 1 << (int(rem).bit_length() - 1)
        c = min(max(min(c, rem), floor), _RELAY_CHUNK_MAX)
        sizes.append(int(c))
        rem -= c
    if rem > 0:
        _fold_tail(sizes, int(rem))
    cands.append(sizes)
    if not words_pow2:
        # two-big + tail: maximum dedup, still a cheap exposed tail.
        tail = max(floor, n // 16)
        mid = n - head - 2 * tail
        if mid > 2 * floor:
            half = (mid + 1) // 2
            if half <= _RELAY_CHUNK_MAX:
                cands.append([head, half, mid - half, tail, tail])
        big = n - head - tail
        if floor < big <= _RELAY_CHUNK_MAX:
            cands.append([head, big, tail])
    else:
        # equal-pow2 middle: 2M-request chunks (the r4 words plans).
        c = 4 * floor
        sizes2 = [head]
        rem = n - head
        while rem >= c:
            sizes2.append(c)
            rem -= c
        if rem > 0:
            _fold_tail(sizes2, int(rem))
        if len(sizes2) <= 40:
            cands.append(sizes2)
    return cands


def _fold_tail(sizes: list, rem: int) -> None:
    """Fold a sub-floor remainder into a schedule's last chunk — the
    last entry also sizes every OVERFLOW chunk when a longer stream in
    the same banded plan reuses the schedule, so it must never be an
    RTT-sized crumb.  If the fold would push the chunk past the
    _RELAY_CHUNK_MAX lane ceiling, split the total in half instead
    (both halves >= the fold target > floor)."""
    total = sizes[-1] + rem
    if total <= _RELAY_CHUNK_MAX:
        sizes[-1] = total
    else:
        sizes[-1] = total // 2
        sizes.append(total - total // 2)


def _sim_schedule_wall(sizes, *, cpu_per_req: float, digest_frac: float,
                       dedup_a: float, dedup_alpha: float, bpu_up: float,
                       bpu_down: float, words_up: float, link_up: float,
                       link_down: float, rtt: float,
                       dev_per_lane: float) -> float:
    """Predicted wall for one schedule under the measured tunnel model:
    CPU (walk + host prep) strictly serializes on one timeline, link
    BYTES serialize on another (uploads of queued dispatches stream
    back-to-back; concurrent drains overlap their RTTs), each chunk's
    fetch completes one RTT after its step's wire has cleared.  Used to
    RANK candidate schedules — absolute accuracy is not required, the
    revert check (measured walls) is the safety net."""
    t_cpu = 0.0
    link_free = 0.0
    done = 0.0
    for c in sizes:
        t_cpu += c * cpu_per_req
        if digest_frac > 0.5:
            u = min(c, dedup_a * (c ** dedup_alpha))
            lanes = _bucket_pow2(max(int(u), 1))
            up_b, down_b = bpu_up * lanes, bpu_down * lanes
        else:
            lanes = _bucket_pow2(int(c))
            up_b, down_b = words_up * lanes, c / 8.0
        start = max(t_cpu, link_free)
        link_free = start + up_b / link_up + down_b / link_down
        done = max(done, link_free + lanes * dev_per_lane + rtt)
    return done


def _presorted_scatter_usable(eng, algo: str, padded: int) -> bool:
    """Whether a digest dispatch at this padded lane count can use the
    dense presorted block sweep (module-level so tests can force the
    sorted path onto the XLA fallback)."""
    from ratelimiter_tpu.ops.pallas import block_scatter

    shape = (eng.sw_packed if algo == "sw" else eng.tb_packed).shape
    return block_scatter.enabled(shape, padded)


def _route_chunk(key_ids: np.ndarray, n_shards: int):
    """(shard, stable order, per-shard counts) — C fast path with a
    bit-identical numpy fallback."""
    from ratelimiter_tpu.engine.native_index import shard_route

    r = shard_route(key_ids, n_shards)
    if r is not None:
        return r
    from ratelimiter_tpu.parallel.sharded import shard_of_int_keys

    shard = shard_of_int_keys(key_ids, n_shards)
    order = np.argsort(shard, kind="stable")
    return shard, order, np.bincount(shard, minlength=n_shards)


def _pad_tail(arr: np.ndarray, size: int, fill, dtype) -> np.ndarray:
    """Contiguous cast + right-pad with ``fill`` up to ``size``."""
    arr = np.ascontiguousarray(arr, dtype=dtype)
    if len(arr) < size:
        arr = np.concatenate(
            [arr, np.full(size - len(arr), fill, dtype=dtype)])
    return arr


class TpuBatchedStorage(RateLimitStorage):
    supports_device_batching = True

    def __init__(
        self,
        num_slots: int = 1 << 20,
        max_batch: int = 8192,
        max_delay_ms: float = 0.5,
        max_inflight: int = 4,
        max_pending: int = 0,
        queue_deadline_ms: float = 0.0,
        clock_ms: Callable[[], int] = _wall_clock_ms,
        engine: DeviceEngine | None = None,
        table: LimiterTable | None = None,
        checkpointable: bool = False,
        meter_registry=None,
        host_parallel: int | None = None,
        trace_sample: int = 0,
        obs_slo_ms: float = 0.0,
        observability: bool = True,
        recorder=None,
        adaptive_flush: bool = True,
        flush_floor_ms: float = 0.05,
        serving_cache: bool = False,
        serving_cache_ttl_ms: float = 50.0,
        serving_cache_max_keys: int = 65536,
        serving_cache_unconfirmed_cap: int = 64,
        serving_cache_guard_ms: float = 5.0,
        usage_max_tenants: int = 256,
        telemetry_max_clients: int = 1024,
        lineage_capacity: int = 256,
        table_capacity: int = 0,
    ):
        self._clock_ms = clock_ms
        # Observability (ARCHITECTURE §13).  The stage/latency histograms
        # are UNCONDITIONAL: a storage built without a registry gets a
        # private one (log2-bucket timers are O(1) lock-free records, so
        # always-on is affordable — gated <=2% of the headline stream by
        # bench/observability_overhead.py).  ``observability=False`` is
        # the explicit opt-out that the overhead bench measures against.
        self._obs = bool(observability)
        if meter_registry is None and self._obs:
            from ratelimiter_tpu.metrics import MeterRegistry

            meter_registry = MeterRegistry()
        self.registry = meter_registry
        if self._obs:
            from ratelimiter_tpu.observability import flight_recorder

            self._recorder = (recorder if recorder is not None
                              else flight_recorder())
            if obs_slo_ms and obs_slo_ms > 0:
                self._recorder.set_slo_ms(obs_slo_ms)
        else:
            self._recorder = None
        # The storage-latency histogram the reference documents but never
        # ships (ARCHITECTURE notes; SURVEY §5.5): per-dispatch wall time.
        self._latency = (
            meter_registry.timer(
                "ratelimiter.storage.latency",
                "Device dispatch latency (per micro-batch)")
            if self._obs else None
        )
        # Per-stage pipeline timers (r6, unconditional since the
        # observability PR): where a stream chunk's seconds go — pack
        # (string hashing), index (slot walk), layout (host dispatch
        # prep), enqueue (device dispatch call), fetch (the blocking
        # result read).
        self._stage_timers = None
        if self._obs:
            self._stage_timers = {
                s: meter_registry.timer(
                    f"ratelimiter.stream.{s}",
                    f"Stream pipeline {s} stage (us per chunk)")
                for s in ("route", "pack", "index", "layout", "enqueue",
                          "fetch")}
        # Reusable dispatch staging buffers shared by every stream loop.
        self._staging = _StagingPool()
        if engine is not None and table is None:
            table = engine.table
        # table_capacity pre-sizes the policy table (ratelimiter.table.
        # capacity): an implicit mid-traffic grow is decision-safe but
        # recompiles the step for the new table shape — see
        # LimiterTable._grow.
        self.table = table if table is not None else LimiterTable(
            capacity=table_capacity if table_capacity > 0 else 64)
        self.engine = engine if engine is not None else DeviceEngine(num_slots, self.table)
        if host_parallel is None:  # auto-elect (explicit kwarg wins; 0 off)
            host_parallel = self._auto_host_parallel(checkpointable)
        self._host_parallel = (int(host_parallel)
                               if host_parallel and host_parallel > 1 else 0)
        self._configs: Dict[int, Tuple[str, RateLimitConfig]] = {}
        # Policy-update listeners (control plane, ARCHITECTURE §15):
        # parties holding a policy-derived mirror — the degraded host
        # limiter's oracles, the lease manager's clamps — subscribe here
        # and are told (lid, algo, config, generation) AFTER the device
        # row moved.  The hybrid serving cache is handled inline (its
        # invalidation must precede the row write, like reset_key).
        self._policy_listeners: List[Callable] = []
        # Standby-promotion window flag: decisions are refused (typed,
        # retryable) while promote_from_replica swaps the indexes.
        self._promoting = False
        # Fencing (replication/orchestrator.py): a monotonically-bumped
        # epoch installed by failover before a replacement starts serving.
        # _fence_all refuses every decision; _fenced_shards scopes the
        # fence to the named shards of a sharded engine (survivor traffic
        # keeps flowing).  Both cost one falsy check on the hot path
        # until a fence is actually installed.
        self._fence_epoch = 0
        self._fence_all = False
        self._fenced_shards: frozenset = frozenset()
        self.fence_rejected = 0
        # Scoped fence epochs (ARCHITECTURE §14b): token-lease revocation
        # is keyed off lease_scope_epoch(lid, key), not the global fence
        # epoch, so a single-shard promotion revokes only the leases whose
        # keys route to the promoted shard.  _shard_fence_epochs is a
        # per-shard ratchet (never cleared by lift_fence — revoking a
        # lease is always safe; resurrecting one never is);
        # _full_fence_epoch moves only on whole-storage fences.
        self._shard_fence_epochs: Dict[int, int] = {}
        self._full_fence_epoch = 0
        # Distributed fence lease (cross-host failover, ARCHITECTURE
        # §10c): the orchestrator grants this storage the right to serve
        # at a fence epoch for a bounded TTL and renews it while probes
        # answer.  A storage whose lease EXPIRES — partitioned from its
        # orchestrator and from the standby-relayed renewal path — SELF-
        # FENCES: it stops deciding within one TTL of the last renewal,
        # which is what bounds a partitioned zombie's over-admission
        # without any quorum machinery.  _lease_deadline_ms == 0 means no
        # lease is installed; the hot-path cost is then one falsy check.
        self._lease_epoch = 0
        self._lease_deadline_ms = 0
        self.lease_self_fenced = False
        # The engine decides the index shape: flat LRU for single device,
        # per-shard LRU (key pinned to shard by hash) for a sharded engine.
        # The native index checkpoints at fingerprint level by default;
        # checkpointable=True swaps in enumerable KEYED Python indexes —
        # needed only for dumps that must re-hash keys in a different
        # geometry (cross-shard rebalance; engine/checkpoint.py).
        def make_index():
            # host_parallel=T partitions the host index over T native
            # sub-indexes with per-partition LRU (the trade the
            # device-sharded index already makes) so batch assignment
            # scales across cores instead of serializing on one DRAM
            # probe stream.  Single-device engines only; checkpointable
            # deployments keep the enumerable Python index.
            if host_parallel > 1:
                if checkpointable:
                    raise ValueError(
                        "host_parallel requires fingerprint checkpoints; "
                        "it cannot combine with checkpointable=True "
                        "(which needs the keyed Python index)")
                if hasattr(self.engine, "n_shards"):
                    raise ValueError(
                        "host_parallel applies to single-device engines; "
                        "the sharded engine already partitions the host "
                        "index per device shard")
                if self.engine.num_slots % host_parallel:
                    raise ValueError(
                        f"num_slots ({self.engine.num_slots}) must divide "
                        f"evenly by host_parallel ({host_parallel})")
                from ratelimiter_tpu.engine.native_index import (
                    native_available,
                )

                if native_available():
                    from ratelimiter_tpu.engine.partitioned import (
                        PartitionedSlotIndex,
                    )

                    return PartitionedSlotIndex(self.engine.num_slots,
                                                host_parallel)
                raise RuntimeError(
                    "host_parallel requires the native slot index "
                    "(C++ build unavailable)")
            index = self.engine.make_slot_index()
            if not checkpointable:
                return index
            if hasattr(index, "_sub"):
                if not all(hasattr(s, "_map") for s in index._sub):
                    # Native sub-indexes are fingerprint-only; checkpoints
                    # need the enumerable Python subs.
                    index = type(index)(index.slots_per_shard,
                                        index.n_shards, native=False)
                return index
            if not hasattr(index, "_map"):
                from ratelimiter_tpu.engine.slots import SlotIndex

                index = SlotIndex(self.engine.num_slots)
            return index

        self._index = {"sw": make_index(), "tb": make_index()}
        # Host mirror of which slots' lids the device lid map knows
        # (per algo, allocated on first digest-multi stream).
        self._lid_known: Dict[str, np.ndarray] = {}
        # Per-algo locks serializing _lid_known reads/marks + their
        # dispatch against _clear_slots (clear-wins: an eviction
        # concurrent with a mark must leave known=False so the lid is
        # re-uploaded).  Per algo so sw and tb clears never serialize
        # against each other.
        self._lid_locks = {"sw": threading.Lock(), "tb": threading.Lock()}
        self._host = InMemoryStorage(clock_ms=clock_ms)  # legacy-contract ops
        from ratelimiter_tpu.utils.tracing import DecisionTrace

        self.trace = DecisionTrace()
        # Fleet telemetry plane (observability/telemetry.py): fleet-true
        # ratelimiter.decisions.* counters + the per-tenant usage ring
        # (fed from micro drains, stream chunks, sheds, degraded-path
        # decisions, and client telemetry reports), and the trace-id
        # lineage ring sampled ids accumulate hops in.  Both are part of
        # the always-on observability layer (None with it off).
        self.telemetry = None
        self.lineage = None
        if self._obs:
            from ratelimiter_tpu.observability import (
                TelemetryPlane,
                TraceLineage,
            )

            self.telemetry = TelemetryPlane(
                meter_registry, clock_ms=clock_ms,
                max_clients=telemetry_max_clients)
            self.telemetry.usage.max_tenants = max(int(usage_max_tenants),
                                                   1)
            self.lineage = TraceLineage(capacity=lineage_capacity,
                                        sample_n=int(trace_sample))
        # Request-lifecycle tracer (observability/trace.py): the batcher
        # stamps enqueue/assembly/device/resolve and this aggregates them
        # into the ratelimiter.latency.* histograms, sampling 1-in-N full
        # traces into the enriched DecisionTrace ring.
        self._tracer = None
        if self._obs:
            from ratelimiter_tpu.observability import LatencyTracer

            self._tracer = LatencyTracer(
                meter_registry, trace=self.trace,
                sample_n=int(trace_sample), recorder=self._recorder,
                lineage=self.lineage)
        # Optional stream instrumentation (VERDICT r2 #1): when a caller
        # sets this to a list, the streaming loops append one record per
        # chunk — {mode, n, u, wire_bytes, assign_s, host_s, fetch_s} — so
        # a bench can show WHERE the seconds of a pass went (e.g. a
        # multi-second fetch_s on one chunk = a mid-timing compile).
        self.stream_stats: list | None = None
        # Link profile (upload bytes/s, round-trip s) + per-stream-shape
        # chunk plans (VERDICT r3 #1).  With no profile the streaming
        # loops keep their wire-budget growth schedule; with one, the
        # first pass over a stream shape measures walk/wire and elects a
        # pipelined split when the link is fast enough to hide the fetch
        # chain under the walks.  Plans are cached per (kind, algo,
        # multi, n) so every later pass runs the SAME chunk schedule —
        # shape determinism is what keeps XLA compiles out of timed
        # regions (ROUND_NOTES r3).
        self._link_profile: Tuple[float, float] | None = None
        self._chunk_plans: Dict[tuple, tuple] = {}
        # Host-vs-device shard routing election (r8): None until the
        # first large sharded chunk A/Bs both (see _route_sharded).
        self._route_mode: str | None = None
        # Batch timestamps are clamped monotonically non-decreasing: a wall
        # clock stepping backwards (NTP) must not roll windows backwards —
        # the slot model keeps only (curr, prev) buckets, and a regressed
        # stamp would read as a window change and zero live counts.  (The
        # reference has the same hazard unmitigated: window keys + TTLs
        # both misbehave under clock regression.)
        self._last_stamp = 0
        self._stamp_lock = threading.Lock()
        # Clock-regression observability: the clamp silently absorbs a
        # backward wall-clock jump — count each absorbed regression so an
        # NTP step (or a broken injected clock) is visible in metrics
        # instead of only as mysteriously-frozen windows.
        self.backward_clamps = 0
        self._backward_clamp_counter = (
            meter_registry.counter(
                "ratelimiter.time.backward_clamp",
                "Wall-clock regressions absorbed by the monotonic batch-"
                "timestamp clamp")
            if meter_registry is not None else None)

        def _stamp() -> int:
            with self._stamp_lock:
                now = self._clock_ms()
                if now < self._last_stamp:
                    self.backward_clamps += 1
                    if self._backward_clamp_counter is not None:
                        self._backward_clamp_counter.increment()
                else:
                    self._last_stamp = now
                return self._last_stamp

        self._monotonic_now = _stamp

        # Hybrid host-side serving tier (cache/hybrid.py, Apt-Serve
        # shape): answers hot repeat-reject and safely-under-limit keys
        # host-side from exact adopted per-key state, device-confirmed
        # asynchronously.  OFF by default (ratelimiter.cache.hybrid.*
        # wires it); None costs one falsy check per acquire.
        self._serving = None
        if serving_cache:
            from ratelimiter_tpu.cache.hybrid import HybridServingCache

            self._serving = HybridServingCache(
                clock_ms=lambda: self._monotonic_now(),
                ttl_ms=serving_cache_ttl_ms,
                max_keys=serving_cache_max_keys,
                unconfirmed_cap=serving_cache_unconfirmed_cap,
                guard_ms=serving_cache_guard_ms,
                registry=meter_registry if self._obs else None,
            )

        # Dispatch/drain split (engine + batcher): the flusher only enqueues
        # device work; the drainer fetches — several batches in flight at
        # once, so fetch latency overlaps the next dispatches.
        def _dispatcher(fn):
            def run(s, l, p):
                stamp = _stamp()
                return (fn(s, l, p, stamp), time.perf_counter(), stamp,
                        np.asarray(l, dtype=np.int64))

            return run

        # Staged fast path (r11): the batcher hands over its pre-packed
        # combined staging buffer; dispatch is stamp + one upload + one
        # cached jit call, with the pack/layout sub-stages timed into
        # the ratelimiter.latency.assembly.* histograms.
        def _staged_dispatcher(algo):
            micro_ok = hasattr(self.engine, "micro_staged_dispatch")

            def run(buf, n):
                tracer = self._tracer
                t0 = time.perf_counter()
                stamp = _stamp()
                buf[3, 0] = stamp
                t1 = time.perf_counter()
                handle = self.engine.micro_staged_dispatch(algo, buf, n)
                if tracer is not None:
                    t2 = time.perf_counter()
                    tracer.record_sub("pack", (t1 - t0) * 1e6)
                    tracer.record_sub("layout", (t2 - t1) * 1e6)
                # Copy the lid lanes out for per-tenant accounting at
                # drain time: the staging buffer recycles once the drain
                # completes, so the drainer must not hold a view.
                return (handle, t1, stamp, buf[1, :n].copy())

            return run if micro_ok else None

        def _drainer(algo, fn, staged_fn=None):
            def run(handle_t0, n):
                handle, t0, stamp, lids = handle_t0
                out = fn(handle, n)
                dt_us = (time.perf_counter() - t0) * 1e6
                self._record_dispatch(algo, n, int(out["allowed"].sum()),
                                      dt_us)
                if self.telemetry is not None:
                    # Per-tenant fleet accounting: one bincount pass per
                    # batch, never per decision.
                    self.telemetry.note_batch(lids, out["allowed"],
                                              now_ms=stamp)
                if self._serving is not None:
                    # The hybrid serving tier needs the dispatch stamp to
                    # adopt exact per-key state (cache/hybrid.py).
                    out["stamp"] = np.full(n, stamp, dtype=np.int64)
                return out

            return run

        def _staged_drainer(algo):
            return _drainer(
                algo, lambda h, n: self.engine.micro_staged_drain(
                    algo, h, n))

        # The legacy list drains decode the same fused handle layout as
        # the staged path, so one drainer per algo serves both: the
        # flusher dispatches staged, dispatch_direct dispatches lists,
        # and either handle round-trips through (handle, t0, stamp).
        staged = {a: f for a, f in (("sw", _staged_dispatcher("sw")),
                                    ("tb", _staged_dispatcher("tb")))
                  if f is not None}
        # Adaptive flush control (engine/flush_control.py): ON by
        # default; the controller's applied deadline/size trigger stay
        # hard-clamped within [flush_floor_ms, max_delay_ms] /
        # [_MICRO_FLOOR-ish, max_batch].
        self._flush_controller = None
        if adaptive_flush:
            from ratelimiter_tpu.engine.flush_control import (
                AdaptiveFlushController,
            )

            self._flush_controller = AdaptiveFlushController(
                base_delay_ms=max_delay_ms,
                floor_ms=min(flush_floor_ms, max_delay_ms)
                if max_delay_ms > 0 else flush_floor_ms,
                cap_ms=max(max_delay_ms, flush_floor_ms),
                size_floor=32,
                size_cap=max_batch,
                meter_registry=meter_registry if self._obs else None,
            )
        self._batcher = MicroBatcher(
            dispatch={
                "sw": _dispatcher(self.engine.sw_acquire_dispatch),
                "tb": _dispatcher(self.engine.tb_acquire_dispatch),
            },
            drain={
                "sw": (_staged_drainer("sw") if "sw" in staged
                       else _drainer("sw", self.engine.sw_acquire_drain)),
                "tb": (_staged_drainer("tb") if "tb" in staged
                       else _drainer("tb", self.engine.tb_acquire_drain)),
            },
            dispatch_staged=staged or None,
            clear={
                "sw": lambda slots: self._clear_slots("sw", slots),
                "tb": lambda slots: self._clear_slots("tb", slots),
            },
            max_batch=max_batch,
            max_delay_ms=max_delay_ms,
            max_inflight=max_inflight,
            max_pending=max_pending,
            deadline_ms=queue_deadline_ms,
            controller=self._flush_controller,
            meter_registry=meter_registry,
            tracer=self._tracer,
            recorder=self._recorder,
        )

    def _auto_host_parallel(self, checkpointable: bool) -> int:
        """Elected partition count for the host slot index (see the
        _HOST_PARALLEL_AUTO_* notes): min(cores, 8), walked down to the
        largest count dividing num_slots; 0 (single index) when the
        engine is sharded, the table is small, the native library is
        missing, the host has <= 2 cores, or checkpoints need the
        enumerable Python index."""
        if checkpointable or hasattr(self.engine, "n_shards"):
            return 0
        if self.engine.num_slots < _HOST_PARALLEL_AUTO_MIN_SLOTS:
            return 0
        from ratelimiter_tpu.engine.native_index import native_available

        if not native_available():
            return 0
        try:
            cores = len(os.sched_getaffinity(0))
        except (AttributeError, OSError):  # pragma: no cover - non-linux
            cores = os.cpu_count() or 1
        if cores <= 2:
            return 0
        t = min(cores, _HOST_PARALLEL_AUTO_MAX)
        while t > 1 and self.engine.num_slots % t:
            t -= 1
        return t if t > 1 else 0

    # ------------------------------------------------------------------------
    # Batched decision protocol (the hot path)
    # ------------------------------------------------------------------------
    def register_limiter(self, algo: str, config: RateLimitConfig) -> int:
        """Register a limiter policy; returns its limiter id (device table row)."""
        if algo not in ("sw", "tb"):
            raise ValueError(f"unknown algorithm kind: {algo!r}")
        config.validate()
        lid = self.table.register(config)
        self._configs[lid] = (algo, config)
        if self._serving is not None:
            self._serving.register(lid, algo, config)
        return lid

    # ------------------------------------------------------------------------
    # Live policy updates (control/, ARCHITECTURE §15)
    # ------------------------------------------------------------------------
    def set_policy(self, lid: int, config: RateLimitConfig,
                   generation: int | None = None) -> int:
        """Live-update one limiter's policy; returns the policy
        generation the update installed.

        Semantics: every decision stamped BEFORE this call returns was
        evaluated under the old row, every decision after under the new
        one — pending micro-batch traffic is flushed first so the
        generation boundary is exact (a queued request never silently
        jumps generations between submit and dispatch).  The device row
        moves via three scalar updates (LimiterTable.set_policy —
        window/algo shape immutable), so no recompile and no table
        re-upload.  The hybrid serving tier forgets the lid's adopted
        state BEFORE the row moves (a host serve racing the update must
        not answer from the old policy), and registered policy
        listeners (degraded limiter, lease manager) are notified after.

        ``generation`` is for replication only: a standby replaying the
        primary's updates installs the primary's stamps.
        """
        entry = self._configs.get(int(lid))
        if entry is None:
            raise KeyError(f"no limiter registered under lid={lid}")
        algo, _old = entry
        config.validate()
        if self._serving is not None:
            self._serving.update_policy(int(lid), algo, config)
        self._batcher.flush()
        gen = self.table.set_policy(int(lid), config,
                                    generation=generation)
        self._configs[int(lid)] = (algo, config)
        for listener in self._policy_listeners:
            try:
                listener(int(lid), algo, config, gen)
            except Exception:  # noqa: BLE001 — a broken mirror must not
                # poison the actuation path; the listener logs itself.
                log.exception("policy listener failed for lid=%d", lid)
        return gen

    def add_policy_listener(self, listener) -> None:
        """Subscribe ``listener(lid, algo, config, generation)`` to live
        policy updates (called after the device row moved)."""
        self._policy_listeners.append(listener)

    def policy_info(self) -> Dict:
        """Policy-generation metadata (the fence_info analog): the
        table-wide monotonic generation plus each lid's row stamp."""
        return {
            "generation": self.table.generation,
            "lids": {int(lid): {
                "algo": algo,
                "generation": self.table.row_generation(lid),
                "max_permits": cfg.max_permits,
                "window_ms": cfg.window_ms,
                "refill_rate": cfg.refill_rate,
            } for lid, (algo, cfg) in self._configs.items()},
        }

    def acquire(self, algo: str, lid: int, key: str, permits: int,
                deadline_ms: float | None = None,
                trace_id: int = 0) -> dict:
        """Single decision through the micro-batcher (blocks until the batch
        containing this request lands; bounded by max_delay_ms).

        ``deadline_ms`` overrides the storage-wide queue-deadline budget
        for this request (admission control; engine/batcher.py)."""
        return self.acquire_async(algo, lid, key, permits,
                                  deadline_ms=deadline_ms,
                                  trace_id=trace_id).result()

    def acquire_async(self, algo: str, lid: int, key: str, permits: int,
                      deadline_ms: float | None = None,
                      trace_id: int = 0):
        """Future-returning :meth:`acquire` — the pipelining ingress
        primitive (service/sidecar.py): a connection handler submits
        every frame of a pipelined batch before resolving any, so all
        of them coalesce into the same micro-batch flush instead of
        paying one batcher round trip each.

        ``trace_id``: a 64-bit trace id carried end to end (0 = mint
        one here when lineage sampling is armed) — sampled ids record
        batcher/shard/resolve hops (observability/telemetry.py).

        With the hybrid serving tier enabled, a tracked key's decision
        may resolve host-side immediately (see cache/hybrid.py): a pure
        reject touches no device at all; a mutating decision rides the
        next micro-batch asynchronously as its device confirmation."""
        lin = self.lineage
        if not trace_id and lin is not None and lin.sample_n > 0:
            from ratelimiter_tpu.observability.telemetry import (
                mint_trace_id,
            )

            trace_id = mint_trace_id()
        serving = self._serving
        if serving is not None:
            fut = self._serve_host_side(algo, lid, key, permits)
            if fut is not None:
                return fut
        t0 = time.perf_counter() if self._tracer is not None else 0.0
        slot = self._assign_slot(algo, lid, key, hold_pin=True)
        if self._tracer is not None:
            self._tracer.record_sub(
                "index", (time.perf_counter() - t0) * 1e6)
        # The pin (taken atomically inside the assign) holds until the
        # submit registers the slot in pending_slots.
        try:
            with self._pins_released(self._index[algo], [slot]):
                fut = self._batcher.submit(algo, slot, lid, permits,
                                           deadline_ms=deadline_ms,
                                           trace_id=trace_id)
        except OverloadedError:
            if self.telemetry is not None:
                self.telemetry.note_shed(lid, 1)
            raise
        if serving is not None:
            serving.watch_miss(algo, lid, key, permits, slot, fut)
        return fut

    def _serve_host_side(self, algo: str, lid: int, key: str, permits: int):
        """Hybrid-tier serve attempt: a resolved Future, or None (miss).

        The fence/promotion checks run BEFORE the tier is consulted — a
        host-served decision must refuse exactly where a device dispatch
        would.  A host-served mutating decision is forwarded through the
        normal batcher path under the tier's lock (so device order ==
        serve order per key) and confirmed by its drain callback."""
        self._check_not_promoting()
        if self._fenced_shards:
            self._check_fence_keys([lid], [key])
        serving = self._serving
        with serving.lock:
            served = serving.serve(algo, lid, key, permits)
            if served is None:
                return None
            out, predicted = served
            if predicted is not None:  # mutated host-side: confirm async
                slot = self._assign_slot(algo, lid, key, hold_pin=True)
                with self._pins_released(self._index[algo], [slot]):
                    cfut = self._batcher.submit(algo, slot, lid, permits)
                serving.watch_confirm(algo, lid, key, predicted, slot,
                                      cfut)
        fut: Future = Future()
        fut.set_result(out)
        return fut

    def acquire_async_many(self, algo: str, lid: int,
                           keys: Sequence[str], permits=None,
                           deadline_ms: float | None = None):
        """Bulk :meth:`acquire_async` for a pipelined burst sharing one
        limiter: the keys hash in one windowed C pass off the interned
        UTF-8 buffers and map in one batched slot walk
        (native/str_pack.cpp:rl_strlist_hash_fp ->
        rl_index_assign_fps/engine/native_index.py:assign_batch_strs),
        then submit in one vectorized staging-buffer write — zero
        per-request Python on the index/layout half of assembly.
        Returns one Future per key; decisions ride the next micro-batch
        flush together.  Falls back to per-key submits without the
        native index.  The hybrid tier is bypassed (burst callers want
        coalescing, not per-key host serves)."""
        self._check_not_promoting()
        if self._fenced_shards:
            self._check_fence_keys([lid] * len(keys), keys)
        n = len(keys)
        if permits is None:
            permits = np.ones(n, dtype=np.int64)
        index = self._index[algo]
        if not hasattr(index, "assign_batch_strs"):
            return [self.acquire_async(algo, lid, k, int(p),
                                       deadline_ms=deadline_ms)
                    for k, p in zip(keys, permits)]
        t0 = time.perf_counter() if self._tracer is not None else 0.0
        with self._evictions_cleared(algo):
            slots, clears = index.assign_batch_strs(
                list(keys), lid,
                pinned=self._batcher.pending_slots(algo),
                hold_pins=True)
        if self._tracer is not None:
            self._tracer.record_sub(
                "index", (time.perf_counter() - t0) * 1e6)
        for evicted in clears:
            self._batcher.add_clear(algo, int(evicted))
        try:
            with self._pins_released(index, slots):
                return self._batcher.submit_many(
                    algo, slots, np.full(n, lid, dtype=np.int64), permits,
                    deadline_ms=deadline_ms)
        except OverloadedError:
            if self.telemetry is not None:
                self.telemetry.note_shed(lid, n)
            raise

    def acquire_async_block(self, algo: str, lid: int, data, offsets,
                            permits=None,
                            deadline_ms: float | None = None,
                            trace_id: int = 0):
        """Columnar :meth:`acquire_async_many`: the caller hands the v5
        batch frame's key column verbatim (data uint8[klen] packed UTF-8
        + offsets i64[n+1]) and gets ONE future resolving to
        ``{"allowed": bool[n], ...}`` lane slices — zero per-request
        Python objects end to end (native assign_batch_bytes ->
        batcher.submit_block).  Returns None when this storage can't take
        the columnar shortcut (Python index, or shard fences that need
        the key strings) — the caller falls back to the decoded-string
        path with identical decisions."""
        self._check_not_promoting()
        if self._fenced_shards:
            return None  # fence checks need the decoded keys
        index = self._index[algo]
        if not hasattr(index, "assign_batch_bytes"):
            return None
        n = len(offsets) - 1
        if permits is None:
            permits = np.ones(n, dtype=np.int64)
        t0 = time.perf_counter() if self._tracer is not None else 0.0
        with self._evictions_cleared(algo):
            slots, clears = index.assign_batch_bytes(
                data, offsets, lid,
                pinned=self._batcher.pending_slots(algo),
                hold_pins=True)
        if self._tracer is not None:
            self._tracer.record_sub(
                "index", (time.perf_counter() - t0) * 1e6)
        for evicted in clears:
            self._batcher.add_clear(algo, int(evicted))
        try:
            with self._pins_released(index, slots):
                return self._batcher.submit_block(
                    algo, slots, np.full(n, lid, dtype=np.int64), permits,
                    deadline_ms=deadline_ms, trace_id=trace_id)
        except OverloadedError:
            if self.telemetry is not None:
                self.telemetry.note_shed(lid, n)
            raise

    def acquire_many(
        self, algo: str, lid_per_req: Sequence[int], keys: Sequence[str],
        permits: Sequence[int],
    ) -> Dict[str, np.ndarray]:
        """Whole-batch synchronous decision (the vectorized/bench path)."""
        self._check_not_promoting()
        if self._fenced_shards:
            self._check_fence_keys(lid_per_req, keys)
        index = self._index[algo]
        lid0 = lid_per_req[0] if lid_per_req else 0
        uniform_lid = all(l == lid0 for l in lid_per_req)
        if uniform_lid and hasattr(index, "assign_batch_strs"):
            # Native fast path: flush queued traffic first, then one C call
            # maps the whole batch; same-batch keys are generation-pinned and
            # slots of requests queued since the flush are pin-protected.
            self._batcher.flush()
            with self._evictions_cleared(algo):
                slots, clears = index.assign_batch_strs(
                    list(keys), lid0,
                    pinned=self._batcher.pending_slots(algo),
                    hold_pins=True)
            with self._pins_released(index, slots):
                return self._batcher.dispatch_direct(
                    algo, slots, list(lid_per_req), list(permits),
                    list(clears))
        pinned = self._batcher.pending_slots(algo)
        slots: List[int] = []
        clears: List[int] = []
        # try/finally from the FIRST assign: a mid-loop raise ("all slots
        # pinned") must release the pins earlier iterations took — and
        # clear the evictions they applied (the index already remapped
        # those slots; see _evictions_cleared).
        try:
            try:
                for lid, key in zip(lid_per_req, keys):
                    slot, evicted = index.assign((lid, key), pinned=pinned,
                                                 hold_pin=True)
                    if evicted is not None:
                        clears.append(evicted)
                    pinned.add(slot)
                    slots.append(slot)
            except Exception:
                if clears:
                    self._clear_slots(algo, clears)
                raise
            return self._batcher.dispatch_direct(
                algo, slots, list(lid_per_req), list(permits), clears)
        finally:
            self._unpin_held(
                index, [np.asarray(slots, dtype=np.int32)] if slots else [])

    def acquire_many_ids(
        self, algo: str, lid: int, key_ids: np.ndarray, permits: np.ndarray,
    ) -> Dict[str, np.ndarray]:
        """Int-key whole-batch decision — the hyperscale hot path.

        Integer user/tenant ids skip string hashing entirely: one C call for
        slot assignment, one device dispatch for the decisions.
        """
        self._check_not_promoting()
        if self._fenced_shards:
            self._check_fence_int_keys(key_ids)
        index = self._index[algo]
        if hasattr(index, "assign_batch_ints"):
            self._batcher.flush()
            with self._evictions_cleared(algo):
                slots, clears = index.assign_batch_ints(
                    np.ascontiguousarray(key_ids, dtype=np.int64), lid,
                    pinned=self._batcher.pending_slots(algo),
                    hold_pins=True)
            clears = list(clears)
        else:
            pinned = self._batcher.pending_slots(algo)
            slots = []
            clears = []
            # try/finally from the FIRST assign (see acquire_many): a
            # mid-loop raise must release earlier iterations' pins and
            # clear their applied evictions.
            try:
                try:
                    for k in np.asarray(key_ids):
                        slot, evicted = index.assign((lid, int(k)),
                                                     pinned=pinned,
                                                     hold_pin=True)
                        if evicted is not None:
                            clears.append(evicted)
                        pinned.add(slot)
                        slots.append(slot)
                except Exception:
                    if clears:
                        self._clear_slots(algo, clears)
                    raise
                slots = np.asarray(slots, dtype=np.int32)
                lids = np.full(len(slots), lid, dtype=np.int32)
                return self._batcher.dispatch_direct(algo, slots, lids,
                                                     permits, clears)
            finally:
                self._unpin_held(
                    index,
                    [np.asarray(slots, dtype=np.int32)] if len(slots)
                    else [])
        lids = np.full(len(slots), lid, dtype=np.int32)
        with self._pins_released(index, slots):
            return self._batcher.dispatch_direct(algo, slots, lids, permits,
                                                 clears)

    def acquire_stream_ids(
        self,
        algo: str,
        lid,
        key_ids: np.ndarray,
        permits: np.ndarray | None = None,
        *,
        batch: int = 1 << 14,
        subbatches: int = 4,
    ) -> np.ndarray:
        """Whole-stream int-key decisions, pipelined — the hyperscale path.

        The stream is cut into super-batches of ``subbatches * batch``
        requests.  For each: one C call assigns slots, one device dispatch
        runs ``subbatches`` sequential decision steps (lax.scan), and only
        the bit-packed allow/deny mask comes back — while it is in flight
        the next super-batch is being indexed and dispatched, so transfer
        latency overlaps device compute.  Decisions are identical to
        ``acquire_many_ids`` called per sub-batch (tests/test_packed.py);
        permits above 2^31-1 — above any limiter's max_permits, which is
        bounded to int32 — are denied without touching state, exactly as
        the i64 batch path rejects them.

        ``lid`` is either one limiter id for the whole stream (the device
        reads that policy row once — zero table gathers) or an int array of
        per-request limiter ids (multi-tenant stream).  Both modes index a
        bucket under the same (lid, key) namespace as ``acquire_many_ids``
        and ``acquire``, so paths can be mixed freely.  ``permits=None``
        means one permit per request (the permits upload is skipped; the
        device materializes ones).  Returns bool[n] allowed.
        """
        self._check_not_promoting()
        if self._fenced_shards:
            self._check_fence_int_keys(key_ids)
        multi_lid = np.ndim(lid) != 0
        if multi_lid:
            lid_arr = np.ascontiguousarray(lid, dtype=np.int64)
            if lid_arr.size and ((lid_arr < 0) | (lid_arr >= len(self.table))).any():
                raise ValueError("limiter ids out of range")
        # The stream paths carry permits as i32 lanes; a value past 2^31-1
        # would wrap negative and read as an ALLOW (a negative "request"
        # credits tokens) where the i64 batch path rejects it.  max_permits
        # always fits int32 (Java-int parity bound in core/config.py), so
        # any such request is above every limiter's cap: force-deny it by
        # dispatching its lane as padding (slot -1) — decision identical to
        # the batch path's reject, state untouched.
        oversize = None
        if permits is not None:
            permits = np.asarray(permits)
            if permits.size and int(permits.min(initial=0)) < np.iinfo(
                    np.int32).min:
                raise ValueError("permits below int32 range")
            over = permits > np.iinfo(np.int32).max
            if over.any():
                oversize = over

        index = self._index[algo]
        if hasattr(index, "_sub") and getattr(index, "supports_batch_ints", False):
            # Sharded engine: route keys to shards host-side, one shard_map'd
            # scan dispatch per super-batch, zero cross-shard device traffic.
            self._batcher.flush()
            return self._stream_sharded(
                algo, lid, np.ascontiguousarray(key_ids, dtype=np.int64),
                permits, batch, subbatches, index, multi_lid,
                lid_arr if multi_lid else None, oversize)
        if not hasattr(index, "assign_batch_ints"):
            # Python-index fallback: plain per-batch path, same decisions.
            n = len(key_ids)
            out = np.empty(n, dtype=bool)
            p = np.ones(n, dtype=np.int64) if permits is None \
                else np.asarray(permits)
            for i in range(0, n, batch):
                chunk = key_ids[i:i + batch]
                if multi_lid:
                    chunk_lids = lid_arr[i:i + batch]
                    pinned = self._batcher.pending_slots(algo)
                    slots, clears = [], []
                    try:
                        for l, k in zip(chunk_lids, chunk):
                            s, ev = index.assign((int(l), int(k)),
                                                 pinned=pinned)
                            if ev is not None:
                                clears.append(ev)
                            pinned.add(s)
                            slots.append(s)
                    except Exception:  # mid-loop raise: clear applied evs
                        if clears:
                            self._clear_slots(algo, clears)
                        raise
                    res = self._batcher.dispatch_direct(
                        algo, slots, list(chunk_lids), list(p[i:i + batch]),
                        clears)
                    out[i:i + batch] = res["allowed"]
                else:
                    out[i:i + batch] = self.acquire_many_ids(
                        algo, lid, chunk, p[i:i + batch])["allowed"]
            return out

        self._batcher.flush()
        key_ids = np.ascontiguousarray(key_ids, dtype=np.int64)
        if oversize is not None:
            permits = np.where(oversize, 1, permits)  # lanes masked, see above

        if (permits is not None and not multi_lid and oversize is None
                and hasattr(index, "assign_batch_ints_uniques")
                and permits.size
                and int(permits.min()) >= 1
                and int(permits.max()) <= self.engine.weighted_permit_cap):
            # Weighted-permit relay (ops/relay.py:*_relay_weighted): the
            # index's duplicate structure splits segments into closed-form
            # singles and a short rank-major scan — no device sort, no
            # solver, chunks grow to the wire budget.  Requests with
            # permits < 1 or above the word capacity keep the flat path's
            # semantics and routing.
            rb = self.engine.rank_bits

            def assign_uniques_w(start, chunk_n):
                with self._evictions_cleared(algo):
                    return index.assign_batch_ints_uniques(
                        key_ids[start:start + chunk_n], lid, rb,
                        pinned=self._batcher.pending_slots(algo),
                        hold_pins=True)

            return self._stream_weighted(
                algo, lid, assign_uniques_w, len(key_ids),
                np.ascontiguousarray(permits, dtype=np.int64), index)

        if (permits is None
                and hasattr(index, "assign_batch_ints_uniques")
                and self.engine.relay_usable()):
            # Unit-permit relay path (ops/relay.py): the index hands the
            # device the duplicate structure it computed while assigning
            # slots, deleting the device-side sort/scan entirely.
            rb = self.engine.rank_bits

            def assign_uniques(start, chunk_n):
                chunk = key_ids[start:start + chunk_n]
                with self._evictions_cleared(algo):
                    if multi_lid:
                        return index.assign_batch_ints_multi_uniques(
                            chunk, lid_arr[start:start + chunk_n], rb,
                            pinned=self._batcher.pending_slots(algo),
                            hold_pins=True)
                    return index.assign_batch_ints_uniques(
                        chunk, lid, rb,
                        pinned=self._batcher.pending_slots(algo),
                        hold_pins=True)

            return self._stream_relay(algo, lid, assign_uniques, len(key_ids),
                                      lid_arr if multi_lid else None)

        def assign(start, chunk_n):
            chunk = key_ids[start:start + chunk_n]
            with self._evictions_cleared(algo):
                if multi_lid:
                    return index.assign_batch_ints_multi(
                        chunk, lid_arr[start:start + chunk_n],
                        pinned=self._batcher.pending_slots(algo),
                        hold_pins=True)
                return index.assign_batch_ints(
                    chunk, lid, pinned=self._batcher.pending_slots(algo),
                    hold_pins=True)

        return self._stream_flat(algo, lid, assign, len(key_ids), permits,
                                 oversize, batch, subbatches,
                                 lid_arr if multi_lid else None)

    def _stream_relay(self, algo, lid, assign_uniques, n,
                      lid_arr=None, key_kind="ints") -> np.ndarray:
        """Relay streaming loop (unit permits): per chunk, one C call
        assigns slots AND produces the duplicate structure — per-unique
        (slot | segment count) words plus host-side (unique-index, rank)
        per request (native/slot_index.cpp:assign_batch_uniques).  The
        dispatch is chosen per chunk by measured traffic:

        - **segment digest** (skewed traffic): upload one uint32 per
          UNIQUE slot, device returns one allowed-count per unique, host
          reconstructs per-request booleans as ``rank < n_allowed[uidx]``
          (one numpy gather).  Bytes shrink by the duplicate factor —
          4-10x on the Zipf/multi-tenant scenarios — and the device
          gathers/scatters only unique rows.
        - **per-request words** (uniform traffic, duplicate-poor): the
          (slot|rank|last) words are reconstructed in numpy from the same
          digest output and dispatched through the bit-mask relay step.

        Both decide identically to the sorted flat path on the same
        chunking (tests/test_relay.py).  Chunks are ``_RELAY_CHUNK``
        requests (growing to the wire budget) and pipeline three-deep so
        fetches ride in the shadow of later chunks' host work + upload."""
        from ratelimiter_tpu.engine.native_index import rebuild_words_into
        from ratelimiter_tpu.ops.relay import rebuild_words, wire_costs

        multi_lid = lid_arr is not None
        eng = self.engine
        rb = eng.rank_bits
        cdt = eng.counts_dtype()
        digest_bpu, words_bpr = wire_costs(multi_lid,
                                           resident_lids=True)
        bits_dispatch = (eng.sw_relay_dispatch if algo == "sw"
                         else eng.tb_relay_dispatch)
        counts_dispatch = (eng.sw_relay_counts_dispatch if algo == "sw"
                           else eng.tb_relay_counts_dispatch)
        def clear(slots):
            self._clear_slots(algo, slots)
        out = np.empty(n, dtype=bool)
        drains = _DrainSet(self._drain_pool())

        # Chunk plan (VERDICT r3 #1): the first pass over this stream
        # shape runs the wire-budget growth schedule and measures; later
        # passes may run a fixed pipelined split instead, with eager
        # drains so fetches ride under the worker's walk of the next
        # chunk.  tot[...] feeds the end-of-pass election.  key_kind
        # separates int- from str-keyed streams: their walks cost very
        # differently, so they must not share a plan.
        # n is BANDED into the plan key (quarter-octave) so a service
        # with naturally jittering stream lengths reuses one plan per
        # band instead of re-measuring every distinct n.
        plan_key = ("relay", key_kind, algo, lid_arr is not None,
                    _bucket_fine(n, floor=_RELAY_CHUNK))
        plan, pipelined, tot, timed_assign, t_pass0 = self._plan_setup(
            plan_key, assign_uniques)
        rates = self._device_rates()

        def drain(mode, handle, start, count, extra, t0, rec, bufs=()):
            try:
                tf0 = time.perf_counter()
                arr = np.asarray(handle)  # the one blocking fetch
                tf1 = time.perf_counter()
                dt_us = (tf1 - t0) * 1e6
                self._stage("fetch", tf1 - tf0)
                if mode == "bits":
                    got = np.unpackbits(arr)[:count].astype(bool)
                elif mode == "split":
                    # [packed singleton bits | multi count bytes] -> one
                    # per-unique counts lane, then the standard rank
                    # compare (singleton counts are exactly their allow
                    # bit).
                    from ratelimiter_tpu.engine.native_index import (
                        relay_decide,
                    )

                    uidx2, rank, u, n_s, s_pad, m_pad, cdt_l = extra
                    csize = np.dtype(cdt_l).itemsize
                    counts_all = np.empty(u, dtype=cdt_l)
                    counts_all[:n_s] = np.unpackbits(
                        arr[:s_pad // 8])[:n_s]
                    counts_all[n_s:] = arr[
                        s_pad // 8:s_pad // 8 + m_pad * csize].view(
                            cdt_l)[:u - n_s]
                    got = relay_decide(counts_all, uidx2, rank)
                else:  # digest: reconstruct from per-unique counts
                    from ratelimiter_tpu.engine.native_index import (
                        relay_decide,
                    )

                    uidx, rank, u = extra
                    got = relay_decide(arr[:u], uidx, rank)
                out[start:start + count] = got
                n_allowed = int(got.sum())
                with tot["_lock"]:
                    tot["fetch_s"] += tf1 - tf0
                    if rec is not None:
                        rec["fetch_s"] = round(tf1 - tf0, 6)
                        rec["fetch_at"] = [round(tf0 - t_pass0, 6),
                                           round(tf1 - t_pass0, 6)]
                    self._record_dispatch(algo, count, n_allowed, dt_us,
                                          path=f"relay|{mode}",
                                          lid=None if multi_lid else lid)
            finally:
                # Staging buffers are reusable only after the fetch: the
                # upload that read them is certainly consumed by then.
                for b in bufs:
                    self._staging.give(b)

        cursor = _ChunkCursor(plan, pipelined)
        start = 0
        fut = None  # prefetched next-chunk assignment (holds pins)
        try:
            while start < n:
                cn = cursor.next_size(n - start)
                t_a0 = time.perf_counter()
                if fut is not None:
                    uwords, uidx, rank, clears = fut.result()
                    fut = None
                else:
                    uwords, uidx, rank, clears = timed_assign(start, cn)
                t_assign = time.perf_counter() - t_a0
                u = len(uwords)
                pack_s = (getattr(self._index[algo], "str_pack_s", None)
                          if key_kind == "strs" else None)
                if pack_s is not None:
                    self._stage("pack", pack_s)
                rec = self._stream_rec("relay", n=int(cn), u=int(u),
                                       assign_s=t_assign)
                if rec is not None:
                    if self._host_parallel:
                        # The walk-term split: assign_s is the EXPOSED
                        # main-thread time while the C walk itself fans
                        # out over this many partitions (walk_s stays
                        # the true cumulative walk seconds).
                        rec["host_parallel"] = self._host_parallel
                    if pack_s is not None:
                        rec["pack_s"] = round(pack_s, 6)
                uslots_all = (uwords >> np.uint32(rb + 1)).astype(np.int32)
                with self._pins_released(self._index[algo], uslots_all):
                    if len(clears):
                        clear(list(clears))
                    l_chunk = (lid_arr[start:start + cn] if multi_lid
                               else None)
                    # Mode election: steady-state digest cost per unique plus
                    # this chunk's (slot, lid) delta uploads charged at
                    # 1/_DELTA_AMORT (they are an investment — once resident,
                    # every later chunk reads the lid from the device map).
                    fresh = None
                    n_delta = 0
                    if cdt is not None and multi_lid:
                        with self._lid_locks[algo]:
                            known = self._lid_known.setdefault(
                                algo, np.zeros(eng.num_slots, dtype=bool))
                            uslots = uslots_all.astype(np.int64)
                            fresh = ~known[uslots]
                        from ratelimiter_tpu.parallel.sharded import _bucket as _bkt
                        n_delta = _bkt(max(int(fresh.sum()), 1), floor=8)
                    # One sorted-eligibility verdict drives BOTH the
                    # mode election's device rate and the dispatch path
                    # below — they must never disagree.  Sorting pays
                    # off when EITHER sorted device path engages: the
                    # dense presorted sweep, or (scalar-lid dispatches
                    # only) the fused Pallas relay step the engine
                    # elects per device (ops/pallas/relay_step.py).
                    fused_ok = (not multi_lid
                                and hasattr(eng, "_relay_fused_ok")
                                and eng._relay_fused_ok(
                                    algo, _bucket_pow2(u)))
                    srt_ok = (u >= _SORT_UNIQUES_MIN
                              and _sort_affordable(self._link_profile, u)
                              and (fused_ok or _presorted_scatter_usable(
                                  eng, algo, _bucket_pow2(u))))
                    digest = cdt is not None and _elect_digest_mode(
                        self._link_profile, u, cn, n_delta, digest_bpu,
                        words_bpr, srt_ok,
                        cdt_size=np.dtype(cdt).itemsize if cdt else 1,
                        rates=rates)
                    # Split-digest election (r5): singletons as a 3-byte
                    # slot plane with BIT decisions back, multis as
                    # classic uwords+counts — beats classic digest when
                    # most uniques are singletons and beats words mode
                    # at high u/n, per-direction costs compared against
                    # whichever of the two won above.
                    split = False
                    n_singles = 0
                    if (self._link_profile is not None and cdt is not None
                            and not multi_lid and rb >= 2
                            and eng.num_slots <= 0xFFFFFF
                            and u >= _SORT_UNIQUES_MIN):
                        prof = self._link_profile
                        up_r = max(prof[0], 1.0)
                        down_r = max(prof[2], 1.0) if len(prof) > 2 else up_r
                        cdt_b = np.dtype(cdt).itemsize
                        singles_mask = (((uwords >> np.uint32(1))
                                         & np.uint32((1 << rb) - 1)) == 1)
                        n_singles = int(singles_mask.sum())
                        n_multi = u - n_singles
                        cost_split = (
                            n_singles * (3.0 / up_r + 0.125 / down_r)
                            + n_multi * (4.0 / up_r + cdt_b / down_r)
                            + u * (rates["s_per_unique_unsorted"]
                                   + _SPLIT_HOST_S_PER_UNIQUE))
                        if digest:
                            # Classic digest uploads exactly the 4 B
                            # uword and downloads the cdt count (the
                            # blended digest_bpu would overcharge the
                            # upload by 1 B at cdt_b=1).
                            dev_u = rates["s_per_unique_sorted" if srt_ok
                                          else "s_per_unique_unsorted"]
                            rival = u * (4.0 / up_r + cdt_b / down_r
                                         + dev_u)
                        else:
                            rival = cn * ((words_bpr - 0.125) / up_r
                                          + 0.125 / down_r
                                          + rates["s_per_lane"])
                        split = cost_split < rival
                    now = self._monotonic_now()
                    t_prep = time.perf_counter()
                    t0 = time.perf_counter()
                    if split:
                        from ratelimiter_tpu.engine.native_index import (
                            split_layout,
                        )

                        srt = False  # split lanes dispatch unsorted
                        s3, mwords, uidx2, n_s = split_layout(
                            uwords, rb, uidx, singles=singles_mask)
                        # Quarter-octave buckets: pow2 padding at these
                        # lane counts wastes up to ~55% of the wire the
                        # split exists to save (2.7M singles -> 4.19M
                        # pow2 lanes, measured); fine buckets cap the
                        # waste at ~12% for a couple extra compile
                        # shapes.  Both stay multiples of 8 (packbits).
                        s_pad = _bucket_fine(n_s)
                        m_pad = _bucket_fine(u - n_s)
                        s3p = self._staging.take((s_pad, 3), np.uint8)
                        s3p[:n_s] = s3
                        s3p[n_s:] = 0xFF
                        mw = self._staging.take((m_pad,), np.uint32)
                        mw[:u - n_s] = mwords
                        mw[u - n_s:] = 0xFFFFFFFF
                        split_dispatch = (
                            eng.sw_relay_counts_split_dispatch
                            if algo == "sw"
                            else eng.tb_relay_counts_split_dispatch)
                        t_e0 = time.perf_counter()
                        outh = split_dispatch(s3p, mw, lid, now, cdt)
                        self._stage("layout", t_e0 - t0)
                        self._stage("enqueue", time.perf_counter() - t_e0)
                        item = ("split", outh, start, cn,
                                (uidx2, rank, u, n_s, s_pad, m_pad, cdt),
                                t0, rec, [s3p, mw])
                        digest = True  # per-unique accounting below
                    elif digest:
                        # Slot-sorted digest: the C index sorts the uniques
                        # in place (uidx remapped — reconstruction is order-
                        # agnostic) so the device write is a dense sweep.
                        # srt_ok (shared with the election above) already
                        # gates on the sweep actually engaging — on the
                        # XLA fallback the scatter is order-blind and the
                        # sort would be pure overhead.
                        srt = False
                        if srt_ok:
                            from ratelimiter_tpu.engine.native_index import (
                                sort_uniques,
                            )

                            srt = sort_uniques(uwords, rb, uidx)
                        size = _bucket_pow2(u)
                        uw = self._staging.take((size,), np.uint32)
                        uw[:u] = uwords
                        uw[u:] = 0xFFFFFFFF
                        if multi_lid:
                            # Tenant ids live RESIDENT on device (a slot's lid is
                            # immutable while assigned): upload only the (slot,
                            # lid) pairs the device doesn't know yet — fresh
                            # assignments and post-eviction reuse, tracked in
                            # _lid_known and invalidated by _clear_slots.  Per-
                            # unique lids map through uidx (NOT positional: a
                            # partitioned index merges uniques partition-major).
                            from ratelimiter_tpu.parallel.sharded import _bucket

                            first = rank == 0
                            ulids = np.zeros(u, dtype=np.int32)
                            ulids[uidx[first]] = l_chunk[first]
                            # Re-read fresh, mark, and dispatch under the lock
                            # shared with _clear_slots: an eviction racing the
                            # mark must win (forcing a later re-upload), never
                            # lose to a stale known=True.
                            with self._lid_locks[algo]:
                                if srt:  # uwords were re-ordered in place
                                    uslots = (uwords >> np.uint32(rb + 1)
                                              ).astype(np.int64)
                                fresh = ~known[uslots]
                                n_delta = int(fresh.sum())
                                dsize = _bucket(max(n_delta, 1), floor=8)
                                d_slots = _pad_tail(uslots[fresh], dsize, -1,
                                                    np.int32)
                                d_lids = _pad_tail(ulids[fresh], dsize, 0,
                                                   np.int32)
                                resident = (eng.sw_relay_counts_resident_dispatch
                                            if algo == "sw"
                                            else eng.tb_relay_counts_resident_dispatch)
                                t_e0 = time.perf_counter()
                                counts = resident(uw, d_slots, d_lids, now,
                                                  cdt, slots_sorted=srt)
                                self._stage("layout", t_e0 - t0)
                                self._stage("enqueue",
                                            time.perf_counter() - t_e0)
                                # Mark AFTER the dispatch: a raise must not
                                # leave slots "known" with no lid uploaded.
                                known[uslots[fresh]] = True
                                n_delta = dsize  # charge the padded lane
                        else:
                            t_e0 = time.perf_counter()
                            counts = counts_dispatch(uw, lid, now, cdt,
                                                     slots_sorted=srt)
                            self._stage("layout", t_e0 - t0)
                            self._stage("enqueue",
                                        time.perf_counter() - t_e0)
                        item = ("digest", counts, start, cn,
                                (uidx, rank, u), t0, rec, [uw])
                    else:
                        size = _bucket_pow2(cn)
                        words = self._staging.take((size,), np.uint32)
                        words[cn:] = 0xFFFFFFFF
                        if not rebuild_words_into(uwords, uidx, rank, rb,
                                                  words[:cn]):
                            words[:cn] = rebuild_words(uwords, uidx, rank, rb)
                        lid_lane = lid if not multi_lid else _pad_tail(
                            l_chunk, size, 0, np.int32)
                        if rec is not None:
                            rec["rebuild_s"] = round(
                                time.perf_counter() - t_prep, 6)
                            t_prep = time.perf_counter()
                        t_e0 = time.perf_counter()
                        bits = bits_dispatch(words, lid_lane, now)
                        self._stage("layout", t_e0 - t0)
                        self._stage("enqueue", time.perf_counter() - t_e0)
                        item = ("bits", bits, start, cn, None, t0, rec,
                                [words])
                    if rec is not None:
                        rec["dispatch_s"] = round(
                            time.perf_counter() - t_prep, 6)
                # Grow the next chunk toward the wire budget at this chunk's
                # measured bytes/request (skewed streams compact hard in
                # digest mode, so their chunks grow to _RELAY_CHUNK_MAX and
                # the fixed per-dispatch latency amortizes away).
                if split:
                    # Charge the PADDED lanes: that is what actually
                    # ships, and the chunk-growth/election feedback
                    # must see it.
                    wire_b = (3.125 * _bucket_fine(n_singles)
                              + (4.0 + np.dtype(cdt).itemsize)
                              * _bucket_fine(u - n_singles))
                else:
                    wire_b = (digest_bpu * u + 8 * n_delta if digest
                              else words_bpr * cn)
                host_span = time.perf_counter() - t_a0 - t_assign
                with tot["_lock"]:
                    tot["wire"] += wire_b
                    tot["chunks"] += 1
                    tot["host_s"] += host_span
                    tot["cu"].append((int(cn), int(u)))
                    tot["device_s"] += (
                        u * rates["s_per_unique_sorted" if srt
                                  else "s_per_unique_unsorted"]
                        if digest else cn * rates["s_per_lane"])
                    if digest:
                        tot["digest_chunks"] += 1
                        tot["bpu"] = digest_bpu
                    else:
                        tot["bpr"] = words_bpr
                if rec is not None:
                    rec["mode"] = ("split" if split
                                   else "digest" if digest else "bits")
                    rec["wire_bytes"] = int(wire_b)
                    rec["walk_s"] = round(tot["walk_s"], 6)  # cumulative
                    rec["host_s"] = round(host_span, 6)
                    if split:
                        rec["singles"] = int(n_singles)
                if not pipelined:
                    bpr = max(wire_b / cn, 1e-3)
                    budget = (_RELAY_WIRE_BUDGET_DIGEST if digest
                              else _RELAY_WIRE_BUDGET_WORDS)
                    cursor.grow(int(min(max(budget / bpr, _RELAY_CHUNK),
                                        _RELAY_CHUNK_MAX)))
                start += cn
                if start < n:
                    # Prefetch the next chunk's assignment on the worker: it
                    # runs (GIL-free C walk) while this chunk's drain blocks
                    # in its (GIL-free) fetch on the drain pool.
                    fut = self._assign_pool().submit(
                        timed_assign, start, cursor.peek(n - start))
                # Concurrent drain: the fetch cycle of this chunk overlaps
                # the next chunks' walks AND the other in-flight fetches'
                # round trips (ROUND_NOTES r5: serial cycles 688 ms vs
                # concurrent 295 ms for 3 chunks).
                drains.submit(drain, *item)
            drains.finish()  # propagate any drain error before returning
        finally:
            if fut is not None:
                self._abort_prefetch(
                    algo, self._index[algo], fut,
                    lambda res: (res[0] >> np.uint32(rb + 1)).astype(
                        np.int32))
            drains.finish(swallow=True)  # no-op on the normal path
        self._plan_finish(plan_key, plan, pipelined, n, tot, t_pass0)
        return out

    def _stream_weighted(self, algo, lid, assign_uniques, n, permits,
                          index, key_kind="ints") -> np.ndarray:
        """Weighted-permit relay streaming loop.

        Per chunk, one C call assigns slots and hands back the duplicate
        structure (uidx, rank); the host sorts segments by occurrence
        count DESCENDING and lays the permits out rank-major compacted
        (all rank-0 permits, then rank-1, ... — 1 B/request with zero
        padding waste, plus 4 B/unique of words), so each rank step's
        active segments are a PREFIX and the device reads its permits
        with one contiguous ``dynamic_slice``.  A short ``lax.scan``
        over rank steps then runs the exact skip recurrence of the
        sorted flat step.  No sort, no solver, no super-linear compile
        shapes, so chunks grow to the wire budget and pipeline
        three-deep exactly like the unit-permit relay.  A chunk whose
        deepest
        segment exceeds ``_WREL_MAX_R`` (heavy duplication — the scan
        would be long and mostly masked) falls back to sorted flat
        dispatches for that chunk.  Decisions are bit-identical to
        ``_stream_flat`` on the same chunking (tests/test_relay.py)."""
        eng = self.engine
        rb = eng.rank_bits
        cdt = eng.counts_dtype()
        dispatch = (eng.sw_weighted_dispatch if algo == "sw"
                    else eng.tb_weighted_dispatch)
        wc_dispatch = (eng.sw_weighted_counts_dispatch if algo == "sw"
                       else eng.tb_weighted_counts_dispatch)
        flat_dispatch = (eng.sw_flat_dispatch if algo == "sw"
                         else eng.tb_flat_dispatch)
        # The CSR mask needs true counts; the word count field clamps at
        # (1 << rank_bits) - 1, so deeper chunks must fall back.
        r_cap = min(_WREL_MAX_R, (1 << rb) - 1)
        out = np.empty(n, dtype=bool)
        drains = _DrainSet(self._drain_pool())

        def drain(kind, handle, start, count, extra, t0, rec):
            tf0 = time.perf_counter()
            if kind == "weighted_coal":
                # Coalesced digest: per-unique allowed counts; the
                # prefix-allow closed form makes ``rank < counts[uidx]``
                # the exact arrival-order reconstruction (same C helper
                # as the unit-permit digest drain).
                arr = np.ascontiguousarray(np.asarray(handle))
                tf1 = time.perf_counter()
                from ratelimiter_tpu.engine.native_index import relay_decide

                uidx, rank, u = extra
                got = relay_decide(arr[:u], uidx, rank)
            elif kind == "weighted_native":
                arr = np.ascontiguousarray(np.asarray(handle))
                tf1 = time.perf_counter()
                from ratelimiter_tpu.engine.native_index import (
                    weighted_decide,
                )

                roff, spos32, uidx, rank = extra
                got = weighted_decide(arr, roff, spos32, uidx, rank)
            elif kind == "weighted":
                flat_bits = np.unpackbits(np.asarray(handle))
                tf1 = time.perf_counter()
                pos = extra  # roff[rank] + spos per request
                got = flat_bits[pos].astype(bool)
            else:  # flat-fallback slice
                arr = np.asarray(handle)
                tf1 = time.perf_counter()
                got = np.unpackbits(arr)[:count].astype(bool)
            self._stage("fetch", tf1 - tf0)
            out[start:start + count] = got
            dt_us = (time.perf_counter() - t0) * 1e6
            n_allowed = int(got.sum())
            with tot["_lock"]:
                tot["fetch_s"] += tf1 - tf0
                if rec is not None:
                    rec["fetch_s"] = round(
                        rec.get("fetch_s", 0) + (tf1 - tf0), 6)
                    rec["fetch_at"] = [round(tf0 - t_pass0, 6),
                                       round(tf1 - t_pass0, 6)]
                self._record_dispatch(algo, count, n_allowed, dt_us,
                                      path=f"relay_w|{kind}", lid=lid)

        # Chunk plan election — same machinery as _stream_relay (first
        # pass measures at the growth schedule; later passes may run a
        # fixed pipelined split with eager drains).
        plan_key = ("weighted", key_kind, algo,
                    _bucket_fine(n, floor=_RELAY_CHUNK))  # banded, see relay
        plan, pipelined, tot, timed_assign, t_pass0 = self._plan_setup(
            plan_key, assign_uniques)
        rates = self._device_rates()

        cursor = _ChunkCursor(plan, pipelined)
        start = 0
        fut = None  # prefetched next-chunk assignment (holds pins)
        try:
            while start < n:
                cn = cursor.next_size(n - start)
                t_a0 = time.perf_counter()
                if fut is not None:
                    uwords, uidx, rank, clears = fut.result()
                    fut = None
                else:
                    uwords, uidx, rank, clears = timed_assign(start, cn)
                t_assign = time.perf_counter() - t_a0
                u = len(uwords)
                uslots = (uwords >> np.uint32(rb + 1)).astype(np.int32)
                p_chunk = permits[start:start + cn]
                rec = self._stream_rec("relay_w", n=int(cn), u=int(u),
                                       assign_s=t_assign)
                with self._pins_released(index, uslots):
                    if len(clears):
                        self._clear_slots(algo, list(clears))
                    r_max = int(rank.max()) + 1 if cn else 1
                    now = self._monotonic_now()
                    t0 = time.perf_counter()
                    wlane = None
                    if _COALESCE and cdt is not None and cn:
                        # Segment-uniform weight probe: coalescing needs
                        # every repeat of a key to carry the same permits
                        # within the chunk (the closed form consumes
                        # n_allowed * w at once).  One scatter + one
                        # compare over the chunk — cheap next to the scan
                        # it deletes.  Mixed-weight chunks keep the exact
                        # rank-major scan path below, bit-identical either
                        # way.
                        wfirst = np.zeros(max(u, 1), dtype=np.uint8)
                        firsts = rank == 0
                        wfirst[uidx[firsts]] = p_chunk[firsts]
                        if not np.any(wfirst[uidx] != p_chunk):
                            wlane = wfirst
                    if wlane is not None:
                        u_b = _bucket_fine(max(u, 1))
                        uw_pad = _pad_tail(uwords, u_b, 0xFFFFFFFF,
                                           np.uint32)
                        w_pad = _pad_tail(wlane, u_b, 0, np.uint8)
                        handle = wc_dispatch(uw_pad, w_pad, lid, now, cdt)
                        drains.submit(drain, "weighted_coal", handle,
                                      start, cn, (uidx, rank, u), t0, rec)
                        csize = np.dtype(cdt).itemsize
                        wire_b = (5 + csize) * u_b
                        dev_s = u_b * rates["s_per_unique_unsorted"]
                        if rec is not None:
                            rec["mode"] = "weighted_coal"
                            rec["wire_bytes"] = int(wire_b)
                    elif r_max <= r_cap:
                        # Count-descending rank-major layout: segments sorted
                        # by occurrence count so each rank step's active set
                        # is a prefix — permits ship compacted (1 B/request,
                        # zero padding) and the device reads each step with
                        # one contiguous dynamic_slice (ops/relay.py:
                        # _weighted_step_w).  Counts come straight from the
                        # words' count field — unclamped here, since the true
                        # r_max (from the rank scratch) fit under r_cap.
                        # The layout itself is one C pass over structure the
                        # probe walk already produced (rl_weighted_layout,
                        # VERDICT r3 #2); the numpy argsort/bincount/scatter
                        # below is the library-less fallback, bit-identical.
                        from ratelimiter_tpu.engine.native_index import (
                            weighted_layout,
                        )

                        r_b = 2
                        while r_b < r_max:
                            r_b *= 2
                        u_b = _bucket_fine(max(u, 1))
                        uw_pad = np.full(u_b, 0xFFFFFFFF, dtype=np.uint32)
                        spos32 = np.empty(max(u, 1), dtype=np.int32)
                        roff = np.empty(r_b, dtype=np.int64)
                        perms_rank = np.zeros(_bucket_fine(cn) + u_b,
                                              dtype=np.uint8)
                        p64 = np.ascontiguousarray(p_chunk, dtype=np.int64)
                        if weighted_layout(uwords, rb, uidx, rank, p64, r_b,
                                           uw_pad, spos32, roff, perms_rank):
                            handle = dispatch(uw_pad, perms_rank, roff, lid,
                                              now, r_b)
                            drains.submit(
                                drain, "weighted_native", handle, start,
                                cn, (roff, spos32, uidx, rank), t0, rec)
                        else:
                            counts = ((uwords >> np.uint32(1))
                                      & np.uint32((1 << rb) - 1)).astype(
                                          np.int64)
                            order = np.argsort(-counts, kind="stable")
                            spos = np.empty(max(u, 1), dtype=np.int64)
                            spos[order] = np.arange(u, dtype=np.int64)
                            # k_r = number of segments with count > r; roff
                            # is its exclusive prefix sum.
                            hist = np.bincount(counts, minlength=r_b + 1)
                            k_r = u - np.cumsum(hist[:r_b])
                            roff = np.zeros(r_b, dtype=np.int64)
                            np.cumsum(k_r[:-1], out=roff[1:])
                            uw_pad = _pad_tail(uwords[order], u_b, 0xFFFFFFFF,
                                               np.uint32)
                            pos = roff[rank] + spos[uidx]
                            perms_rank[pos] = p_chunk
                            handle = dispatch(uw_pad, perms_rank, roff, lid,
                                              now, r_b)
                            drains.submit(drain, "weighted", handle, start,
                                          cn, pos, t0, rec)
                        wire_b = (4 * u_b + len(perms_rank)
                                  + len(perms_rank) // 8)
                        dev_s = cn * rates["s_per_lane"]  # scan ~ lanes
                        if rec is not None:
                            rec["mode"] = "weighted"
                            rec["wire_bytes"] = int(wire_b)
                    else:
                        # Heavy duplication: sorted flat dispatches for this
                        # chunk (<= _FLAT_MAX_LANES lanes each, as the sort
                        # compile ceiling requires).
                        slots_req = uslots[uidx]
                        for off in range(0, cn, _FLAT_MAX_LANES):
                            sl = min(_FLAT_MAX_LANES, cn - off)
                            size = _bucket_pow2(sl)
                            s_pad = _pad_tail(slots_req[off:off + sl], size,
                                              -1, np.int32)
                            p_pad = _pad_tail(p_chunk[off:off + sl], size, 1,
                                              np.uint8)
                            bits = flat_dispatch(s_pad, lid, p_pad, now)
                            drains.submit(drain, "flat", bits, start + off,
                                          sl, None, t0, rec)
                        wire_b = 5.0 * cn
                        dev_s = cn * rates["s_per_lane"]
                        if rec is not None:
                            rec["mode"] = "flat_fb"
                            rec["wire_bytes"] = int(wire_b)
                host_span = time.perf_counter() - t_a0 - t_assign
                with tot["_lock"]:
                    tot["wire"] += wire_b
                    tot["chunks"] += 1
                    tot["host_s"] += host_span
                    tot["cu"].append((int(cn), int(u)))
                    tot["bpr"] = wire_b / max(cn, 1)
                    tot["device_s"] += dev_s
                if rec is not None:
                    rec["walk_s"] = round(tot["walk_s"], 6)  # cumulative
                    rec["host_s"] = round(host_span, 6)
                if not pipelined:
                    bpr = max(wire_b / cn, 1e-3)
                    cursor.grow(int(min(
                        max(_RELAY_WIRE_BUDGET_WEIGHTED / bpr,
                            _RELAY_CHUNK), _RELAY_CHUNK_MAX)))
                start += cn
                if start < n:
                    # Prefetch the next chunk's assignment (see _stream_relay).
                    fut = self._assign_pool().submit(
                        timed_assign, start, cursor.peek(n - start))
            drains.finish()  # propagate any drain error before returning
        finally:
            if fut is not None:
                self._abort_prefetch(
                    algo, index, fut,
                    lambda res: (res[0] >> np.uint32(rb + 1)).astype(
                        np.int32))
            drains.finish(swallow=True)  # no-op on the normal path
        self._plan_finish(plan_key, plan, pipelined, n, tot, t_pass0)
        return out

    def _stream_flat(self, algo, lid, assign, n, permits, oversize,
                     batch, subbatches, lid_arr=None) -> np.ndarray:
        """Common flat-streaming loop: per super-batch, one host slot
        assignment (``assign(start, count) -> (slots, clears)``), one FLAT
        device dispatch (ops/flat.py — every request in a dispatch shares
        its timestamp, so the flat sorted batch decides identically to
        ``subbatches`` sequential scan steps), and a pipelined bitmask
        fetch that overlaps the next super-batch's indexing + dispatch.

        The sorted step's lane count is capped at ``_FLAT_MAX_LANES``:
        its sort/scan ops have XLA:TPU compile times that grow
        super-linearly with lane count (~30 s at 512K lanes, ~4 min at
        2M, unusable at 4M — bench/profile_compile.py).  A super-batch
        larger than the cap dispatches as ONE ``lax.scan`` of
        cap-sized sub-batches instead (ops/packed.py) — same sorted step
        compiled once at the cap, but a single dispatch + fetch round
        trip per super-batch, which measures ~1.6x faster than chaining
        capped flat dispatches on the dev tunnel."""
        multi_lid = lid_arr is not None
        super_n = int(subbatches) * int(batch)
        k_scan = 0
        if super_n > _FLAT_MAX_LANES:
            # Bounded by the stream length: a short stream must not pad
            # up to the requested super-batch's worth of dead lanes.
            k_scan = min(-(-super_n // _FLAT_MAX_LANES),
                         max(-(-n // _FLAT_MAX_LANES), 1))
            super_n = k_scan * _FLAT_MAX_LANES
            if k_scan == 1:
                k_scan = 0  # plain flat dispatch at the cap
        eng = self.engine
        if k_scan:
            dispatch = (eng.sw_scan_dispatch if algo == "sw"
                        else eng.tb_scan_dispatch)
        else:
            dispatch = (eng.sw_flat_dispatch if algo == "sw"
                        else eng.tb_flat_dispatch)
        def clear(slots):
            self._clear_slots(algo, slots)
        # When every permit in the stream fits a byte (the common case —
        # permits above max_permits are pointless), the permits lane ships
        # as uint8: 5 B/request on the wire instead of 8.  The device step
        # upcasts, decisions unchanged.
        p_dtype = np.int32
        if (permits is not None and permits.size
                and int(permits.min()) >= 0 and int(permits.max()) <= 255):
            p_dtype = np.uint8

        out = np.empty(n, dtype=bool)
        drains = _DrainSet(self._drain_pool())
        rec_lock = threading.Lock()

        def drain(handle, start, count, t0, rec):
            tf0 = time.perf_counter()
            arr = np.asarray(handle)  # the one blocking fetch
            tf1 = time.perf_counter()
            dt_us = (tf1 - t0) * 1e6
            self._stage("fetch", tf1 - tf0)
            if k_scan:  # uint8[k, cap//8]
                got = np.unpackbits(arr, axis=1).reshape(-1)[:count]
                got = got.astype(bool)
            else:  # uint8[super_n//8]
                got = np.unpackbits(arr)[:count].astype(bool)
            out[start:start + count] = got
            n_allowed = int(got.sum())
            with rec_lock:
                if rec is not None:
                    rec["fetch_s"] = round(tf1 - tf0, 6)
                self._record_dispatch(algo, count, n_allowed, dt_us,
                                      path="flat|scan" if k_scan
                                      else "flat|sorted",
                                      lid=None if multi_lid else lid)

        fut = None  # prefetched next-chunk assignment (holds pins)
        try:
            for start in range(0, n, super_n):
                cn = min(super_n, n - start)
                # The tail super-batch shrinks to its own sub-batch count so a
                # partial chunk doesn't ship k_scan's worth of padding lanes.
                k_i = (min(k_scan, -(-cn // _FLAT_MAX_LANES)) if k_scan else 0)
                pad_n = k_i * _FLAT_MAX_LANES if k_i else super_n
                t_a0 = time.perf_counter()
                if fut is not None:
                    slots, clears = fut.result()
                    fut = None
                else:
                    slots, clears = assign(start, cn)
                t_assign = time.perf_counter() - t_a0
                lanes = 4 + (np.dtype(p_dtype).itemsize
                             if permits is not None else 0) + (
                    4 if multi_lid else 0)
                rec = self._stream_rec(
                    "flat", mode="scan" if k_i else "flat", n=int(cn),
                    assign_s=t_assign, wire_bytes=int(pad_n * lanes))
                raw_slots = slots
                with self._pins_released(self._index[algo], raw_slots):
                    if len(clears):
                        clear(list(clears))
                    slots = _pad_tail(slots, pad_n, -1, np.int32)
                    if oversize is not None:
                        slots[:cn][oversize[start:start + cn]] = -1  # deny
                    lid_flat = lid if not multi_lid else _pad_tail(
                        lid_arr[start:start + cn], pad_n, 0, np.int32)
                    p_flat = None if permits is None else _pad_tail(
                        permits[start:start + cn], pad_n, 1, p_dtype)
                    now = self._monotonic_now()
                    t0 = time.perf_counter()
                    if k_i:
                        bits = dispatch(
                            slots.reshape(k_i, _FLAT_MAX_LANES),
                            lid_flat if not multi_lid
                            else lid_flat.reshape(k_i, _FLAT_MAX_LANES),
                            None if p_flat is None
                            else p_flat.reshape(k_i, _FLAT_MAX_LANES),
                            np.full(k_i, now, dtype=np.int64))
                    else:
                        bits = dispatch(slots, lid_flat, p_flat, now)
                    self._stage("index", t_assign)
                    self._stage("enqueue", time.perf_counter() - t0)
                if rec is not None:
                    rec["host_s"] = round(time.perf_counter() - t_a0 - t_assign,
                                          6)
                nxt = start + super_n
                if nxt < n:
                    # Prefetch the next super-batch's assignment (see
                    # _stream_relay).
                    fut = self._assign_pool().submit(
                        assign, nxt, min(super_n, n - nxt))
                # Concurrent drain (see _stream_relay): the fetch cycle
                # overlaps later super-batches' walks and fetches.
                drains.submit(drain, bits, start, cn, t0, rec)
            drains.finish()  # propagate any drain error before returning
        finally:
            if fut is not None:
                self._abort_prefetch(
                    algo, self._index[algo], fut,
                    lambda res: np.asarray(res[0], dtype=np.int32))
            drains.finish(swallow=True)  # no-op on the normal path
        return out

    def acquire_stream_strs(
        self,
        algo: str,
        lid: int,
        keys: Sequence[str],
        permits: np.ndarray | None = None,
        *,
        batch: int = 1 << 14,
        subbatches: int = 4,
    ) -> np.ndarray:
        """Whole-stream STRING-key decisions, pipelined — the end-to-end
        analog of :meth:`acquire_stream_ids` (VERDICT r1 #3).

        Per super-batch: one C call hashes+assigns the whole key chunk
        (``assign_batch_strs``), one flat device dispatch decides it, and
        the bit-packed fetch overlaps the next chunk's host work — so the
        Python/ctypes string handling rides in the fetch shadow instead of
        serializing with it.  Decisions are identical to ``acquire_many``
        on the same chunks (same index namespace, same kernels).  Returns
        bool[n] allowed.
        """
        self._check_not_promoting()
        if self._fenced_shards:
            self._check_fence_keys([lid] * len(keys), keys)
        index = self._index[algo]
        oversize = None
        if permits is not None:
            permits = np.asarray(permits)
            if permits.size and int(permits.min(initial=0)) < np.iinfo(
                    np.int32).min:
                raise ValueError("permits below int32 range")
            over = permits > np.iinfo(np.int32).max
            if over.any():
                oversize = over
        if (hasattr(index, "_sub")
                and getattr(index, "supports_batch_strs", False)
                and permits is None
                and hasattr(self.engine, "relay_usable")
                and self.engine.relay_usable()):
            # Sharded engine, string keys (r6): hash each chunk ONCE
            # (fingerprints straight off the UTF-8 buffers), route by
            # h1 — the same quantity shard_of_key's string branch
            # computes, so scalar and stream traffic agree on every
            # key's shard — and run the shard-parallel pipelined relay.
            self._batcher.flush()
            return self._stream_relay_sharded(
                algo, lid, keys if isinstance(keys, list) else list(keys),
                index, False, None, key_kind="strs")
        if not hasattr(index, "assign_batch_strs"):
            # Python-index / sharded fallback: chunked batch path, same
            # decisions (no pipelining).
            n = len(keys)
            out = np.empty(n, dtype=bool)
            for i in range(0, n, batch):
                chunk = list(keys[i:i + batch])
                p = ([1] * len(chunk) if permits is None
                     else list(permits[i:i + batch]))
                res = self.acquire_many(algo, [lid] * len(chunk), chunk, p)
                out[i:i + len(chunk)] = res["allowed"]
            return out

        self._batcher.flush()
        if oversize is not None:
            permits = np.where(oversize, 1, permits)

        # Chunking passes a WINDOW (start, count) into the whole key
        # sequence — the index hashes straight out of it (zero per-key
        # Python objects on the list fast path; the r5 loop copied a
        # fresh list slice per chunk).
        if (permits is not None and oversize is None
                and hasattr(index, "assign_batch_strs_uniques")
                and permits.size
                and int(permits.min()) >= 1
                and int(permits.max()) <= self.engine.weighted_permit_cap):
            # Weighted relay for string keys — same loop as the int path,
            # only the assign closure differs (see acquire_stream_ids).
            rb = self.engine.rank_bits

            def assign_uniques_w(start, chunk_n):
                with self._evictions_cleared(algo):
                    return index.assign_batch_strs_uniques(
                        keys, lid, rb,
                        pinned=self._batcher.pending_slots(algo),
                        hold_pins=True, start=start, count=chunk_n)

            return self._stream_weighted(
                algo, lid, assign_uniques_w, len(keys),
                np.ascontiguousarray(permits, dtype=np.int64), index,
                key_kind="strs")

        if (permits is None
                and hasattr(index, "assign_batch_strs_uniques")
                and self.engine.relay_usable()):
            rb = self.engine.rank_bits

            def assign_uniques(start, chunk_n):
                with self._evictions_cleared(algo):
                    return index.assign_batch_strs_uniques(
                        keys, lid, rb,
                        pinned=self._batcher.pending_slots(algo),
                        hold_pins=True, start=start, count=chunk_n)

            return self._stream_relay(algo, lid, assign_uniques, len(keys),
                                      key_kind="strs")

        def assign(start, chunk_n):
            with self._evictions_cleared(algo):
                return index.assign_batch_strs(
                    keys, lid,
                    pinned=self._batcher.pending_slots(algo),
                    hold_pins=True, start=start, count=chunk_n)

        return self._stream_flat(algo, lid, assign, len(keys), permits,
                                 oversize, batch, subbatches)

    def _stream_sharded(self, algo, lid, key_ids, permits, batch, subbatches,
                        index, multi_lid, lid_arr,
                        oversize=None) -> np.ndarray:
        """Sharded-engine streaming: per-super-batch host routing (key ->
        shard by the deterministic splitmix hash), per-shard native slot
        assignment, one shard_map'd FLAT dispatch (ops/flat.py — the
        sub-batch dimension is gone: all requests in a dispatch share its
        timestamp, so each shard decides its whole slice as one sorted
        batch), pipelined bitmask fetch.  Decisions are identical to the
        flat single-device stream on the same per-key request order."""
        eng = self.engine
        if (permits is None and hasattr(eng, "relay_usable")
                and eng.relay_usable()
                and all(hasattr(s, "assign_batch_ints_uniques")
                        for s in index._sub)):
            return self._stream_relay_sharded(algo, lid, key_ids, index,
                                              multi_lid, lid_arr)
        if oversize is not None:
            permits = np.where(oversize, 1, permits)  # lanes masked; the
            # oversized requests dispatch as padding (slot -1) below.
        n_sh, sps = eng.n_shards, eng.slots_per_shard
        # Same per-dispatch lane cap as _stream_flat: the per-shard slice
        # is what the sorted step compiles over, and _bucket rounds the
        # busiest shard's count up to a power of two, so budget half the
        # single-device lanes per shard to keep the bucketed b_loc at or
        # under _FLAT_MAX_LANES even with hash imbalance.
        super_n = min(int(subbatches) * int(batch),
                      (_FLAT_MAX_LANES // 2) * n_sh)
        dispatch = (eng.sw_flat_sharded_dispatch if algo == "sw"
                    else eng.tb_flat_sharded_dispatch)
        def clear(slots):
            self._clear_slots(algo, slots)
        n = len(key_ids)
        out = np.empty(n, dtype=bool)
        drains = _DrainSet(self._drain_pool())
        rec_lock = threading.Lock()

        def drain(handle, start, cnt, shard, cols, b_loc, t0):
            arr = np.asarray(handle)  # uint8[n_sh, b_loc//8]
            dt_us = (time.perf_counter() - t0) * 1e6
            bits = np.unpackbits(arr, axis=1)[:, :b_loc].astype(bool)
            got = bits[shard, cols]
            out[start:start + cnt] = got
            n_allowed = int(got.sum())
            with rec_lock:
                self._record_dispatch(algo, cnt, n_allowed, dt_us,
                                      path="sharded|flat",
                                      lid=None if multi_lid else lid)

        pool = self._shard_pool(n_sh)
        try:
            for start in range(0, n, super_n):
                self._stream_sharded_chunk(
                    algo, lid, key_ids, permits, oversize, index, multi_lid,
                    lid_arr, start, super_n, n_sh, sps, pool, dispatch,
                    clear, drains, drain)
            drains.finish()
        finally:
            drains.finish(swallow=True)  # no-op on the normal path
        return out

    def _stream_sharded_chunk(self, algo, lid, key_ids, permits, oversize,
                              index, multi_lid, lid_arr, start, super_n,
                              n_sh, sps, pool, dispatch, clear, drains,
                              drain) -> None:
        """One super-batch of the sharded FLAT stream (split out so the
        loop in :meth:`_stream_sharded` can wrap drain lifetime cleanly)."""
        chunk = key_ids[start:start + super_n]
        cn = len(chunk)
        clears: list = []
        pins_by_shard: dict = {}
        for g in self._batcher.pending_slots(algo):
            pins_by_shard.setdefault(g // sps, set()).add(g % sps)
        l_chunk = lid_arr[start:start + cn] if multi_lid else None
        # One routing pass (see _stream_relay_sharded); per-shard C
        # calls run on the pool against contiguous slices.
        shard, order, counts = _route_chunk(chunk, n_sh)
        offs = np.zeros(n_sh + 1, dtype=np.int64)
        np.cumsum(counts, out=offs[1:])
        kst = chunk[order]
        l_st = l_chunk[order] if multi_lid else None

        def assign_shard(s):
            lo, hi = int(offs[s]), int(offs[s + 1])
            if lo == hi:
                return None
            sub = index._sub[s]
            if multi_lid:
                return sub.assign_batch_ints_multi(
                    kst[lo:hi], l_st[lo:hi],
                    pinned=pins_by_shard.get(s), hold_pins=True)
            return sub.assign_batch_ints(
                kst[lo:hi], lid, pinned=pins_by_shard.get(s),
                hold_pins=True)

        # Pins of successful shards accumulate in held as results are
        # collected; the finally releases them on ANY raise (a leaked
        # pin would make its slot permanently unevictable).
        local_sorted = np.empty(cn, dtype=np.int32)
        held: list = []
        try:
            futs = [pool.submit(assign_shard, s) for s in range(n_sh)]
            err = None
            for s, f in enumerate(futs):
                try:
                    r = f.result()
                except Exception as exc:  # noqa: BLE001
                    err = err if err is not None else exc
                    # Partial-failure lanes still evicted: globalize
                    # into the pooled clears, cleared below.
                    clears.extend(consume_pending_clears(exc, s * sps))
                    continue
                if r is None:
                    continue
                sl, ev = r
                local_sorted[offs[s]:offs[s + 1]] = sl
                held.append(s * sps + sl.astype(np.int64))
                clears.extend(s * sps + int(e) for e in ev)
            if err is not None:
                # Successful shards' assignments are already in the
                # index: their evicted slots must be zeroed even
                # though no dispatch happens (ADVICE r3).
                if clears:
                    clear(clears)
                raise err
            if clears:
                clear(clears)
            local = np.empty(cn, dtype=np.int32)
            local[order] = local_sorted
            # Column of each request within its shard row (arrival
            # order — the stable per-slot segment order the flat step
            # sorts by).
            cols = np.empty(cn, dtype=np.int64)
            cols[order] = np.arange(cn) - offs[shard[order]]
            from ratelimiter_tpu.parallel.sharded import _bucket

            b_loc = _bucket(int(counts.max(initial=1)))
            slots_mat = np.full((n_sh, b_loc), -1, dtype=np.int32)
            slots_mat[shard, cols] = local
            if oversize is not None:
                ov = oversize[start:start + cn]
                slots_mat[shard[ov], cols[ov]] = -1  # force-deny
            lid_sb = lid
            if multi_lid:
                lid_mat = np.zeros((n_sh, b_loc), dtype=np.int32)
                lid_mat[shard, cols] = l_chunk
                lid_sb = lid_mat
            p_sb = None
            if permits is not None:
                p_mat = np.ones((n_sh, b_loc), dtype=np.int32)
                p_mat[shard, cols] = permits[start:start + cn]
                p_sb = p_mat
            now = self._monotonic_now()
            t0 = time.perf_counter()
            bits = dispatch(slots_mat, lid_sb, p_sb, now)
        finally:
            self._unpin_held(index, held)
        # Concurrent drain (see _stream_relay): fetch cycles overlap.
        drains.submit(drain, bits, start, cn, shard, cols, b_loc, t0)

    def _stream_relay_sharded(self, algo, lid, key_ids, index, multi_lid,
                              lid_arr, key_kind="ints") -> np.ndarray:
        """Sharded relay streaming over fully independent per-shard
        pipelines (r8; ROADMAP item 1).

        Per chunk the main thread does ONE routing pass (host C router
        or the on-mesh route-and-count pass, whichever the measured
        election picked — :meth:`_route_sharded`) and hands each shard
        its contiguous slice; from there everything is per-shard: slot
        assignment, eviction clears, layout into the lane's own staging
        buffer, a SINGLE-DEVICE dispatch on the shard's own device
        (``ShardedDeviceEngine.relay_shard_dispatch``), and a bounded
        per-lane drain queue.  There is no cross-shard barrier anywhere;
        the only ordering constraint is per-shard stream order, enforced
        by each lane's FIFO worker — which is also the clear path: a
        shard's eviction clears enter its device stream ahead of the
        dispatch that reuses those slots, and a key never migrates
        shards, so nothing else needs ordering.

        The r6/r7 loop instead barriered every chunk into one mesh-wide
        shard_map dispatch: every shard waited for the slowest sibling's
        layout, the multi-device launch rendezvoused all devices, and
        the lane padding followed the busiest shard — BENCH_r05 measured
        the result anti-scaling 19.5M -> 4.3M decisions/s from 1 -> 8
        shards on the CPU mesh.

        Mode (digest vs words) is elected PER SHARD from that shard's
        own dedup ratio; every dispatch records its route as
        ``sharded|digest`` / ``sharded|words`` with its shard id in the
        decision trace and latency histograms, per-shard stage seconds
        feed the ``ratelimiter.stream.*`` timers (``route`` is the new
        binning stage), and a lane whose drain queue blocks flags
        ``shard.drain_saturated`` to the flight recorder.  Decisions are
        bit-identical to the r7 loop and to the flat single-device
        oracle on the same per-key request order (per-key order is
        per-shard order)."""
        from ratelimiter_tpu.engine.native_index import (
            hash_str_keys,
            relay_decide_pos,
            rebuild_words_into,
        )
        from ratelimiter_tpu.ops.relay import rebuild_words, wire_costs
        from ratelimiter_tpu.parallel.sharded import _bucket

        eng = self.engine
        n_sh, sps = eng.n_shards, eng.slots_per_shard
        rb = eng.rank_bits
        cdt = eng.counts_dtype()
        digest_bpu, words_bpr = wire_costs(multi_lid)
        n = len(key_ids)
        out = np.empty(n, dtype=bool)
        if n == 0:
            return out
        lanes = self._shard_lanes(n_sh)
        stop = threading.Event()
        errors: list = []  # (chunk_i, shard, exc); first in stream order wins
        err_lock = threading.Lock()

        def fail(ci, s, exc):
            with err_lock:
                errors.append((ci, s, exc))
            stop.set()

        def shard_task(ci, s, start, now, keys_s, h1_s, h2_s, pos_s, l_s,
                       pins_s, ctx):
            """Everything one shard does for one chunk, on its lane's
            FIFO worker.  Never raises: failures land in ``errors`` and
            set ``stop`` (sibling lanes stop dispatching; evictions an
            already-applied assignment made are still cleared)."""
            if stop.is_set():
                return
            lane = lanes[s]
            sub = index._sub[s]
            ns = len(pos_s)
            buf = None
            pinned_local = None
            dispatched = False
            try:
                tw0 = time.perf_counter()
                try:
                    if key_kind != "ints":
                        uw, uidx, rank, ev = sub.assign_batch_fps_uniques(
                            h1_s, h2_s, rb, pinned=pins_s, hold_pins=True)
                    elif multi_lid:
                        uw, uidx, rank, ev = (
                            sub.assign_batch_ints_multi_uniques(
                                keys_s, l_s, rb, pinned=pins_s,
                                hold_pins=True))
                    else:
                        uw, uidx, rank, ev = sub.assign_batch_ints_uniques(
                            keys_s, lid, rb, pinned=pins_s, hold_pins=True)
                except Exception as exc:  # noqa: BLE001
                    # Lanes that assigned before the failure are already
                    # remapped in the index: their evicted slots must be
                    # zeroed even though nothing dispatches (ADVICE r3).
                    pc = consume_pending_clears(exc, 0)
                    if len(pc):
                        self._clear_shard(algo, s, pc)
                    raise
                walk_s = time.perf_counter() - tw0
                ctx["walk"][s] = walk_s
                self._stage("index", walk_s)
                if len(ev):
                    # Stream-order clear path: this lane is a FIFO, so
                    # the clear precedes this chunk's dispatch in this
                    # shard's device stream.
                    self._clear_shard(algo, s, ev)
                u = len(uw)
                ctx["u"][s] = u
                pinned_local = (uw >> np.uint32(rb + 1)).astype(np.int32)
                t_l0 = time.perf_counter()
                digest = (cdt is not None
                          and digest_bpu * _bucket(max(u, 1))
                          <= words_bpr * ns)
                if digest:
                    u_pad = _bucket(max(u, 1))
                    buf = lane.staging.take((u_pad,), np.uint32)
                    buf[:u] = uw
                    buf[u:] = 0xFFFFFFFF
                    lid_lane = lid
                    if multi_lid:
                        first = rank == 0
                        ulids = np.zeros(u_pad, dtype=np.int32)
                        ulids[uidx[first]] = l_s[first]
                        lid_lane = ulids
                    ctx["wire"][s] = digest_bpu * u
                else:
                    b_pad = _bucket(max(ns, 1))
                    buf = lane.staging.take((b_pad,), np.uint32)
                    if not rebuild_words_into(uw, uidx, rank, rb,
                                              buf[:ns]):
                        buf[:ns] = rebuild_words(uw, uidx, rank, rb)
                    buf[ns:] = 0xFFFFFFFF
                    lid_lane = lid
                    if multi_lid:
                        lid_lane = np.zeros(b_pad, dtype=np.int32)
                        lid_lane[:ns] = l_s
                    ctx["wire"][s] = words_bpr * ns
                mode = "digest" if digest else "words"
                ctx["modes"][s] = mode
                layout_s = time.perf_counter() - t_l0
                ctx["layout"][s] = layout_s
                self._stage("layout", layout_s)
                if stop.is_set():  # a sibling failed after our assign
                    return
                t0 = time.perf_counter()
                if digest:
                    handle = eng.relay_shard_dispatch(
                        algo, s, "counts", buf, lid_lane, now, cdt)
                else:
                    handle = eng.relay_shard_dispatch(
                        algo, s, "bits", buf, lid_lane, now)
                dispatched = True
                enq_s = time.perf_counter() - t0
                ctx["enq"][s] = enq_s
                self._stage("enqueue", enq_s)
            except Exception as exc:  # noqa: BLE001
                fail(ci, s, exc)
                return
            finally:
                # Pins release once the dispatch entered the shard's
                # stream (or on any failure) — see _pins_released.
                if pinned_local is not None and hasattr(sub, "unpin_batch"):
                    sub.unpin_batch(pinned_local)
                if not dispatched and buf is not None:
                    lane.staging.give(buf)

            def drain(handle=handle, mode=mode, buf=buf, u=u, uidx=uidx,
                      rank=rank, pos_s=pos_s, ns=ns, s=s, start=start,
                      t0=t0, ctx=ctx):
                try:
                    tf0 = time.perf_counter()
                    arr = np.asarray(handle)
                    tf1 = time.perf_counter()
                    self._stage("fetch", tf1 - tf0)
                    if mode == "digest":
                        # Fused reconstruct + unscatter straight into the
                        # output suffix (one C pass).
                        alw = relay_decide_pos(arr[:u], uidx, rank, pos_s,
                                               out[start:])
                    else:
                        bits = np.unpackbits(arr)[:ns].astype(bool)
                        out[start + pos_s] = bits
                        alw = int(bits.sum())
                    rec = ctx["rec"]
                    if rec is not None:
                        with ctx["lock"]:
                            rec["fetch_s"] = round(
                                max(rec.get("fetch_s", 0.0), tf1 - tf0), 6)
                    self._record_dispatch(algo, ns, int(alw),
                                          (tf1 - t0) * 1e6,
                                          path=f"sharded|{mode}", shard=s,
                                          lid=None if multi_lid else lid)
                finally:
                    lane.staging.give(buf)

            lane.drains.submit(drain)

        # Chunk sizing: learned steady-state size per stream shape (the
        # single-device election machinery stays unused here — the
        # lanes' host work is already off the critical path, so giant
        # chunks win).
        plan_key = ("relay_sharded", key_kind, algo, bool(multi_lid),
                    _bucket_fine(n, floor=_RELAY_CHUNK))
        plan = self._chunk_plans.get(plan_key)
        chunk = (int(plan["chunk"]) if plan and plan.get("chunk")
                 else _RELAY_CHUNK)
        inflight: list = []
        ci = 0
        start = 0

        def finalize(ctx):
            """Join one chunk's shard tasks, fold its per-shard seconds
            into the chunk record, and re-learn the chunk size from its
            measured bytes/request."""
            nonlocal chunk
            for f in ctx["futs"]:
                f.result()  # tasks never raise; surfaces executor faults
            wire_b = float(ctx["wire"].sum())
            rec = ctx["rec"]
            if rec is not None:
                modes = [m for m in ctx["modes"] if m]
                with ctx["lock"]:
                    rec.update(
                        u=int(ctx["u"].sum()),
                        mode=(modes[0] if len(set(modes)) == 1
                              else "mixed"),
                        wire_bytes=int(wire_b),
                        route_s=round(float(ctx["route_s"]), 6),
                        assign_s=round(float(ctx["walk"].max()), 6),
                        shard_walk_s=[round(float(x), 6)
                                      for x in ctx["walk"]],
                        shard_n=[int(x) for x in ctx["shard_n"]],
                        layout_s=round(float(ctx["layout"].sum()), 6),
                        dispatch_s=round(float(ctx["enq"].sum()), 6),
                        host_s=round(float(ctx["route_s"])
                                     + float(ctx["layout"].sum())
                                     + float(ctx["enq"].sum()), 6),
                    )
                    if ctx["pack_s"]:
                        rec["pack_s"] = round(ctx["pack_s"], 6)
            if wire_b > 0 and ctx["cn"]:
                bpr = max(wire_b / ctx["cn"], 1e-3)
                digesty = sum(1 for m in ctx["modes"] if m == "digest")
                mody = max(sum(1 for m in ctx["modes"] if m), 1)
                budget = (_RELAY_WIRE_BUDGET_DIGEST
                          if 2 * digesty >= mody
                          else _RELAY_WIRE_BUDGET_WORDS)
                chunk = int(min(max(budget / bpr, _RELAY_CHUNK),
                                _RELAY_CHUNK_MAX))

        try:
            while start < n and not stop.is_set():
                cn = min(chunk, n - start)
                t_r0 = time.perf_counter()
                pack_s = 0.0
                h1st = h2st = kst = None
                if key_kind == "ints":
                    kchunk = key_ids[start:start + cn]
                    shard, order, counts, kst = self._route_sharded(
                        eng, kchunk=kchunk)
                else:
                    t_p0 = time.perf_counter()
                    fp = hash_str_keys(key_ids, lid, start, cn)
                    if fp is None:
                        raise RuntimeError(
                            "native string hashing unavailable mid-stream "
                            "(mutated key list?)")
                    pack_s = time.perf_counter() - t_p0
                    self._stage("pack", pack_s)
                    shard, order, counts, h1st, h2st = self._route_sharded(
                        eng, h1=fp[0], h2=fp[1])
                route_s = time.perf_counter() - t_r0 - pack_s
                self._stage("route", route_s)
                offs = np.zeros(n_sh + 1, dtype=np.int64)
                np.cumsum(counts, out=offs[1:])
                l_chunk = lid_arr[start:start + cn] if multi_lid else None
                pins = self._batcher.pending_slots_sharded(algo, sps)
                now = self._monotonic_now()
                rec = self._stream_rec("relay_sharded", n=int(cn))
                ctx = {
                    "cn": cn, "rec": rec, "lock": threading.Lock(),
                    "walk": np.zeros(n_sh), "layout": np.zeros(n_sh),
                    "enq": np.zeros(n_sh), "wire": np.zeros(n_sh),
                    "u": np.zeros(n_sh, np.int64),
                    "modes": [None] * n_sh, "shard_n": counts,
                    "route_s": route_s, "pack_s": pack_s, "futs": [],
                }
                for s in range(n_sh):
                    lo, hi = int(offs[s]), int(offs[s + 1])
                    if lo == hi:
                        continue
                    pos_s = order[lo:hi]
                    ctx["futs"].append(lanes[s].pipe.submit(
                        shard_task, ci, s, start, now,
                        kst[lo:hi] if kst is not None else None,
                        h1st[lo:hi] if h1st is not None else None,
                        h2st[lo:hi] if h2st is not None else None,
                        pos_s,
                        l_chunk[pos_s] if multi_lid else None,
                        pins.get(s), ctx))
                inflight.append(ctx)
                start += cn
                ci += 1
                # Bounded look-ahead: route at most _SHARD_LOOKAHEAD
                # chunks beyond the oldest still-assembling one (bounds
                # staging memory and the learned-size feedback lag).
                while len(inflight) > _SHARD_LOOKAHEAD:
                    finalize(inflight.pop(0))
            while inflight:
                finalize(inflight.pop(0))
            if not stop.is_set():
                for lane in lanes:
                    lane.drains.finish()
        finally:
            while inflight:
                try:
                    finalize(inflight.pop(0))
                except Exception:  # noqa: BLE001 — primary error wins
                    pass
            for lane in lanes:
                lane.drains.finish(swallow=True)  # no-op when healthy
        if errors:
            errors.sort(key=lambda e: (e[0], e[1]))
            raise errors[0][2]
        self._chunk_plans[plan_key] = {"kind": "giant", "chunk": chunk,
                                       "passes": 3}
        return out

    def _route_sharded(self, eng, kchunk=None, h1=None, h2=None):
        """One chunk's shard routing: ``(shard, order, counts, gathered
        keys)`` for int traffic, ``(..., h1_sorted, h2_sorted)`` for
        string traffic.  Host C router (``rl_shard_route2`` /
        ``rl_route_hashes2``) vs the on-mesh route-and-count pass
        (parallel/sharded.py:build_route_count) is a MEASURED election —
        ``RATELIMITER_DEVICE_ROUTE=on|off|auto`` (auto A/Bs both once
        per storage on the first large chunk and reports the verdict to
        the flight recorder).  On a CPU container the host pass wins
        (the "device" shares the core); on a real slice the device does
        the O(n) binning where the mesh is real, and the losing pass
        never serves."""
        ints = h1 is None
        n = len(kchunk) if ints else len(h1)
        mode = self._route_mode
        if mode is None:
            env = os.environ.get("RATELIMITER_DEVICE_ROUTE",
                                 "auto").lower()
            if env in ("1", "on", "device"):
                mode = self._route_mode = "device"
            elif env in ("0", "off", "host"):
                mode = self._route_mode = "host"
            elif n < (1 << 16):
                mode = "host"  # too small to measure; not sticky
            else:
                t0 = time.perf_counter()
                host = self._route_host(kchunk, h1, h2, eng.n_shards)
                host_s = time.perf_counter() - t0
                # Warm the device pass (compile + first transfer) so the
                # election compares steady-state costs, not a one-time
                # jit — the service pays the compile once per geometry.
                (eng.route_on_device(key_ids=kchunk) if ints
                 else eng.route_on_device(hashes=h1))
                t0 = time.perf_counter()
                dev = (eng.route_on_device(key_ids=kchunk) if ints
                       else eng.route_on_device(hashes=h1))
                # Charge the device side the gather the host router
                # fuses in (the per-shard slices need sorted keys).
                _ = kchunk[dev[1]] if ints else h1[dev[1]]
                dev_s = time.perf_counter() - t0
                self._route_mode = ("device" if dev_s < host_s
                                    else "host")
                if self._recorder is not None:
                    self._recorder.record(
                        "sharded.route_elect",
                        host_s=round(host_s, 6),
                        device_s=round(dev_s, 6),
                        elected=self._route_mode, n=int(n))
                return host
        if mode == "device":
            if ints:
                shard, order, counts = eng.route_on_device(key_ids=kchunk)
                return shard, order, counts, kchunk[order]
            shard, order, counts = eng.route_on_device(hashes=h1)
            return shard, order, counts, h1[order], h2[order]
        return self._route_host(kchunk, h1, h2, eng.n_shards)

    @staticmethod
    def _route_host(kchunk, h1, h2, n_sh):
        if h1 is None:
            from ratelimiter_tpu.engine.native_index import (
                shard_route_gather,
            )

            r2 = shard_route_gather(kchunk, n_sh)
            if r2 is not None:  # fused route+gather, one C pass
                return r2
            shard, order, counts = _route_chunk(kchunk, n_sh)
            return shard, order, counts, kchunk[order]
        from ratelimiter_tpu.engine.native_index import route_hashes_gather

        return route_hashes_gather(h1, h2, n_sh)

    def _clear_shard(self, algo: str, s: int, local_slots) -> None:
        """Per-shard eviction clears (r8): zero LOCAL slots in shard
        ``s``'s own device stream (``ShardedDeviceEngine.clear_shard``).
        Mirrors :meth:`_clear_slots`' resident-lid invalidation — the
        sharded digest path keeps no resident lids today, but the guard
        preserves the invariant if it ever does."""
        local_slots = [int(x) for x in local_slots]
        if not local_slots:
            return
        known = self._lid_known.get(algo)
        if known is None:
            self.engine.clear_shard(algo, s, local_slots)
            return
        with self._lid_locks[algo]:
            self.engine.clear_shard(algo, s, local_slots)
            base = s * self.engine.slots_per_shard
            known[np.asarray(local_slots, dtype=np.int64) + base] = False

    def _shard_lanes(self, n_sh: int):
        """The per-shard pipeline lanes (lazily created; see
        :class:`_ShardLane`)."""
        lanes = getattr(self, "_shard_lanes_obj", None)
        if lanes is None:
            lanes = [_ShardLane(s, recorder=self._recorder)
                     for s in range(n_sh)]
            self._shard_lanes_obj = lanes
        return lanes

    def available_many(
        self, algo: str, lid: int, keys: Sequence[str]
    ) -> np.ndarray:
        """Read-only availablePermits; unknown keys are computed host-side
        (absent state: full availability)."""
        _, config = self._configs[lid]
        index = self._index[algo]
        known: List[Tuple[int, int]] = []  # (position, slot)
        out = np.empty(len(keys), dtype=np.int64)
        for i, key in enumerate(keys):
            slot = index.get((lid, key))
            if slot is None:
                out[i] = config.max_permits
            else:
                known.append((i, slot))
        if known:
            # Flush queued mutations so the read observes them.
            self._batcher.flush()
            now = self._monotonic_now()
            slots = [s for _, s in known]
            if algo == "sw":
                vals = self.engine.sw_available(slots, [lid] * len(slots), now)
            else:
                vals = self.engine.tb_available(slots, [lid] * len(slots), now)
            for (i, _), v in zip(known, vals):
                out[i] = v
        return out

    def reset_key(self, algo: str, lid: int, key: str) -> None:
        """Admin reset: flush pending, clear the slot, then release it.

        Order matters: the slot is zeroed while still mapped to the old key,
        and only then returned to the free list — so no other key can be
        assigned the slot before it is clean (a zeroed slot reads as absent).
        """
        index = self._index[algo]
        if self._serving is not None:
            # Mid-stream policy reset: the hybrid tier must forget its
            # adopted state BEFORE the device clear so a concurrent
            # serve can't answer from pre-reset counters.
            self._serving.invalidate(algo, lid, key)
        if index.get((lid, key)) is None:
            return
        self._batcher.flush()
        slot = index.get((lid, key))
        if slot is None:
            return
        self._clear_slots(algo, [slot])
        index.remove((lid, key))

    # ------------------------------------------------------------------------
    # Token leases (leases/): atomic bulk reserve / credit
    # ------------------------------------------------------------------------
    def lease_reserve(self, algo: str, lid: int, key: str,
                      requested: int) -> Dict:
        """Atomically charge up to ``requested`` permits for one key
        against the live device counters — the grant side of a token
        lease (leases/manager.py).  Pending micro-batch traffic is
        flushed first so the grant observes every decision already
        admitted.  Runs the fused RESERVE kernel (ops/lease.py) on the
        single-device engine, the exclusive host round trip on the
        sharded mesh.  Returns ``{"granted", "ws", "stamp"}`` —
        ``ws`` is the charged window start (sliding window; 0 for the
        token bucket), which :meth:`lease_credit` must present.

        The same fence/promotion checks guard this as every decision
        surface: a fenced storage refuses with ``FencedError``, which
        the lease manager converts into lease revocation."""
        self._check_not_promoting()
        if self._fenced_shards:
            self._check_fence_keys([lid], [key])
        if self._serving is not None:
            # A leased key's state mutates outside the hybrid tier's
            # watch: its adopted snapshot is stale the moment the
            # reserve lands.
            self._serving.invalidate(algo, lid, key)
        self._batcher.flush()
        slot = self._assign_slot(algo, lid, key, hold_pin=True)
        with self._pins_released(self._index[algo], [slot]):
            now = self._monotonic_now()
            granted, ws = self.engine.lease_reserve(
                algo, [slot], [int(lid)], [int(requested)], now)
        return {"granted": int(granted[0]), "ws": int(ws[0]),
                "stamp": int(now)}

    def lease_credit(self, algo: str, lid: int, key: str, credit: int,
                     grant_ws: int) -> Dict:
        """Return ``credit`` unused reserved permits for one key (lease
        renewal/release).  A key whose slot was evicted credits nothing
        — its charge was cleared with the slot.  Returns ``{"credited",
        "stamp"}`` (the stamp makes the operation replayable against
        the oracle bit-for-bit — leases/manager.py records it)."""
        self._check_not_promoting()
        if self._fenced_shards:
            self._check_fence_keys([lid], [key])
        index = self._index[algo]
        if index.get((lid, key)) is None:
            return {"credited": 0, "stamp": 0}
        if self._serving is not None:
            self._serving.invalidate(algo, lid, key)
        self._batcher.flush()
        slot = index.get((lid, key))
        if slot is None:
            return {"credited": 0, "stamp": 0}
        now = self._monotonic_now()
        credited = self.engine.lease_credit(
            algo, [slot], [int(lid)], [int(credit)], [int(grant_ws)], now)
        return {"credited": int(credited[0]), "stamp": int(now)}

    def flush(self) -> None:
        self._batcher.flush()

    def warm_micro_shapes(self) -> None:
        """Pre-compile the small-shape micro-batch step for both algos
        (engine/engine.py:warm_micro_shapes): call once at service boot
        so the first interactive request doesn't pay an XLA compile.
        No-op on engines without micro shapes (the sharded engine
        buckets at its own floor)."""
        if hasattr(self.engine, "warm_micro_shapes"):
            self.engine.warm_micro_shapes()

    # ------------------------------------------------------------------------
    # Link-adaptive chunk planning (VERDICT r3 #1)
    # ------------------------------------------------------------------------
    def set_link_profile(self, upload_bytes_per_s: float,
                         rtt_s: float,
                         download_bytes_per_s: float | None = None) -> None:
        """Tell the streaming loops what the host<->device link measures
        (bench probes it; a service can call :meth:`probe_link`).  Clears
        cached chunk plans — they were elected for the old link.  The
        download rate defaults to the upload rate when the caller only
        probed one direction; the dev tunnel degrades the two
        independently, so callers that CAN probe both should."""
        self._link_profile = (float(upload_bytes_per_s), float(rtt_s),
                              float(download_bytes_per_s
                                    if download_bytes_per_s is not None
                                    else upload_bytes_per_s))
        self._chunk_plans.clear()

    def probe_link(self) -> Tuple[float, float, float]:
        """Measure the link (utils/link.py — the same probe the bench
        logs) and feed :meth:`set_link_profile`.  ~1-1.5 s on a healthy
        link; callers gate it (boot, or a periodic health task)."""
        from ratelimiter_tpu.utils.link import measure_link

        up_bps, rtt_s, down_bps = measure_link()
        self.set_link_profile(up_bps, rtt_s, down_bps)
        return self._link_profile

    def _elect_chunk_plan(self, key: tuple, n: int, tot: dict,
                          wall_s: float) -> None:
        """End-of-first-pass election for a stream shape: keep giant
        chunks (wire-budget growth), or switch later passes to a fixed
        descending SCHEDULE of chunk sizes.

        ``tot`` holds this pass's measured totals at the giant schedule
        (walk_s + host_s -> the pass's serial CPU rate, wire bytes,
        per-chunk (c, u) pairs -> the dedup curve, digest_chunks ->
        which mode the pass ran).  Candidate schedules from
        :func:`_schedule_candidates` are ranked by
        :func:`_sim_schedule_wall` under the measured tunnel model
        (concurrent drains overlap fetch round trips; link bytes
        serialize; CPU serializes); the best wins if it beats the
        simulated giant baseline by _PIPELINE_WIN_MARGIN.  The revert
        check (measured pipelined walls vs the giant pass's measured
        wall) remains the safety net for simulator error.

        A GIANT verdict stays provisional for a few passes: the first
        pass of a fresh storage compiles inside its fetches and walks
        insert-heavy — later (clean) giant passes re-elect.  A
        pipelined verdict is sticky, and a plan reverted by
        _maybe_revert_plan is locked giant, so the plan cannot
        oscillate."""
        cur = self._chunk_plans.get(key)
        if cur is not None and (cur["kind"] != "giant" or cur.get("locked")
                                or cur.get("passes", 0) >= 3):
            return
        if self._link_profile is None:
            return
        if n < (_RELAY_CHUNK << 2) or tot["walk_s"] <= 0:
            return
        prof = self._link_profile
        up, rtt = prof[0], prof[1]
        down = prof[2] if len(prof) > 2 else up
        chunks = max(tot.get("chunks", 1), 1)
        wire_s = tot["wire"] / max(up, 1.0)
        serial_pred = (tot["walk_s"] + tot.get("host_s", 0.0) + wire_s
                       + tot.get("device_s", 0.0) + chunks * rtt)
        if cur is None:
            if len(self._chunk_plans) >= 128:
                # Bound the cache, evicting cheapest-to-lose first
                # (ADVICE r4): giant/provisional plans cost one measuring
                # pass to rebuild, so they go before ACTIVE pipelined
                # plans (wiping one forces a mid-service re-measure plus
                # fresh compile shapes) and before LOCKED plans (wiping
                # one re-enables the oscillation its lock prevents).
                # Only if each tier alone still exceeds the bound does
                # the memory bound win outright.
                self._chunk_plans = {k: v for k, v
                                     in self._chunk_plans.items()
                                     if v.get("locked")
                                     or v["kind"] == "pipelined"}
                if len(self._chunk_plans) >= 128:
                    self._chunk_plans = {k: v for k, v
                                         in self._chunk_plans.items()
                                         if v.get("locked")}
                if len(self._chunk_plans) >= 128:
                    self._chunk_plans.clear()
            # The very first pass over a fresh stream shape is the wrong
            # evidence to elect from: its walk is insert/eviction-heavy
            # (2-4x the steady hit walk) and its fetches absorb XLA
            # compiles.  Record a provisional giant verdict; the next
            # giant pass measures steady state and elects for real.
            self._chunk_plans[key] = {"kind": "giant", "chunk": 0,
                                      "ref": round(serial_pred, 4),
                                      "passes": 1}
            return
        digest_frac = tot.get("digest_chunks", 0) / chunks
        # Dedup curve u = A * c^alpha fitted from the growth schedule's
        # most separated (chunk, uniques) pairs; digest wire AND device
        # lanes scale with uniques, so schedules with more chunks pay
        # A * sum(c_i^alpha) > A * n^alpha and the simulator sees it.
        cu = [p for p in tot.get("cu", []) if p[0] > 0 and p[1] > 0]
        alpha, a_fit = 1.0, 1.0
        if len(cu) >= 2:
            (c1, u1) = cu[0]
            (c2, u2) = max(cu, key=lambda p: p[0])
            if c2 > c1 * 1.5:
                import math

                alpha = min(max(math.log(max(u2, 1) / max(u1, 1))
                                / math.log(c2 / c1), 0.55), 1.0)
            a_fit = u2 / (c2 ** alpha)
        elif cu:
            a_fit = cu[0][1] / float(cu[0][0])
        rates = self._device_rates()
        bpu_up = 8.0 if tot.get("bpu", 6.0) >= 10.0 else 4.0
        bpu_down = 2.0 if tot.get("bpu", 6.0) >= 10.0 else 1.0
        dev_lane = rates["s_per_unique_unsorted" if digest_frac > 0.5
                         else "s_per_lane"]
        if key[0] == "weighted" and cu:
            # Weighted wire = 4 B/unique words + ~1.125 B/request permits
            # and bits: express it per UNIQUE through the giant pass's
            # request/unique ratio so the simulator's dedup curve (the
            # per-unique share grows subadditively as chunks shrink)
            # applies — the words branch would wrongly see splitting as
            # wire-neutral.  Device cost is the per-request scan, also
            # mapped per unique.
            r_pu = max(cu[-1][0] / max(cu[-1][1], 1), 1.0)
            digest_frac = 1.0
            bpu_up = 4.0 + 1.125 * r_pu
            bpu_down = 0.125 * r_pu
            dev_lane = rates["s_per_lane"] * r_pu
        sim_args = dict(
            cpu_per_req=(tot["walk_s"] + tot.get("host_s", 0.0)) / n,
            digest_frac=digest_frac, dedup_a=a_fit, dedup_alpha=alpha,
            # blended 6 B/unique = 4 B uword up + count back (resident
            # lids); blended 10 = uword + 4 B lid lane up + 2 B back.
            bpu_up=bpu_up, bpu_down=bpu_down,
            words_up=tot.get("bpr", 4.125) - 0.125,
            link_up=max(up * _DISPATCH_RATE_DERATE, 1.0),
            link_down=max(down * _DISPATCH_RATE_DERATE, 1.0), rtt=rtt,
            dev_per_lane=dev_lane)
        giant_sim = _sim_schedule_wall([_RELAY_CHUNK, n - _RELAY_CHUNK],
                                       **sim_args)
        best = None
        for sizes in _schedule_candidates(n, _RELAY_CHUNK,
                                          words_pow2=digest_frac <= 0.5):
            w = _sim_schedule_wall(sizes, **sim_args)
            if best is None or w < best[0]:
                best = (w, sizes)
        if best is not None and best[0] < _PIPELINE_WIN_MARGIN * giant_sim:
            # ref: the simulated baseline that justified the election.
            # giant_wall: the MEASURED wall of the (clean, steady) giant
            # pass that elected — the revert check compares against
            # this, not the simulated figure (simulator error must not
            # un-revert a plan the measurements rejected).
            self._chunk_plans[key] = {"kind": "pipelined",
                                      "schedule": tuple(best[1]),
                                      "chunk": int(max(best[1])),
                                      "ref": round(serial_pred, 4),
                                      "giant_wall": round(wall_s, 4),
                                      "passes": 0, "best": None}
        else:
            self._chunk_plans[key] = {
                "kind": "giant", "chunk": 0, "ref": round(serial_pred, 4),
                "passes": (cur.get("passes", 0) + 1) if cur else 1}

    def _plan_setup(self, plan_key: tuple, assign_uniques):
        """Shared head of the relay/weighted streaming loops: look up the
        chunk plan, build the measurement accumulator, and wrap the
        assign closure so the TRUE walk seconds are recorded wherever
        the walk runs (main thread or prefetch worker).  Returns
        (plan, pipelined, tot, timed_assign, t_pass0)."""
        plan = self._chunk_plans.get(plan_key)
        pipelined = plan is not None and plan["kind"] == "pipelined"
        tot = {"walk_s": 0.0, "wire": 0.0, "fetch_s": 0.0, "chunks": 0,
               "device_s": 0.0, "digest_chunks": 0, "host_s": 0.0,
               "cu": [], "_lock": threading.Lock()}

        def timed_assign(s0, cnt):
            ta = time.perf_counter()
            r = assign_uniques(s0, cnt)
            dt = time.perf_counter() - ta
            tot["walk_s"] += dt
            self._stage("index", dt)
            return r

        return plan, pipelined, tot, timed_assign, time.perf_counter()

    def _plan_finish(self, plan_key: tuple, plan, pipelined: bool, n: int,
                     tot: dict, t_pass0: float) -> None:
        """Shared tail: giant passes (re-)elect — a provisional giant
        verdict from a compile-contaminated first pass gets corrected by
        clean later measurements — and pipelined passes feed the revert
        check."""
        if pipelined:
            self._maybe_revert_plan(plan_key,
                                    time.perf_counter() - t_pass0)
        else:
            self._elect_chunk_plan(plan_key, n, tot,
                                   time.perf_counter() - t_pass0)

    def _maybe_revert_plan(self, key: tuple, wall_s: float) -> None:
        """A pipelined plan whose BEST pass (over at least two — the
        first re-compiles the new shapes) still measures clearly worse
        than the MEASURED wall of the giant pass that elected it
        reverts to giant — sticky, like the election, so chunk shapes
        stay deterministic after.  (Comparing against the analytic
        serial baseline instead wrongly reverted plans that beat the
        real giant: its per-fetch fixed cost is under-calibrated.)"""
        plan = self._chunk_plans.get(key)
        if plan is None or plan["kind"] != "pipelined":
            return
        plan["passes"] += 1
        plan["best"] = (wall_s if plan["best"] is None
                        else min(plan["best"], wall_s))
        ref = plan.get("giant_wall", plan["ref"])
        if plan["passes"] >= 2 and plan["best"] > _PIPELINE_REVERT * ref:
            # locked: a reverted shape must not be re-elected later, or
            # the plan (and its compile shapes) could oscillate.
            self._chunk_plans[key] = {"kind": "giant", "chunk": 0,
                                      "ref": plan["ref"], "locked": True}

    @staticmethod
    def _unpin_held(index, held) -> None:
        """Release pins accumulated as a list of slot arrays — the finally
        half of :meth:`_pins_released` for loops that take pins shard by
        shard and must release whatever was taken on any exception path."""
        if held and hasattr(index, "unpin_batch"):
            index.unpin_batch(np.concatenate(held))

    @contextlib.contextmanager
    def _evictions_cleared(self, algo: str):
        """A failed batch assignment still applied evictions for the lanes
        that succeeded before the failure (engine/errors.py
        SlotCapacityError.pending_clears): those slots are already
        remapped to new keys in the index, so zero their device state
        before the error propagates — exactly as the success path clears
        evictions ahead of reuse.  Clears once (the attribute is consumed)
        however many handlers the raise passes through."""
        try:
            yield
        except Exception as exc:  # noqa: BLE001 — always re-raised
            pc = getattr(exc, "pending_clears", None)
            if pc is not None and len(pc):
                # Clear FIRST, null after: a clear-time failure must
                # propagate with the clears still attached so an outer
                # handler could retry (zeroing is idempotent).
                self._clear_slots(algo, [int(s) for s in pc])
                exc.pending_clears = None
            raise

    @contextlib.contextmanager
    def _pins_released(self, index, slots):
        """Release pins taken ATOMICALLY inside an assign
        (``hold_pins=True``) once the enclosed dispatch is enqueued.

        The pins close an eviction race: without them, concurrent scalar
        traffic under eviction pressure could reassign-and-clear a slot
        BETWEEN the batch's slot assignment and its dispatch entering
        the device stream, making the batch write stale state into
        another key's slot.  Pinning after the assign returned would
        leave the same gap, which is why the indexes pin under the same
        lock hold as the assignment.  (Dispatches serialize in program
        order, so anything cleared AFTER the enqueue stays correct.)"""
        try:
            yield
        finally:
            if hasattr(index, "unpin_batch") and len(slots):
                index.unpin_batch(slots)

    def _clear_slots(self, algo: str, slots) -> None:
        """Single choke point for zeroing evicted/reset slots.

        Besides the device-state clear, it invalidates the host's record
        of which slots' tenant ids the device lid map knows — a cleared
        slot can be reassigned to a different (lid, key), so its resident
        lid must be re-uploaded on next digest use."""
        if not len(slots):
            return
        if self._serving is not None:
            # A cleared slot's key state is gone on device; any hybrid
            # tier entry tracking it is stale the moment the clear is in
            # the stream (eviction paths also invalidate at remap time —
            # see _assign_slot — this is the stream/direct-path backstop).
            self._serving.invalidate_slots(algo, slots)
        if self._lid_known.get(algo) is None:
            # No resident-lid tracking for this algo: nothing to
            # invalidate, so don't serialize against digest dispatches.
            (self.engine.sw_clear if algo == "sw"
             else self.engine.tb_clear)(list(slots))
            return
        with self._lid_locks[algo]:
            (self.engine.sw_clear if algo == "sw"
             else self.engine.tb_clear)(list(slots))
            known = self._lid_known.get(algo)
            if known is not None:
                known[np.asarray(slots, dtype=np.int64)] = False

    def _record_dispatch(self, algo: str, n: int, allowed: int,
                         dt_us: float, path: str = "micro",
                         lid=None, **extra) -> None:
        """Latency histogram + enriched decision trace + SLO anomaly
        hook for a completed dispatch.  ``path`` names the dispatch
        route (micro / relay|digest / relay|split / flat / sharded|...);
        ``extra`` carries enrichments like the shard id.  ``lid`` (a
        single-tenant dispatch's limiter id) feeds the per-tenant usage
        ring; mixed-tenant micro batches feed it from their drainer
        instead."""
        if not self._obs:
            return
        self._latency.record_us(dt_us)
        if lid is not None and self.telemetry is not None:
            self.telemetry.note_server(int(lid), n, allowed)
        lin = self.lineage
        if (lin is not None and lin.sample_n > 0 and path != "micro"):
            # Stream chunks: mint one trace id per dispatch; a sampled
            # one records its shard/path hop and enriches the trace
            # entry — the per-shard-lane leg of the lineage.
            from ratelimiter_tpu.observability.telemetry import (
                mint_trace_id,
                trace_hex,
            )

            tid = mint_trace_id()
            if lin.sampled(tid):
                lin.record(tid, "shard", path=path,
                           shard=extra.get("shard", 0), algo=algo,
                           batch=n, device_us=round(dt_us, 1))
                extra = dict(extra, trace=trace_hex(tid))
        self.trace.record(algo, n, allowed, dt_us, path=path, **extra)
        rec = self._recorder
        if rec is not None and rec.slo_us > 0.0 and dt_us > rec.slo_us:
            rec.anomaly("slow_dispatch", dt_us,
                        algo=algo, batch=n, path=path, **extra)

    def _stage(self, stage: str, secs: float) -> None:
        """Record one chunk's seconds in a pipeline-stage timer
        (pack/index/layout/enqueue/fetch; no-op with observability off)."""
        t = self._stage_timers
        if t is not None:
            t[stage].record_us(secs * 1e6)

    def _stream_rec(self, path: str, **fields):
        """One optional per-chunk instrumentation record: appends to
        ``stream_stats`` (None = off) and returns the dict so the caller
        can keep enriching it as the chunk progresses.  Floats round to
        us precision; the single choke point for what used to be four
        copy-pasted append blocks."""
        if self.stream_stats is None:
            return None
        rec = {"path": path}
        for k, v in fields.items():
            rec[k] = round(v, 6) if isinstance(v, float) else v
        self.stream_stats.append(rec)
        return rec

    # ------------------------------------------------------------------------
    # Checkpoint / resume (engine/checkpoint.py; SURVEY.md §5.4)
    # ------------------------------------------------------------------------
    def save_checkpoint(self, path: str) -> None:
        """Flush pending work and snapshot device state + key->slot maps."""
        from ratelimiter_tpu.engine import checkpoint as ckpt

        self._batcher.flush()
        self.engine.block_until_ready()
        ckpt.save_checkpoint(path, self.engine, ckpt.dump_slot_indexes(self))

    def restore_checkpoint(self, path: str) -> None:
        from ratelimiter_tpu.engine import checkpoint as ckpt

        data = ckpt.load_checkpoint(path)
        self._batcher.flush()
        ckpt.restore_engine_state(self.engine, data)
        ckpt.restore_slot_indexes(self, data["meta"]["index"])
        # The device lid map is not checkpointed: forget what the device
        # "knows" so the next digest-multi dispatch re-uploads lids.
        self._lid_known.clear()

    def promote_from_replica(self, index_dump: Dict) -> None:
        """Failover promotion hook (replication/standby.py).

        The standby's engine already holds the replicated rows; what it
        lacks is ADDRESSING — its key->slot indexes are empty so no
        traffic could route into half-replicated state.  Promotion
        rebuilds the indexes from the last replicated journal frame
        (native fingerprint dumps restore at native speed, exactly as
        checkpoint restore does) and clears the host's resident-lid
        mirror — the shadow device's lid map was never populated, so
        the first digest-multi dispatch must re-upload tenant ids.
        After this returns the storage serves decisions bit-identical
        to the oracle for every key at or before the replicated epoch.

        A decision racing the restore must never see a half-applied
        index (it could assign a fresh slot that collides with another
        key's replicated row): the promotion window REFUSES decisions
        with the typed, retryable ``PromotionInProgressError`` — the
        window is one index restore, microseconds to low milliseconds.
        """
        from ratelimiter_tpu.engine import checkpoint as ckpt

        self._promoting = True
        try:
            self._batcher.flush()
            if self._serving is not None:
                # Every adopted snapshot predates the index swap.
                self._serving.invalidate_all()
            ckpt.restore_slot_indexes(self, index_dump)
            self._lid_known.clear()
            self.engine.block_until_ready()
        finally:
            self._promoting = False

    # ------------------------------------------------------------------------
    # Fencing (replication/orchestrator.py)
    # ------------------------------------------------------------------------
    def fence(self, epoch: int, shards=None) -> int:
        """Install a fence at a monotonic ``epoch``: this storage (or the
        named ``shards`` of its sharded engine) refuses every further
        decision with the typed :class:`FencedError`.

        Failover calls this on the storage being REPLACED before its
        standby is promoted, so a zombie primary — declared dead on a
        false positive but actually still running — cannot keep admitting
        traffic in parallel with the replacement.  The epoch must strictly
        exceed the last installed one (a stale orchestrator instance
        replaying an old fence must not regress a newer decision); a
        non-monotonic epoch raises ``ValueError`` and changes nothing.
        """
        epoch = int(epoch)
        if epoch <= self._fence_epoch:
            raise ValueError(
                f"fence epoch {epoch} is not past the installed epoch "
                f"{self._fence_epoch}; fencing is monotonic")
        self._fence_epoch = epoch
        if shards is None:
            self._fence_all = True
            self._full_fence_epoch = epoch
            # An explicit fence supersedes the serving lease: the lease
            # expiry check is moot once every decision is refused.
            self._lease_deadline_ms = 0
        else:
            self._fenced_shards = self._fenced_shards | frozenset(
                int(q) for q in shards)
            for q in shards:
                self._shard_fence_epochs[int(q)] = epoch
        if self._recorder is not None:
            self._recorder.record(
                "fence.installed", epoch=epoch,
                shards=(sorted(self._fenced_shards) if shards is not None
                        else "all"))
        return epoch

    def lift_fence(self, epoch: int, shards=None) -> None:
        """Lift the fence (operator action after the false-dead primary is
        verified quiesced).  ``epoch`` must be at or past the installed
        fence epoch — a stale lift is refused the same way a stale fence
        is."""
        if int(epoch) < self._fence_epoch:
            raise ValueError(
                f"lift epoch {epoch} is behind the installed fence epoch "
                f"{self._fence_epoch}")
        if shards is None:
            self._fence_all = False
            self._fenced_shards = frozenset()
            # Operator re-arm: a lift also clears a lease self-fence (the
            # operator verified no replacement owns this keyspace); the
            # next orchestrator grant re-installs the lease.
            self.lease_self_fenced = False
        else:
            self._fenced_shards = self._fenced_shards - frozenset(
                int(q) for q in shards)
        if self._recorder is not None:
            self._recorder.record("fence.lifted", epoch=int(epoch))

    def fence_info(self) -> Dict:
        # The epoch reported here stamps token leases (leases/manager.py)
        # — it must cover the SERVING-lease epoch too, so a client lease
        # granted under generation E is revoked after a promotion hands
        # the keyspace to a replacement carrying E+1.
        return {"epoch": max(self._fence_epoch, self._lease_epoch),
                "all": self._fence_all,
                "shards": sorted(self._fenced_shards),
                "shard_epochs": dict(self._shard_fence_epochs),
                "rejected": self.fence_rejected}

    def lease_scope_epoch(self, lid: int, key) -> int:
        """The revocation epoch a token lease on ``(lid, key)`` must be
        checked against (leases/manager.py).  For an unsharded engine
        this is the global ``fence_info()`` epoch — identical semantics
        to before scoping existed.  For a sharded engine, a scoped fence
        (single-shard promotion) only advances the epoch of keys that
        ROUTE to the fenced shard, so survivors renew without a bounce
        and failover cost is O(affected aggregators), not O(clients)."""
        n_sh = getattr(self.engine, "n_shards", None)
        if n_sh is None:
            return max(self._fence_epoch, self._lease_epoch)
        base = max(self._full_fence_epoch, self._lease_epoch)
        if not self._shard_fence_epochs:
            return base
        from ratelimiter_tpu.parallel.sharded import shard_of_key

        q = shard_of_key((int(lid), key), int(n_sh))
        return max(base, self._shard_fence_epochs.get(int(q), 0))

    # ------------------------------------------------------------------------
    # Serving lease: the distributed fence (replication/control.py)
    # ------------------------------------------------------------------------
    def grant_serving_lease(self, epoch: int, ttl_ms: float) -> Dict:
        """Install or renew the serving lease: this storage may decide
        until ``ttl_ms`` from NOW (its own clock — the grant is relative,
        so orchestrator/primary wall clocks need not be synchronized).

        ``epoch`` is the fence generation the grant belongs to; a grant
        must never regress it (a stale orchestrator instance replaying
        an old generation cannot extend a zombie), and a grant can never
        resurrect a fenced storage — once ``fence()`` ran or the lease
        expired, only the operator ``lift_fence`` path re-arms serving.
        """
        epoch = int(epoch)
        if self._fence_all:
            raise ValueError(
                "storage is fenced; a serving lease cannot resurrect it "
                "(operator lift_fence first)")
        if epoch < self._lease_epoch:
            raise ValueError(
                f"serving-lease epoch {epoch} is behind the installed "
                f"epoch {self._lease_epoch}; grants are monotonic")
        self._lease_epoch = epoch
        self._lease_deadline_ms = int(self._clock_ms()) + int(ttl_ms)
        return self.serving_lease_info()

    def release_serving_lease(self) -> Dict:
        """Voluntarily drop the serving lease (graceful stop — the
        SIGTERM/drain path in ``replication/hostproc.py``).  NOT a
        fence: the storage simply stops claiming the keyspace, so the
        orchestrator reads a clean hand-back (``installed: False``)
        instead of a TTL runout, and a later ``grant_serving_lease`` at
        the same-or-newer epoch re-arms serving without an operator
        ``lift_fence``.  Distinguishes "stopped on purpose" from the
        self-fenced zombie the expiry path produces."""
        self._lease_deadline_ms = 0
        if self._recorder is not None:
            self._recorder.record("lease.released",
                                  epoch=self._lease_epoch)
        return self.serving_lease_info()

    def serving_lease_info(self) -> Dict:
        now = int(self._clock_ms())
        installed = bool(self._lease_deadline_ms)
        return {
            "epoch": self._lease_epoch,
            "installed": installed,
            "ttl_remaining_ms": (max(self._lease_deadline_ms - now, 0)
                                 if installed else 0),
            "expired": bool(installed and now >= self._lease_deadline_ms),
            "self_fenced": self.lease_self_fenced,
        }

    def _lease_expired_fence(self) -> None:
        """The serving lease ran out: self-fence.  The orchestrator that
        granted it is either dead or partitioned from us AND from the
        standby relay — either way a replacement may be serving, and the
        decisions we would admit past this point are exactly the
        unbounded half of the split-brain.  Everything admitted BEFORE
        this point is the documented over-admission window: at most one
        lease TTL of traffic, per key at most ``max_permits`` per window
        (the storage/degraded.py bound)."""
        self._fence_all = True
        self._fence_epoch = max(self._fence_epoch, self._lease_epoch)
        self._full_fence_epoch = max(self._full_fence_epoch,
                                     self._fence_epoch)
        self._lease_deadline_ms = 0
        self.lease_self_fenced = True
        if self._recorder is not None:
            self._recorder.record("fence.lease_expired",
                                  epoch=self._lease_epoch)
        self._fence_reject("serving lease expired; orchestrator "
                           "unreachable — a replacement may own this "
                           "keyspace")

    def _fence_reject(self, detail: str):
        self.fence_rejected += 1
        from ratelimiter_tpu.storage.errors import FencedError

        raise FencedError(
            f"storage fenced at epoch {self._fence_epoch} ({detail}): a "
            "failover replacement owns this keyspace; this instance must "
            "not decide")

    def _check_fence_int_keys(self, key_ids) -> None:
        """Shard-scoped fence check for int-key batch/stream paths (only
        reached when a shard fence is installed)."""
        n_sh = getattr(self.engine, "n_shards", None)
        if n_sh is None:
            return
        from ratelimiter_tpu.parallel.sharded import shard_of_int_keys

        shards = shard_of_int_keys(
            np.ascontiguousarray(key_ids, dtype=np.int64), int(n_sh))
        hit = sorted(q for q in self._fenced_shards if (shards == q).any())
        if hit:
            self._fence_reject(f"request routes to fenced shard(s) {hit}")

    def _check_fence_keys(self, lid_per_req, keys) -> None:
        """Shard-scoped fence check for string-key batch paths."""
        n_sh = getattr(self.engine, "n_shards", None)
        if n_sh is None:
            return
        from ratelimiter_tpu.parallel.sharded import shard_of_key

        for lid, key in zip(lid_per_req, keys):
            q = shard_of_key((int(lid), key), int(n_sh))
            if q in self._fenced_shards:
                self._fence_reject(
                    f"key routes to fenced shard {q}")

    def export_keys(self) -> Dict:
        """Geometry-free export of all live per-key state (the rebalance
        counterpart to checkpoints; engine/checkpoint.py:export_keys —
        which flushes pending traffic itself)."""
        from ratelimiter_tpu.engine import checkpoint as ckpt

        return ckpt.export_keys(self)

    def import_keys(self, dump: Dict) -> None:
        """Import an export into THIS storage's geometry (slots assigned by
        this storage's own index/shard hash — this is the rebalance)."""
        from ratelimiter_tpu.engine import checkpoint as ckpt

        self._batcher.flush()
        ckpt.import_keys(self, dump)
        self._lid_known.clear()  # imported slots carry unknown lids

    # ------------------------------------------------------------------------
    # Legacy 10-method contract (host-side, embedded InMemoryStorage)
    # ------------------------------------------------------------------------
    def increment_and_expire(self, key: str, ttl_ms: int) -> int:
        return self._host.increment_and_expire(key, ttl_ms)

    def get(self, key: str) -> int:
        return self._host.get(key)

    def set(self, key: str, value: int, ttl_ms: int) -> None:
        self._host.set(key, value, ttl_ms)

    def compare_and_set(self, key: str, expect: int, update: int) -> bool:
        return self._host.compare_and_set(key, expect, update)

    def delete(self, key: str) -> None:
        self._host.delete(key)

    def z_add(self, key: str, score: float, member: str) -> None:
        self._host.z_add(key, score, member)

    def z_remove_range_by_score(self, key: str, min_score: float, max_score: float) -> int:
        return self._host.z_remove_range_by_score(key, min_score, max_score)

    def z_count(self, key: str, min_score: float, max_score: float) -> int:
        return self._host.z_count(key, min_score, max_score)

    def eval_script(self, script: str, keys: List[str], args: List[int]):
        return self._host.eval_script(script, keys, args)

    def is_available(self) -> bool:
        """Health check: a trivial device round-trip must succeed."""
        try:
            self.engine.block_until_ready()
            return True
        except Exception:  # noqa: BLE001
            return False

    def close(self) -> None:
        self._batcher.close()
        for attr in ("_shard_pool_obj", "_assign_pool_obj",
                     "_drain_pool_obj"):
            pool = getattr(self, attr, None)
            if pool is not None:
                pool.shutdown(wait=False)
        for lane in getattr(self, "_shard_lanes_obj", None) or ():
            lane.close()
        for index in self._index.values():
            if hasattr(index, "close"):
                index.close()

    def _abort_prefetch(self, algo, index, fut, slots_of) -> None:
        """Consume an ORPHANED prefetched assignment (an exception escaped
        before the main loop took it): the index already applied it — its
        evicted slots map to new keys and must be cleared on device
        before any reuse, exactly as the in-loop path clears them — and
        its held pins must be released.  ``slots_of(result)`` extracts
        the pinned slot array from the assign result (whose last element
        is always the clears list)."""
        try:
            res = fut.result()
        except Exception:  # noqa: BLE001 — failed assign holds nothing
            return
        try:
            clears = res[-1]
            if len(clears):
                self._clear_slots(algo, list(clears))
        finally:
            slots = slots_of(res)
            if slots is not None and len(slots):
                self._unpin_held(index, [slots])

    def _assign_pool(self):
        """One-worker pool that prefetches the NEXT chunk's slot
        assignment while the main thread blocks in a device fetch (the
        fetch wait releases the GIL and the C walk releases it too, so
        on any host the assign rides in the fetch shadow)."""
        pool = getattr(self, "_assign_pool_obj", None)
        if pool is None:
            import concurrent.futures as cf

            pool = cf.ThreadPoolExecutor(1, thread_name_prefix="assignpf")
            self._assign_pool_obj = pool
        return pool

    def _device_rates(self) -> dict:
        """Per-lane device step rates for the elections: probed per
        (platform, device kind) and cached (engine/device_rates.py)
        when a link profile is set — profile-less storages never probe
        (elections don't run without one) and use the v5e fallback."""
        if self._link_profile is None:
            return _FB_RATES
        r = getattr(self, "_device_rates_obj", None)
        if r is None:
            from ratelimiter_tpu.engine.device_rates import get_device_rates

            r = get_device_rates()
            self._device_rates_obj = r
        return r

    def _drain_pool(self):
        """Drain workers: device fetches block here CONCURRENTLY so
        their per-fetch round trips overlap (see _DrainSet).  The fetch
        wait sleeps in the runtime, so these threads cost no CPU beyond
        the drains' own numpy post-processing."""
        pool = getattr(self, "_drain_pool_obj", None)
        if pool is None:
            import concurrent.futures as cf

            pool = cf.ThreadPoolExecutor(_DRAIN_WORKERS,
                                         thread_name_prefix="drain")
            self._drain_pool_obj = pool
        return pool

    def _shard_pool(self, n_sh: int):
        """Thread pool for per-shard C index calls (lazily created),
        sized to the SMALLER of shard count and usable cores (r8): the
        calls release the GIL, so real cores overlap them, but
        oversubscribing one core with n_sh walk threads only buys
        scheduler churn and inflated per-walk walls (the BENCH_r05
        8-shard assign_s pathology)."""
        pool = getattr(self, "_shard_pool_obj", None)
        if pool is None:
            import concurrent.futures as cf

            try:
                cores = len(os.sched_getaffinity(0))
            except (AttributeError, OSError):  # pragma: no cover
                cores = os.cpu_count() or 1
            pool = cf.ThreadPoolExecutor(max(1, min(n_sh, cores)),
                                         thread_name_prefix="shardidx")
            self._shard_pool_obj = pool
        return pool

    # ------------------------------------------------------------------------
    def _check_not_promoting(self) -> None:
        """Refuse decisions while a standby promotion is swapping the
        key->slot indexes, and refuse them FOREVER once this storage is
        whole-fenced (two attribute checks on the hot path; see
        :meth:`promote_from_replica` and :meth:`fence`).  With a serving
        lease installed (cross-host topology) this is also where expiry
        bites: the first decision past the lease deadline self-fences —
        every dispatch surface funnels through here, so a partitioned
        zombie's in-flight dispatches lose the race within one check."""
        if self._fence_all:
            self._fence_reject("whole-storage fence")
        if self._lease_deadline_ms \
                and int(self._clock_ms()) >= self._lease_deadline_ms:
            self._lease_expired_fence()
        if self._promoting:
            from ratelimiter_tpu.storage.errors import (
                PromotionInProgressError,
            )

            raise PromotionInProgressError(
                "standby promotion in progress: the key->slot index is "
                "being rebuilt; retry after the promotion window")

    def _assign_slot(self, algo: str, lid: int, key: str,
                     hold_pin: bool = False) -> int:
        self._check_not_promoting()
        if self._fenced_shards:
            self._check_fence_keys([lid], [key])
        index = self._index[algo]
        pinned = self._batcher.pending_slots(algo)
        slot, evicted = index.assign((lid, key), pinned=pinned,
                                     hold_pin=hold_pin)
        if evicted is not None:
            if self._serving is not None:
                # Invalidate at REMAP time, not clear time: the evicted
                # key's index entry is already gone, so a hybrid-tier
                # serve from its adopted state would track a key the
                # device is about to forget.
                self._serving.invalidate_slots(algo, [evicted])
            self._batcher.add_clear(algo, evicted)
        return slot
