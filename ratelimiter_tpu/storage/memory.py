"""Process-local in-memory storage backend.

The framework's *real* (not mocked) test double and single-process deployment
backend — the role SURVEY.md §4 prescribes to invert the reference's
Mockito-mock-only testing.  Implements every method of the
``RateLimitStorage`` contract with Redis-accurate TTL semantics (a key is
gone at/after its deadline) under one lock, so the compat algorithm classes
running over it reproduce the oracle's decisions exactly.

An injectable millisecond clock makes time fully deterministic in tests; the
token-bucket scripts take ``now`` as an argument (exactly like the Lua script
receives ARGV[4], TokenBucketRateLimiter.java:126) so script execution is
time-independent of the storage clock.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Sequence, Tuple

from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.storage.errors import StorageException


def _wall_clock_ms() -> int:
    return time.time_ns() // 1_000_000

_NO_DEADLINE = 1 << 62


class InMemoryStorage(RateLimitStorage):
    def __init__(self, clock_ms: Callable[[], int] = _wall_clock_ms):
        self._clock_ms = clock_ms
        self._lock = threading.RLock()
        # key -> (value, deadline_ms)
        self._counters: Dict[str, Tuple[int, int]] = {}
        # key -> {member: score}
        self._zsets: Dict[str, Dict[str, float]] = {}
        # key -> (tokens_fp, last_refill_ms, deadline_ms) — token buckets
        self._buckets: Dict[str, Tuple[int, int, int]] = {}
        self._available = True

    # -- counters -------------------------------------------------------------
    def _live_counter(self, key: str, now: int) -> int | None:
        entry = self._counters.get(key)
        if entry is None:
            return None
        value, deadline = entry
        if now >= deadline:
            del self._counters[key]
            return None
        return value

    def increment_and_expire(self, key: str, ttl_ms: int) -> int:
        now = self._clock_ms()
        with self._lock:
            value = self._live_counter(key, now) or 0
            value += 1
            self._counters[key] = (value, now + int(ttl_ms))
            return value

    def get(self, key: str) -> int:
        now = self._clock_ms()
        with self._lock:
            value = self._live_counter(key, now)
            return 0 if value is None else value

    def set(self, key: str, value: int, ttl_ms: int) -> None:
        now = self._clock_ms()
        with self._lock:
            self._counters[key] = (int(value), now + int(ttl_ms))

    def compare_and_set(self, key: str, expect: int, update: int) -> bool:
        now = self._clock_ms()
        with self._lock:
            current = self._live_counter(key, now) or 0
            if current != expect:
                return False
            # Preserve any existing deadline (Redis SET without PX on a live
            # key in a MULTI clears TTL; the reference's CAS sets no TTL —
            # RedisRateLimitStorage.java:73-92 — so neither do we).
            self._counters[key] = (int(update), _NO_DEADLINE)
            return True

    def delete(self, key: str) -> None:
        with self._lock:
            self._counters.pop(key, None)
            self._zsets.pop(key, None)
            self._buckets.pop(key, None)

    # -- sorted sets ----------------------------------------------------------
    def z_add(self, key: str, score: float, member: str) -> None:
        with self._lock:
            self._zsets.setdefault(key, {})[member] = float(score)

    def z_remove_range_by_score(self, key: str, min_score: float, max_score: float) -> int:
        with self._lock:
            zset = self._zsets.get(key, {})
            doomed = [m for m, s in zset.items() if min_score <= s <= max_score]
            for m in doomed:
                del zset[m]
            return len(doomed)

    def z_count(self, key: str, min_score: float, max_score: float) -> int:
        with self._lock:
            zset = self._zsets.get(key, {})
            return sum(1 for s in zset.values() if min_score <= s <= max_score)

    # -- scripts --------------------------------------------------------------
    def eval_script(self, script: str, keys: List[str], args: List[int]) -> Sequence[int]:
        if script == "token_bucket":
            return self._script_token_bucket(keys[0], *map(int, args))
        if script == "token_bucket_peek":
            return self._script_token_bucket_peek(keys[0], *map(int, args))
        raise StorageException(f"unknown script: {script!r}")

    def _refill(self, key: str, cap_fp: int, rate_fp: int, now: int) -> Tuple[int, int]:
        """Returns (tokens_fp, last_refill) after lazy init + refill; exact
        oracle math (semantics/oracle.py:TokenBucketOracle._refilled)."""
        entry = self._buckets.get(key)
        if entry is None or now >= entry[2]:
            self._buckets.pop(key, None)
            return cap_fp, now
        tokens_fp, last_refill, _ = entry
        elapsed = now - last_refill
        elapsed = min(elapsed, cap_fp // max(rate_fp, 1) + 1)
        return min(cap_fp, tokens_fp + elapsed * rate_fp), last_refill

    def _script_token_bucket(
        self, key: str, cap_fp: int, rate_fp: int, requested_fp: int, now: int, ttl_ms: int
    ) -> Sequence[int]:
        with self._lock:
            tokens_fp, _ = self._refill(key, cap_fp, rate_fp, now)
            if tokens_fp >= requested_fp:
                tokens_fp -= requested_fp
                self._buckets[key] = (tokens_fp, now, now + ttl_ms)
                return (1, tokens_fp)
            return (0, tokens_fp)

    def _script_token_bucket_peek(
        self, key: str, cap_fp: int, rate_fp: int, now: int
    ) -> Sequence[int]:
        with self._lock:
            tokens_fp, _ = self._refill(key, cap_fp, rate_fp, now)
            return (tokens_fp,)

    # -- health ---------------------------------------------------------------
    def is_available(self) -> bool:
        return self._available

    def set_available(self, available: bool) -> None:
        """Fault-injection hook for failure-path tests (the reference has no
        fault injection at all — SURVEY.md §5.3)."""
        self._available = available
