"""Storage failure type and retry policy.

Mirrors ``storage/StorageException.java:6-15`` (unchecked failure after
retries are exhausted) and the retry wrapper
``RedisRateLimitStorage.java:155-178`` (3 attempts, linear 10/20/30 ms
backoff).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, TypeVar

from ratelimiter_tpu.engine.errors import OverloadedError, ShutdownError

T = TypeVar("T")


class StorageException(RuntimeError):
    """Raised when a storage operation fails after all retries."""


class CircuitOpenError(StorageException):
    """The circuit breaker is open: the backend was not called.

    A ``StorageException`` subclass so the service tier's existing
    fail-open policy absorbs it on paths with no degraded fallback — but
    listed in ``RetryPolicy.no_retry`` because retrying a deterministic
    short-circuit only burns the backoff budget (the breaker will not
    close until its open window elapses and a half-open probe succeeds).
    """


class PromotionInProgressError(StorageException):
    """A standby promotion is rebuilding this storage's key->slot index.

    Decisions are REFUSED for the promotion window rather than risking a
    half-applied index routing a key into another key's replicated row
    (replication/standby.py).  Transient and retryable: the window is
    one index restore, after which the storage serves normally.
    """


class FencedError(StorageException):
    """This storage (or one of its shards) has been fenced by failover.

    The failover orchestrator (replication/orchestrator.py) bumps a
    monotonic fencing epoch on the storage it is replacing BEFORE
    promoting a standby: a zombie primary — declared dead on a
    false-positive health verdict but actually still running — must not
    keep admitting traffic in parallel with its replacement ("When Two
    is Worse Than One": two uncoordinated primaries over-admit without
    bound).  Unlike :class:`PromotionInProgressError` this is NOT
    transient: a fenced storage stays fenced until an operator lifts
    the fence, so it is listed in ``RetryPolicy.no_retry``.
    """


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Linear-backoff retry (RedisRateLimitStorage.java:19-20,155-178).

    Caller-side programming/validation errors (``no_retry``) pass straight
    through: the Java wrapper retried JedisException — transport faults —
    not argument errors, and converting a ValueError into StorageException
    would hand it to the fail-open policy, silently allowing requests a
    caller bug produced.  The overload/lifecycle family is equally
    non-retryable: replaying a shed request amplifies the overload it was
    shed to relieve, a closed batcher will not reopen, and an open
    breaker is deterministic until its window elapses.
    """

    max_retries: int = 3
    retry_delay_ms: float = 10.0
    no_retry: tuple = (ValueError, TypeError, KeyError,
                       OverloadedError, ShutdownError, CircuitOpenError,
                       FencedError)

    def execute(self, operation: Callable[[], T], sleep=time.sleep) -> T:
        last_exc: Exception | None = None
        for attempt in range(self.max_retries):
            try:
                return operation()
            except self.no_retry:
                raise
            except Exception as exc:  # noqa: BLE001 — transport/storage faults
                last_exc = exc
                if attempt < self.max_retries - 1:
                    sleep(self.retry_delay_ms * (attempt + 1) / 1000.0)
        raise StorageException(
            f"Operation failed after {self.max_retries} retries"
        ) from last_exc
