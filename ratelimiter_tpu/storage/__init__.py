from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.storage.errors import RetryPolicy, StorageException
from ratelimiter_tpu.storage.memory import InMemoryStorage
from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

__all__ = [
    "RateLimitStorage",
    "InMemoryStorage",
    "TpuBatchedStorage",
    "RetryPolicy",
    "StorageException",
]
