from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.storage.breaker import CircuitBreakerStorage
from ratelimiter_tpu.storage.chaos import FaultInjectingProxy, FaultInjectingStorage
from ratelimiter_tpu.storage.degraded import DegradedHostLimiter
from ratelimiter_tpu.storage.errors import (
    CircuitOpenError,
    RetryPolicy,
    StorageException,
)
from ratelimiter_tpu.storage.memory import InMemoryStorage
from ratelimiter_tpu.storage.retry import RetryingStorage
from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

__all__ = [
    "CircuitBreakerStorage",
    "CircuitOpenError",
    "DegradedHostLimiter",
    "FaultInjectingProxy",
    "FaultInjectingStorage",
    "RateLimitStorage",
    "InMemoryStorage",
    "RetryingStorage",
    "TpuBatchedStorage",
    "RetryPolicy",
    "StorageException",
]
