from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.storage.errors import RetryPolicy, StorageException
from ratelimiter_tpu.storage.memory import InMemoryStorage

__all__ = ["RateLimitStorage", "InMemoryStorage", "RetryPolicy", "StorageException"]
