"""Circuit breaker over the storage plugin boundary.

Composed in ``service/wiring.py`` as ``retry(breaker(chaos(storage)))``:
the breaker sits INSIDE the retry wrapper, so every retry attempt against
a persistently-failing backend counts toward the consecutive-failure
threshold — a sustained outage stops paying full retry exhaustion after
``ceil(threshold / max_retries)`` requests instead of forever ("When Two
is Worse Than One", PAPERS.md: naive retry layering over a dead backend
only inflates tail latency).

States:

- **closed** — ops pass through; ``failure_threshold`` consecutive
  backend faults (validation/overload/lifecycle errors excluded) open it.
- **open** — for ``open_ms``, ops never touch the backend.  Decisions
  (``acquire`` / ``available_many`` / ``reset_key``) short-circuit to the
  attached ``DegradedHostLimiter`` when one is wired (fail-*approximate*);
  everything else raises ``CircuitOpenError`` immediately (a
  ``StorageException``, so the service tier's fail-open still applies on
  paths with no fallback).
- **half_open** — after ``open_ms``, up to ``half_open_probes`` ops are
  let through as probes.  A probe failure re-opens; once all probes
  succeed the breaker closes and **resyncs**: every key the degraded
  limiter mutated is reset on the device (its host-approximate state and
  the device's stale pre-outage state are both discarded), restoring
  decisions bit-identical to ``semantics/oracle.py`` — the contract
  ``storage/chaos.py:outage_drill`` proves.

The breaker also snapshots the last device-reported counter per key on
the healthy ``acquire`` path (into the fallback's ``note_seen`` cache) so
degraded mode starts each key from its last known budget rather than a
blank slate.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from ratelimiter_tpu.engine.errors import OverloadedError, ShutdownError
from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.storage.chaos import _DECISION_OPS, _LEGACY_OPS
from ratelimiter_tpu.storage.errors import CircuitOpenError
from ratelimiter_tpu.utils.logging import get_logger

log = get_logger("storage.breaker")

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_GAUGE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}

# Never counted as backend faults and never short-circuited into
# CircuitOpenError conversions: caller bugs and local admission/lifecycle
# signals (see RetryPolicy.no_retry for the same family).
_NO_COUNT = (ValueError, TypeError, KeyError,
             OverloadedError, ShutdownError, CircuitOpenError)

# Ops the breaker gates.  acquire/available_many/reset_key get explicit
# methods (they can fall back to the degraded limiter); the rest are
# generated pass-through-or-raise wrappers.
_GATED_PLAIN = tuple(op for op in _DECISION_OPS
                     if op not in ("acquire", "available_many", "reset_key")
                     ) + _LEGACY_OPS


def _wall_clock_ms() -> int:
    return time.time_ns() // 1_000_000


class CircuitBreakerStorage(RateLimitStorage):
    """Wraps a backend; opens after consecutive faults, degrades, resyncs."""

    def __init__(
        self,
        inner: RateLimitStorage,
        failure_threshold: int = 8,
        open_ms: float = 5000.0,
        half_open_probes: int = 1,
        clock_ms: Callable[[], int] = _wall_clock_ms,
        fallback=None,
        registry=None,
        recorder=None,
    ):
        if recorder is None:
            from ratelimiter_tpu.observability import flight_recorder

            recorder = flight_recorder()
        self._recorder = recorder
        self._inner = inner
        self.failure_threshold = max(int(failure_threshold), 1)
        self.open_ms = float(open_ms)
        self.half_open_probes = max(int(half_open_probes), 1)
        self._clock_ms = clock_ms
        self.fallback = fallback
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._open_until = 0
        self._probe_budget = 0
        self._probe_successes = 0
        self.opened_total = 0
        self.resyncs_total = 0
        self._registry = registry
        self._state_gauge = (
            registry.gauge("ratelimiter.breaker.state",
                           "Breaker state: 0=closed 1=half_open 2=open")
            if registry is not None else None)
        self._opened_counter = (
            registry.counter("ratelimiter.breaker.opened",
                             "Breaker open transitions")
            if registry is not None else None)
        self._short_counter = (
            registry.counter(
                "ratelimiter.breaker.short_circuited",
                "Ops short-circuited while the breaker was open "
                "(degraded decisions + immediate CircuitOpenErrors)")
            if registry is not None else None)

    # -- state machine --------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def status(self) -> dict:
        with self._lock:
            out = {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "opened_total": self.opened_total,
                "resyncs_total": self.resyncs_total,
                "degraded_fallback": self.fallback is not None,
            }
        # Shard-aware backend (replication/sharded.py failover router):
        # surface the per-shard serving state so a single failed shard
        # reads as DEGRADED capacity behind a closed breaker, not DOWN.
        shard_health = getattr(self._inner, "shard_health", None)
        if callable(shard_health):
            try:
                shards = shard_health()
                out["shards"] = {str(q): v for q, v in shards.items()}
                out["degraded_shards"] = sorted(
                    str(q) for q, v in shards.items() if v != "active")
            except Exception:  # noqa: BLE001 — status stays best-effort
                pass
        return out

    def trip(self) -> None:
        """Force-open (ops/test hook): behave as if the threshold tripped."""
        with self._lock:
            self._open_locked()

    def _set_gauge_locked(self) -> None:
        if self._state_gauge is not None:
            self._state_gauge.set(_STATE_GAUGE[self._state])

    def _open_locked(self) -> None:
        self._state = OPEN
        self._open_until = self._clock_ms() + self.open_ms
        self._probe_budget = 0
        self._probe_successes = 0
        self.opened_total += 1
        if self._opened_counter is not None:
            self._opened_counter.increment()
        self._set_gauge_locked()
        self._recorder.record(
            "breaker.open", consecutive_failures=self._consecutive,
            degraded=self.fallback is not None)
        log.warning("circuit breaker OPEN for %.0f ms (%d consecutive "
                    "failures); decisions %s", self.open_ms,
                    self._consecutive,
                    "degrade to the host limiter" if self.fallback is not None
                    else "short-circuit to CircuitOpenError")

    def _gate(self) -> str:
        """Admission verdict for one op: 'inner' | 'probe' | 'open'."""
        with self._lock:
            if self._state == CLOSED:
                return "inner"
            if self._state == OPEN:
                if self._clock_ms() >= self._open_until:
                    self._state = HALF_OPEN
                    self._probe_budget = self.half_open_probes
                    self._probe_successes = 0
                    self._set_gauge_locked()
                    self._recorder.record("breaker.half_open")
                    log.info("circuit breaker HALF_OPEN: probing backend")
                else:
                    return "open"
            # HALF_OPEN: hand out the probe budget; everyone else stays out.
            if self._probe_budget > 0:
                self._probe_budget -= 1
                return "probe"
            return "open"

    def _on_success(self, mode: str) -> None:
        resync = False
        with self._lock:
            self._consecutive = 0
            if mode == "probe" and self._state == HALF_OPEN:
                self._probe_successes += 1
                if self._probe_successes >= self.half_open_probes:
                    self._state = CLOSED
                    self._set_gauge_locked()
                    resync = True
                    self._recorder.record("breaker.close")
                    log.info("circuit breaker CLOSED: backend recovered")
        if resync:
            self._resync()

    def _on_failure(self, mode: str) -> None:
        with self._lock:
            if mode == "probe":
                log.warning("half-open probe failed; breaker re-opens")
                self._open_locked()
                return
            self._consecutive += 1
            if self._state == CLOSED and \
                    self._consecutive >= self.failure_threshold:
                self._open_locked()

    def _return_probe(self, mode: str) -> None:
        """A probe slot consumed by an op that raised a non-backend error
        (caller bug / overload) goes back to the budget."""
        if mode != "probe":
            return
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_budget += 1

    def _short_circuited(self) -> None:
        if self._short_counter is not None:
            self._short_counter.increment()

    def _call(self, op: str, *args, **kwargs):
        mode = self._gate()
        if mode == "open":
            self._short_circuited()
            raise CircuitOpenError(
                f"circuit breaker open; {op} short-circuited")
        try:
            out = getattr(self._inner, op)(*args, **kwargs)
        except _NO_COUNT:
            self._return_probe(mode)
            raise
        except Exception:
            self._on_failure(mode)
            raise
        self._on_success(mode)
        return out

    # -- resync (open -> closed) ----------------------------------------------
    def _resync(self) -> None:
        """Discard both sides of every key that diverged while degraded:
        reset it on the device (stale pre-outage counters) and drop the
        host approximation — decisions return to bit-identical-vs-oracle.
        A resync failure (backend flapped again) re-opens the breaker with
        the touched set intact, so the next recovery retries it."""
        fb = self.fallback
        if fb is None:
            return
        touched = fb.touched()
        try:
            for algo, lid, key in touched:
                self._inner.reset_key(algo, lid, key)
        except Exception as exc:  # noqa: BLE001 — reopen, keep the set
            log.warning("post-recovery resync failed (%s); breaker "
                        "re-opens with %d key(s) still to reset",
                        exc, len(touched))
            with self._lock:
                self._open_locked()
            return
        fb.clear_state()
        self.resyncs_total += 1
        self._recorder.record("breaker.resync", keys=len(touched))
        if touched:
            log.info("resynced %d degraded key(s) onto the device",
                     len(touched))

    # -- decision surface with degraded fallback -------------------------------
    def acquire(self, algo: str, lid: int, key: str, permits: int,
                **kwargs) -> dict:
        mode = self._gate()
        if mode == "open":
            self._short_circuited()
            if self.fallback is not None:
                return self.fallback.acquire(algo, lid, key, permits)
            raise CircuitOpenError(
                "circuit breaker open; acquire short-circuited")
        try:
            out = self._inner.acquire(algo, lid, key, permits, **kwargs)
        except _NO_COUNT:
            self._return_probe(mode)
            raise
        except Exception:
            self._on_failure(mode)
            raise
        self._on_success(mode)
        if self.fallback is not None:
            # Healthy-path snapshot: the device's post-op counter seeds
            # this key's degraded budget if an outage starts.
            val = out.get("cache_value", out.get("remaining"))
            if val is not None:
                self.fallback.note_seen(algo, lid, key, int(val),
                                        self._clock_ms())
        return out

    def available_many(self, algo: str, lid: int, keys, **kwargs):
        mode = self._gate()
        if mode == "open":
            self._short_circuited()
            if self.fallback is not None:
                import numpy as np

                return np.asarray(
                    self.fallback.available(algo, lid, list(keys)),
                    dtype=np.int64)
            raise CircuitOpenError(
                "circuit breaker open; available_many short-circuited")
        try:
            out = self._inner.available_many(algo, lid, keys, **kwargs)
        except _NO_COUNT:
            self._return_probe(mode)
            raise
        except Exception:
            self._on_failure(mode)
            raise
        self._on_success(mode)
        return out

    def reset_key(self, algo: str, lid: int, key: str, **kwargs) -> None:
        mode = self._gate()
        if mode == "open":
            self._short_circuited()
            if self.fallback is not None:
                # Applied host-side now; reaches the device at resync.
                return self.fallback.reset(algo, lid, key)
            raise CircuitOpenError(
                "circuit breaker open; reset_key short-circuited")
        try:
            out = self._inner.reset_key(algo, lid, key, **kwargs)
        except _NO_COUNT:
            self._return_probe(mode)
            raise
        except Exception:
            self._on_failure(mode)
            raise
        self._on_success(mode)
        return out

    def register_limiter(self, algo: str, config) -> int:
        """Pass-through + policy capture so the degraded limiter can
        approximate this lid during an outage.  Not failure-counted:
        registration happens at boot, before traffic."""
        lid = self._inner.register_limiter(algo, config)
        if self.fallback is not None:
            self.fallback.register(lid, algo, config)
        return lid

    # -- plumbing -------------------------------------------------------------
    def __getattr__(self, name):
        # Non-gated surface (flush, engine, trace, probe_link, checkpoint
        # hooks, _batcher, ...) passes straight through, mirroring the
        # retry/chaos wrappers.
        return getattr(self._inner, name)

    @property
    def supports_device_batching(self):  # type: ignore[override]
        return getattr(self._inner, "supports_device_batching", False)

    def is_available(self) -> bool:
        # Health reporting, never failure-counted: the health endpoint
        # combines this with the breaker state itself.
        return self._inner.is_available()

    def close(self) -> None:
        self._inner.close()


def _wrap(op: str):
    def method(self, *args, **kwargs):
        return self._call(op, *args, **kwargs)

    method.__name__ = op
    return method


for _op in _GATED_PLAIN:
    setattr(CircuitBreakerStorage, _op, _wrap(_op))
# The abstract-method set was frozen before the loop filled the contract in.
CircuitBreakerStorage.__abstractmethods__ = frozenset()
