"""Retrying storage wrapper — the default-path analog of the reference's
per-op retry (RedisRateLimitStorage.java:155-178: every storage operation
runs through executeWithRetry, 3 attempts with linear 10/20/30 ms backoff,
then surfaces StorageException).

Composition order in service/wiring.py is ``retry(chaos(storage))`` so a
chaos drill exercises exactly the production failure path: transient
injected faults are absorbed by retries; only retry exhaustion escalates
to the service tier's fail-open policy (service/app.py).

Only REPLAY-SAFE ops are retried by default.  The Java wrapper retried
atomic per-key Redis commands, where a replay after a post-commit
transport fault charges at most one extra permit for one key — this
wrapper keeps that blast radius: single ``acquire`` (one request), reads,
resets, and the legacy per-key ops.  The multi-dispatch batch/stream ops
(``acquire_many*``, ``acquire_stream_ids``) mutate device state per
super-batch as they go; replaying them after a mid-stream fault would
re-charge every already-committed request in the stream, so they pass
through un-retried (their callers — bench loops, bulk ingest — own the
retry decision at whatever granularity they can make idempotent).
"""

from __future__ import annotations

from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.storage.chaos import _LEGACY_OPS
from ratelimiter_tpu.storage.errors import RetryPolicy

REPLAY_SAFE_OPS = ("acquire", "available_many", "reset_key") + _LEGACY_OPS
_PASSTHROUGH_OPS = ("acquire_many", "acquire_many_ids", "acquire_stream_ids",
                    "acquire_stream_strs")


class RetryingStorage(RateLimitStorage):
    """Wraps a backend; runs replay-safe ops through RetryPolicy."""

    def __init__(self, inner: RateLimitStorage,
                 policy: RetryPolicy | None = None):
        self._inner = inner
        self.policy = policy if policy is not None else RetryPolicy()

    def __getattr__(self, name):
        # Non-op surface (register_limiter, flush, engine, trace, ...)
        # passes straight through, mirroring FaultInjectingStorage.
        return getattr(self._inner, name)

    @property
    def supports_device_batching(self):  # type: ignore[override]
        return getattr(self._inner, "supports_device_batching", False)

    def close(self) -> None:  # shutdown is not retried
        self._inner.close()

    def is_available(self) -> bool:
        # Health checks report state; retrying one would mask flapping.
        return self._inner.is_available()


def _wrap(op: str):
    def method(self, *args, **kwargs):
        return self.policy.execute(
            lambda: getattr(self._inner, op)(*args, **kwargs))

    method.__name__ = op
    return method


def _passthrough(op: str):
    def method(self, *args, **kwargs):
        return getattr(self._inner, op)(*args, **kwargs)

    method.__name__ = op
    return method


for _op in REPLAY_SAFE_OPS:
    setattr(RetryingStorage, _op, _wrap(_op))
for _op in _PASSTHROUGH_OPS:
    setattr(RetryingStorage, _op, _passthrough(_op))
# The abstract-method set was frozen before the loop filled the contract in.
RetryingStorage.__abstractmethods__ = frozenset()
