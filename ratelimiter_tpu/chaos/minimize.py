"""Failure minimization: shrink a failing FaultPlan to a minimal
reproducer (ARCHITECTURE §17).

Two reductions, both sound because traffic at step ``s`` is a pure
function of ``(plan.seed, s)`` — dropping actions never shifts what
any surviving step does:

1. **prefix truncation** — a violation detected at step ``v`` cannot
   depend on anything after ``v``, so the plan is cut to ``v + 1``
   steps and actions at later steps dropped (one run to confirm);
2. **ddmin** — classic delta debugging over the remaining action list:
   remove chunks, keep any reduction that still reproduces a violation
   of the SAME invariant, refine the granularity, stop when single
   actions can't be removed (or the run budget is spent).

Each candidate costs one full fleet run, so the budget is explicit
(``max_runs``); the result records how many runs were spent and is
always a valid plan — worst case the original, failing one.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ratelimiter_tpu.chaos.plan import FaultPlan


def _run_fn(run_fn: Optional[Callable]) -> Callable:
    if run_fn is not None:
        return run_fn
    from ratelimiter_tpu.chaos.harness import run_plan

    return run_plan


def _same_failure(report: Dict, invariant: str) -> bool:
    v = report.get("violation")
    return v is not None and v.get("invariant") == invariant


def minimize(plan: FaultPlan, run_fn: Optional[Callable] = None,
             max_runs: int = 24) -> Dict:
    """Shrink ``plan`` to a minimal schedule still violating the same
    invariant.  Returns ``{"plan", "violation", "runs", "reduced_from",
    "reproduced"}`` — ``reproduced=False`` means the baseline run never
    failed and the plan comes back untouched."""
    run = _run_fn(run_fn)
    runs = 1
    base = run(plan)
    if base.get("violation") is None:
        return {"plan": plan, "violation": None, "runs": runs,
                "reduced_from": len(plan.actions), "reproduced": False}
    invariant = base["violation"]["invariant"]
    best = plan
    best_violation = base["violation"]

    # 1. Prefix truncation to the detection step.
    vstep = int(base["violation"]["step"])
    if vstep + 1 < int(plan.steps) and runs < max_runs:
        cand = FaultPlan(
            seed=plan.seed, steps=vstep + 1,
            topology=dict(plan.topology),
            actions=[a for a in plan.actions if a.step <= vstep],
            fault_rate=plan.fault_rate)
        rep = run(cand)
        runs += 1
        if _same_failure(rep, invariant):
            best = cand
            best_violation = rep["violation"]

    # 2. ddmin over the action list.
    actions = list(best.actions)
    n = 2
    while len(actions) >= 2 and n <= len(actions) and runs < max_runs:
        chunk = -(-len(actions) // n)  # ceil
        reduced = False
        for i in range(n):
            if runs >= max_runs:
                break
            subset = actions[:i * chunk] + actions[(i + 1) * chunk:]
            if len(subset) == len(actions):
                continue
            cand = best.with_actions(subset)
            rep = run(cand)
            runs += 1
            if _same_failure(rep, invariant):
                actions = subset
                best = cand
                best_violation = rep["violation"]
                n = max(n - 1, 2)
                reduced = True
                break
        if not reduced:
            if n >= len(actions):
                break
            n = min(n * 2, len(actions))

    return {"plan": best, "violation": best_violation, "runs": runs,
            "reduced_from": len(plan.actions), "reproduced": True}
