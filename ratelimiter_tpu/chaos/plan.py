"""Seeded multi-fault schedules (ARCHITECTURE §17).

A :class:`FaultPlan` is the chaos conductor's score: a list of timed
:class:`FaultAction` entries, generated deterministically from
``(seed, topology, steps, fault_rate)`` — the SAME inputs always yield
the SAME schedule, byte for byte, which is what makes every failure
replayable (chaos/replay.py) and minimizable (chaos/minimize.py).

The generator composes fault classes the hand-scripted drills
(storage/chaos.py) only ever exercised one at a time:

- **edge link** faults (``edge_partition`` / ``edge_flap`` /
  ``edge_delay`` / ``edge_garbage`` / ``edge_heal``) — applied to the
  aggregator's upstream link (a ``FaultInjectingProxy`` in the TCP
  topology, an in-process gate in the direct one);
- **shard lifecycle** faults (``kill_shard``, ``pause_shard`` /
  ``resume_shard``) — a kill is a crash the orchestrator must detect,
  fence, and promote around; a pause-then-resume is the classic zombie
  the fence must catch when the promotion happened mid-pause;
- **clock** faults (``clock_jump``) — step one cell's injected clock
  offset forward or backward (storage/tpu.py's now-source);
- **control/policy** churn (``storage_fault``, ``policy_bump``,
  ``controller_claim``) — benign-but-noisy traffic that the epoch-
  monotonicity invariant watches.

Every fault the generator emits auto-schedules its own heal a few steps
later (an unhealed schedule would only measure the outage, not the
recovery), and destructive actions respect per-target cooldowns so the
orchestrator's promote/re-seed cycle gets room to complete — chaos that
never lets the system heal proves nothing about convergence.

``include_defects=True`` (test fixtures only — never the CI gate)
plants a deliberately-broken action (``epoch_rollback``, ``pool_leak``)
so the invariant monitor, minimizer, and artifact replay can be proven
against a KNOWN violation.
"""

from __future__ import annotations

import dataclasses
import json
import random
from typing import Dict, List, Optional

# Ops whose only purpose is violating an invariant on purpose (fixture
# plans); the generator emits them only under include_defects=True.
DEFECT_OPS = ("epoch_rollback", "pool_leak")

FAULT_OPS = (
    "edge_partition", "edge_flap", "edge_delay", "edge_garbage",
    "edge_heal", "kill_shard", "pause_shard", "resume_shard",
    "clock_jump", "storage_fault", "policy_bump", "controller_claim",
)

DEFAULT_TOPOLOGY: Dict = {
    "cells": 2,
    "shards_per_cell": 2,
    "slots_per_shard": 128,
    "n_direct_keys": 24,
    "n_lease_keys": 6,
    "n_edge_keys": 4,
    "edge": "direct",          # "direct" (in-process) or "tcp" (proxy)
    "budget": 12,
    "bulk_budget": 64,
    "slice_budget": 8,
    "lease_ttl_ms": 5000.0,
    "probe_interval_ms": 50.0,
    "suspect_threshold": 3,
    "hysteresis_ms": 200.0,
    "liveness_window": 10,
}


@dataclasses.dataclass(frozen=True)
class FaultAction:
    """One timed conductor action: at schedule ``step``, apply ``op``
    with ``params`` (cell/shard targets, magnitudes)."""

    step: int
    op: str
    params: Dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict:
        return {"step": int(self.step), "op": self.op,
                "params": dict(self.params)}

    @classmethod
    def from_dict(cls, d: Dict) -> "FaultAction":
        return cls(step=int(d["step"]), op=str(d["op"]),
                   params=dict(d.get("params", {})))


@dataclasses.dataclass
class FaultPlan:
    """A deterministic, replayable chaos schedule."""

    seed: int
    steps: int
    topology: Dict
    actions: List[FaultAction]
    fault_rate: float = 0.5

    def by_step(self) -> Dict[int, List[FaultAction]]:
        out: Dict[int, List[FaultAction]] = {}
        for a in self.actions:
            out.setdefault(int(a.step), []).append(a)
        return out

    def with_actions(self, actions: List[FaultAction]) -> "FaultPlan":
        """Same schedule frame (seed/steps/topology — traffic is a pure
        function of those), different action list: the minimizer's
        reduction step."""
        return FaultPlan(seed=self.seed, steps=self.steps,
                         topology=dict(self.topology),
                         actions=list(actions),
                         fault_rate=self.fault_rate)

    # -- serialization ---------------------------------------------------------
    def to_json(self) -> Dict:
        return {
            "version": 1,
            "seed": int(self.seed),
            "steps": int(self.steps),
            "fault_rate": float(self.fault_rate),
            "topology": dict(self.topology),
            "actions": [a.to_dict() for a in self.actions],
        }

    @classmethod
    def from_json(cls, d: Dict) -> "FaultPlan":
        return cls(seed=int(d["seed"]), steps=int(d["steps"]),
                   topology=dict(d.get("topology", {})),
                   actions=[FaultAction.from_dict(a)
                            for a in d.get("actions", [])],
                   fault_rate=float(d.get("fault_rate", 0.5)))

    def dumps(self) -> str:
        return json.dumps(self.to_json(), sort_keys=True)

    # -- generation ------------------------------------------------------------
    @classmethod
    def generate(cls, seed: int, topology: Optional[Dict] = None,
                 steps: int = 24, fault_rate: float = 0.5,
                 include_defects: bool = False) -> "FaultPlan":
        """Deterministically generate a schedule.  Pure function of the
        arguments: ``generate(s, t, n, r)`` is the plan's identity —
        an artifact that records them reproduces the plan exactly.

        The generator keeps the schedule RUNNABLE, not just random:

        - the edge link carries at most one fault at a time, healed
          1–3 steps later;
        - at most one shard per cell is down at once, and a killed or
          paused shard gets a cooldown long enough for the orchestrator
          to promote and re-seed before the next hit;
        - pauses always schedule their resume (the conductor's zombie
          probe runs at resume time);
        - clock jumps are bounded (|jump| <= 4 s) so TTL accounting is
          stressed without making every lease trivially dead.
        """
        topo = dict(DEFAULT_TOPOLOGY)
        topo.update(topology or {})
        rng = random.Random(int(seed))
        steps = int(steps)
        cells = int(topo["cells"])
        shards = int(topo["shards_per_cell"])
        actions: List[FaultAction] = []

        edge_busy_until = -1
        # (cell, shard) -> first step the shard may be targeted again.
        shard_cooldown = {(c, q): 0 for c in range(cells)
                         for q in range(shards)}
        # Promotion settle budget: detect + hysteresis + re-seed ticks.
        settle = int(topo["suspect_threshold"]
                     + topo["hysteresis_ms"] / topo["probe_interval_ms"]
                     + 6)

        weighted = (
            ("edge_partition", 3), ("edge_flap", 1), ("edge_delay", 1),
            ("edge_garbage", 1), ("kill_shard", 3), ("pause_shard", 3),
            ("clock_jump", 3), ("storage_fault", 2), ("policy_bump", 2),
            ("controller_claim", 2),
        )
        ops = [op for op, w in weighted for _ in range(w)]

        def free_shard(step: int):
            cands = [(c, q) for (c, q), until in sorted(
                shard_cooldown.items()) if until <= step]
            return rng.choice(cands) if cands else None

        for step in range(steps):
            if rng.random() >= float(fault_rate):
                continue
            op = rng.choice(ops)
            if op.startswith("edge_"):
                if step <= edge_busy_until:
                    continue
                params: Dict = {}
                if op == "edge_partition":
                    params["direction"] = rng.choice(["both", "up", "down"])
                elif op == "edge_flap":
                    params["period_s"] = rng.choice([0.05, 0.1, 0.2])
                elif op == "edge_delay":
                    params["delay_ms"] = rng.choice([1.0, 2.0, 5.0])
                elif op == "edge_garbage":
                    params["n"] = rng.choice([8, 32, 64])
                heal_at = step + rng.randint(1, 3)
                actions.append(FaultAction(step, op, params))
                actions.append(FaultAction(heal_at, "edge_heal"))
                edge_busy_until = heal_at
            elif op == "kill_shard":
                target = free_shard(step)
                if target is None:
                    continue
                c, q = target
                actions.append(FaultAction(step, "kill_shard",
                                           {"cell": c, "shard": q}))
                shard_cooldown[(c, q)] = step + settle
            elif op == "pause_shard":
                target = free_shard(step)
                if target is None:
                    continue
                c, q = target
                resume_at = step + rng.randint(2, 5)
                actions.append(FaultAction(step, "pause_shard",
                                           {"cell": c, "shard": q}))
                actions.append(FaultAction(resume_at, "resume_shard",
                                           {"cell": c, "shard": q}))
                # A pause that outlived detection promoted a replacement;
                # give the re-seed the same settle room a kill gets.
                shard_cooldown[(c, q)] = resume_at + settle
            elif op == "clock_jump":
                actions.append(FaultAction(step, "clock_jump", {
                    "cell": rng.randrange(cells),
                    "ms": rng.choice([-250, -40, 60, 250, 1200, 4000]),
                }))
            elif op == "storage_fault":
                actions.append(FaultAction(step, "storage_fault",
                                           {"n": rng.randint(1, 3)}))
            elif op == "policy_bump":
                actions.append(FaultAction(step, "policy_bump"))
            elif op == "controller_claim":
                actions.append(FaultAction(step, "controller_claim",
                                           {"cell": rng.randrange(cells)}))

        if include_defects:
            at = rng.randint(2, max(2, steps - 2))
            actions.append(FaultAction(
                at, rng.choice(list(DEFECT_OPS)),
                {"cell": rng.randrange(cells)}))

        actions.sort(key=lambda a: (a.step, a.op))
        return cls(seed=int(seed), steps=steps, topology=topo,
                   actions=actions, fault_rate=float(fault_rate))
