"""The conductor's unified actor layer: one ``apply(action)`` surface
over every fault primitive the repo already has (ARCHITECTURE §17).

Actions come from a :class:`~ratelimiter_tpu.chaos.plan.FaultPlan` and
land on the live :class:`~ratelimiter_tpu.chaos.harness.FleetHarness`:

- edge-link actions drive the ``FaultInjectingProxy`` (TCP topology)
  or the in-process :class:`GatedTransport` (direct topology);
- shard actions flip the per-shard probe flags the orchestrator's
  failure detector reads — a kill ships the replication backlog first
  (the crash loses nothing the wire already carried, which is what
  keeps the oracle reconciliation exact), a pause preserves state and
  the RESUME runs the zombie probe: if a promotion happened mid-pause,
  the old backend must answer direct dispatch with ``FencedError``, and
  serving instead is reported as a ``zombie-serving`` violation;
- clock actions step one cell's skew offset (every storage in the cell
  reads ``base_clock + skew``, mirroring storage/tpu.py's injectable
  process offset for real deployments);
- ``storage_fault`` arms :class:`LeaseFaultGate` (the deterministic
  in-process stand-in for ``FaultInjectingStorage``'s forced-failure
  mode) on the lease path;
- defect actions (``epoch_rollback``, ``pool_leak``) corrupt state ON
  PURPOSE — they exist so tests can prove the monitor catches, the
  minimizer isolates, and the artifact replays a real violation.

Everything here is deterministic given the plan: no wall clocks, no
RNG — replaying the same actions against a fresh harness reproduces
the same trajectory bit for bit (TCP-topology timing faults excepted;
those can shift latencies but never invariant outcomes).

:class:`ProcActor` is the real-subprocess sibling used by the
cross-host drills and the slow soak: it wraps a spawned ``hostproc``/
``edgeproc`` and speaks in signals — SIGSTOP/SIGCONT for the pause
(the classic zombie shape), SIGTERM for the graceful stop the
processes now honor (drain, release serving lease, exit 0), SIGKILL
for the crash.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ratelimiter_tpu.storage.errors import StorageException


class GatedTransport:
    """In-process stand-in for a partitioned edge upstream link: while
    ``cut``, every call raises ``StorageException`` (the aggregator's
    callers see exactly the timeout/error a dead TCP link produces,
    with zero wall-clock cost and full determinism)."""

    def __init__(self, inner):
        self._inner = inner
        self.cut = False
        self.drops = 0

    def __getattr__(self, name):
        target = getattr(self._inner, name)
        if not callable(target):
            return target

        def call(*args, **kwargs):
            if self.cut:
                self.drops += 1
                raise StorageException("edge upstream link partitioned "
                                       "(chaos conductor)")
            return target(*args, **kwargs)

        return call


class LeaseFaultGate:
    """Deterministic storage-fault injector for the lease path: wraps
    the serving storage and force-fails the next ``n`` lease device ops
    (``lease_reserve`` / ``lease_credit``) with ``StorageException`` —
    the manager's deny/refuse paths under storage trouble, with none of
    ``FaultInjectingStorage``'s RNG (the conductor's schedule IS the
    randomness source)."""

    FAIL_OPS = ("lease_reserve", "lease_credit")

    def __init__(self, inner):
        self._inner = inner
        self._forced = 0
        self.injected = 0

    def fail_next(self, n: int = 1) -> None:
        self._forced += int(n)

    def heal(self) -> None:
        self._forced = 0

    def __getattr__(self, name):
        target = getattr(self._inner, name)
        if not callable(target) or name not in self.FAIL_OPS:
            return target

        def call(*args, **kwargs):
            if self._forced > 0:
                self._forced -= 1
                self.injected += 1
                raise StorageException(
                    f"injected lease-path failure in {name} "
                    "(chaos conductor)")
            return target(*args, **kwargs)

        return call


class Actors:
    """Dispatch one plan action onto the harness.  Raises
    ``InvariantViolation`` (via the harness's monitor hook) only from
    the zombie probe — every other action just mutates fault state."""

    def __init__(self, harness):
        self.h = harness
        self.applied: List[Dict] = []

    def apply(self, action, step: int) -> None:
        fn = getattr(self, "_op_" + action.op, None)
        if fn is None:
            raise ValueError(f"unknown chaos op: {action.op!r}")
        fn(step, **dict(action.params))
        self.applied.append(action.to_dict())

    # -- edge link -------------------------------------------------------------
    def _op_edge_partition(self, step, direction: str = "both") -> None:
        self.h.edge_link.partition(direction)

    def _op_edge_flap(self, step, period_s: float = 0.1) -> None:
        self.h.edge_link.flap(float(period_s))

    def _op_edge_delay(self, step, delay_ms: float = 2.0) -> None:
        self.h.edge_link.delay(float(delay_ms))

    def _op_edge_garbage(self, step, n: int = 32) -> None:
        self.h.edge_link.garbage(int(n))

    def _op_edge_heal(self, step) -> None:
        self.h.edge_link.heal()

    # -- shard lifecycle -------------------------------------------------------
    def _op_kill_shard(self, step, cell: int = 0, shard: int = 0) -> None:
        c = self.h.cells[int(cell)]
        # Ship the replication backlog first: the crash takes the
        # process, not bytes already on the wire — and it is what keeps
        # the post-promotion oracle reconciliation exact (the drills'
        # "final deterministic epoch" discipline).
        c.repl.ship_now()
        f = c.flags[int(shard)]
        f["down"] = True
        f["paused"] = False
        f["at_promotions"] = c.orch.promotions
        f["backend"] = c.serving_backend(int(shard))

    def _op_pause_shard(self, step, cell: int = 0, shard: int = 0) -> None:
        c = self.h.cells[int(cell)]
        f = c.flags[int(shard)]
        f["down"] = True
        f["paused"] = True
        f["at_promotions"] = c.orch.promotions
        f["backend"] = c.serving_backend(int(shard))

    def _op_resume_shard(self, step, cell: int = 0,
                         shard: int = 0) -> None:
        c = self.h.cells[int(cell)]
        f = c.flags[int(shard)]
        if not f.get("paused"):
            return  # resume of a shard that was killed meanwhile: no-op
        # Promotion of THIS shard, not the global counter: a concurrent
        # promotion elsewhere in the cell must not flag this backend.
        promoted_during_pause = (
            c.serving_backend(int(shard)) is not f.get("backend"))
        f["down"] = False
        f["paused"] = False
        if promoted_during_pause:
            # The classic zombie: a paused-then-resumed primary whose
            # keyspace was promoted away mid-pause.  Its old backend
            # MUST refuse direct dispatch with the typed fence error.
            self.h.zombie_probe(c, int(shard), f.get("backend"), step)

    # -- clock -----------------------------------------------------------------
    def _op_clock_jump(self, step, cell: int = 0, ms: int = 0) -> None:
        self.h.skew[int(cell)] += int(ms)

    # -- lease-path storage faults --------------------------------------------
    def _op_storage_fault(self, step, n: int = 1) -> None:
        self.h.gate.fail_next(int(n))

    # -- control-plane churn ---------------------------------------------------
    def _op_policy_bump(self, step) -> None:
        c0 = self.h.cells[0]
        c0.primary.set_policy(c0.lid_lease, c0.cfg_lease)

    def _op_controller_claim(self, step, cell: int = 0) -> None:
        seat = self.h.cells[int(cell)].seat
        seat.claim(f"ctl-{int(cell)}", seat.epoch + 1, ttl_ms=60_000.0)

    # -- deliberate defects (fixtures) ----------------------------------------
    def _op_epoch_rollback(self, step, cell: int = 0) -> None:
        # Regress the cell's fence epoch by force — the epoch-
        # monotonicity invariant must catch this at the step's check.
        self.h.cells[int(cell)].primary._fence_epoch -= 1

    def _op_pool_leak(self, step, cell: int = 0) -> None:
        # Mint one permit out of thin air in the first live bulk pool —
        # the conservation invariant must catch this at the step's
        # check.  (No pool yet: leak into the one the next edge grant
        # creates, by retrying on the following step via the monitor's
        # pending-defect latch.)
        pools = sorted(self.h.agg._pools.items())
        if pools:
            pools[0][1].remaining += 1
        else:
            self.h.pending_pool_leak = True


class ProcActor:
    """A real ``hostproc``/``edgeproc`` subprocess under conductor
    control.  ``spawn`` blocks for the one-line ready JSON; the fault
    verbs are signals:

    - :meth:`pause` / :meth:`resume` — SIGSTOP/SIGCONT (the zombie
      shape: the process keeps ALL state and its sockets, it just
      stops scheduling);
    - :meth:`stop_graceful` — SIGTERM; the processes drain, release
      the serving lease, and exit 0 (distinguishable from a crash);
    - :meth:`kill` — SIGKILL, the crash.
    """

    def __init__(self, argv: List[str], env: Optional[Dict] = None):
        self.argv = list(argv)
        self.env = dict(os.environ, **(env or {}))
        self.proc: Optional[subprocess.Popen] = None
        self.ready: Dict = {}

    def spawn(self, timeout_s: float = 60.0) -> Dict:
        self.proc = subprocess.Popen(
            [sys.executable, "-m"] + self.argv,
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, env=self.env)
        line = self.proc.stdout.readline().decode("utf-8", "replace")
        if not line:
            err = self.proc.stderr.read().decode("utf-8", "replace")
            raise RuntimeError(f"{self.argv[0]} died before ready: {err}")
        self.ready = json.loads(line)
        return self.ready

    @property
    def pid(self) -> int:
        return self.proc.pid

    def pause(self) -> None:
        os.kill(self.pid, signal.SIGSTOP)

    def resume(self) -> None:
        os.kill(self.pid, signal.SIGCONT)

    def stop_graceful(self, timeout_s: float = 20.0) -> int:
        """SIGTERM and reap; returns the exit code (0 = the drain/
        release path ran)."""
        self.proc.send_signal(signal.SIGTERM)
        return self.wait(timeout_s)

    def stop_eof(self, timeout_s: float = 20.0) -> int:
        """Close stdin (the launcher-pipe stop the drills use)."""
        try:
            self.proc.stdin.close()
        except OSError:
            pass
        return self.wait(timeout_s)

    def kill(self) -> int:
        self.proc.kill()
        return self.wait(10.0)

    def wait(self, timeout_s: float) -> int:
        try:
            return self.proc.wait(timeout=timeout_s)
        finally:
            for pipe in (self.proc.stdin, self.proc.stdout,
                         self.proc.stderr):
                try:
                    if pipe is not None:
                        pipe.close()
                except OSError:
                    pass

    def close(self) -> None:
        if self.proc is None or self.proc.returncode is not None:
            return
        try:
            self.resume()  # a SIGSTOPped process ignores SIGKILL queueing
        except (OSError, ProcessLookupError):
            pass
        try:
            self.proc.kill()
            self.proc.wait(timeout=10.0)
        except (OSError, subprocess.TimeoutExpired):
            pass
        time.sleep(0)  # let the reaper run before pipes close
