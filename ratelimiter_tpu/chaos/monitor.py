"""The fleet-wide invariant monitor (ARCHITECTURE §17).

One :class:`InvariantMonitor` watches a running
:class:`~ratelimiter_tpu.chaos.harness.FleetHarness` and, after EVERY
conductor step, checks the whole invariant catalog — the union of what
the hand-scripted drills each assert in isolation:

========================  =====================================================
invariant                  claim
========================  =====================================================
``oracle-divergence``      healthy-path decisions are bit-identical to
                           ``semantics/oracle.py`` (and the final lease
                           reserve/credit replay reconciles exactly)
``admission-bound``        per-key over-admission stays within the documented
                           bound: every outstanding lease budget <= the
                           configured cap <= the policy's ``max_permits``, and
                           cumulative ``over_admission`` <= revocations x cap
``conservation``           every BulkPool conserves ``remaining + sliced_out +
                           used_pending == budget + deficit``
``epoch-monotonicity``     fence epochs, controller-seat epochs, and policy
                           generations NEVER regress
``liveness``               on fault-free steps the system keeps admitting:
                           a dedicated liveness probe per path (direct /
                           leased / edge) may not be denied for
                           ``liveness_window`` consecutive healthy steps
``zombie-serving``         a paused-then-resumed backend whose keyspace was
                           promoted away answers direct dispatch with
                           ``FencedError``, never with a decision
========================  =====================================================

A failed check raises :class:`InvariantViolation` — the harness stops
the run at that step and reports ``(invariant, step, detail)``, which
is exactly the tuple the minimizer (chaos/minimize.py) preserves while
shrinking the schedule and the artifact (chaos/replay.py) replays.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class InvariantViolation(Exception):
    """One invariant broke at one step; carries the replay identity."""

    def __init__(self, invariant: str, step: int, detail: str):
        super().__init__(f"[{invariant}] step {step}: {detail}")
        self.invariant = str(invariant)
        self.step = int(step)
        self.detail = str(detail)

    def to_dict(self) -> Dict:
        return {"invariant": self.invariant, "step": self.step,
                "detail": self.detail}


class InvariantMonitor:
    """Per-step checker over the harness's live state."""

    def __init__(self, harness):
        self.h = harness
        self.checks_total = 0
        self.violations: List[Dict] = []
        # Watched epochs: watermark per (series, cell) — any regression
        # is a violation (the fence/authority/policy monotonicity the
        # whole design leans on).
        self._epochs: Dict[Tuple[str, int], int] = {}
        # Liveness: consecutive HEALTHY steps each probe path was
        # denied on (reset by a successful probe or a faulted step).
        self.unserved = {"direct": 0, "lease": 0, "edge": 0}

    # -- reporting -------------------------------------------------------------
    def violation(self, invariant: str, step: int, detail: str) -> None:
        v = InvariantViolation(invariant, step, detail)
        self.violations.append(v.to_dict())
        raise v

    # -- probe bookkeeping (harness calls these during traffic) ---------------
    def note_probe(self, path: str, step: int, served: bool,
                   healthy: bool) -> None:
        """One liveness probe outcome.  Only healthy (fault-free for
        that path) steps count toward the consecutive-denial window —
        a denial during an armed fault is the system being correctly
        unavailable, not a liveness bug."""
        if served:
            self.unserved[path] = 0
        elif healthy:
            self.unserved[path] += 1
        else:
            self.unserved[path] = 0

    # -- the per-step check ----------------------------------------------------
    def check(self, step: int) -> None:
        self.checks_total += 1
        self._check_oracle(step)
        self._check_conservation(step)
        self._check_admission_bound(step)
        self._check_epochs(step)
        self._check_liveness(step)

    def _check_oracle(self, step: int) -> None:
        n = self.h.step_mismatches
        if n:
            self.violation(
                "oracle-divergence", step,
                f"{n} direct decision(s) diverged from the oracle "
                f"this step (of {self.h.step_decisions})")

    def _check_conservation(self, step: int) -> None:
        agg = getattr(self.h, "agg", None)
        if agg is None:
            return
        with agg._lock:
            live = list(agg._pools.values())
            dead = list(agg._dead)
        for pool in live:
            try:
                pool.check_conservation()
            except AssertionError as e:
                self.violation("conservation", step, str(e))
        # Retired carcasses legitimately leak the identity's right-hand
        # side as their final burn report flushes (used_pending drains
        # upstream while the stale budget stays); what must still hold
        # is that nothing went NEGATIVE — a negative ledger is minted
        # permits, the one thing retirement can never do.
        for pool in dead:
            if (pool.remaining < 0 or pool.sliced_out < 0
                    or pool.used_pending < 0 or pool.deficit < 0):
                self.violation(
                    "conservation", step,
                    f"retired pool ({pool.lid},{pool.key!r}) went "
                    f"negative: rem={pool.remaining} "
                    f"out={pool.sliced_out} "
                    f"pending={pool.used_pending} "
                    f"deficit={pool.deficit}")

    def _check_admission_bound(self, step: int) -> None:
        mgr = getattr(self.h, "mgr", None)
        if mgr is None:
            return
        cap = max(mgr.max_budget,
                  getattr(mgr, "max_bulk_budget", 0) or 0)
        policy_cap = self.h.cells[0].cfg_lease.max_permits
        for lease in mgr.table:
            bound = (getattr(mgr, "max_bulk_budget", 0) or cap) \
                if lease.bulk else mgr.max_budget
            if lease.budget > bound or bound > policy_cap:
                self.violation(
                    "admission-bound", step,
                    f"lease ({lease.lid},{lease.key!r}) budget "
                    f"{lease.budget} exceeds cap {bound} "
                    f"(policy max_permits {policy_cap})")
        # Cumulative: every over-admitted permit traces to one revoked
        # or expired lease, each worth at most one full budget.
        events = mgr.revoked_total + mgr.expired_total
        if mgr.over_admission_total > events * cap:
            self.violation(
                "admission-bound", step,
                f"over_admission {mgr.over_admission_total} exceeds "
                f"{events} revocations/expiries x cap {cap}")

    def _watch(self, step: int, series: str, cell: int,
               value: Optional[int]) -> None:
        if value is None:
            return
        key = (series, int(cell))
        last = self._epochs.get(key)
        if last is not None and int(value) < last:
            self.violation(
                "epoch-monotonicity", step,
                f"{series} epoch regressed in cell {cell}: "
                f"{last} -> {value}")
        self._epochs[key] = int(value)

    def _check_epochs(self, step: int) -> None:
        for c in self.h.cells:
            self._watch(step, "orchestrator-fence", c.idx,
                        c.orch.fence_epoch)
            self._watch(step, "storage-fence", c.idx,
                        c.primary._fence_epoch)
            self._watch(step, "controller-seat", c.idx, c.seat.epoch)
            gen = c.policy_generation()
            self._watch(step, "policy-generation", c.idx, gen)

    def _check_liveness(self, step: int) -> None:
        window = int(self.h.topo["liveness_window"])
        for path, n in self.unserved.items():
            if n >= window:
                self.violation(
                    "liveness", step,
                    f"{path} liveness probe denied on {n} consecutive "
                    f"fault-free steps (window {window})")
