"""FleetHarness: the chaos conductor's stage (ARCHITECTURE §17).

Boots the FULL stack in one process — ``cells`` cells, each a sharded
primary (``parallel/``) behind a ``ShardFailoverRouter`` with an N+1
``ShardStandbySet``, per-shard replication, a ``FailoverOrchestrator``
on a simulated probe clock, and a ``ControllerSeat``; cell 0 adds the
lease tier (``LeaseManager`` + a strict leased client) and the edge
tier (``EdgeAggregator`` subleasing to two edge clients, upstream
either in-process or through a real TCP ``FaultInjectingProxy``) —
then executes a :class:`~ratelimiter_tpu.chaos.plan.FaultPlan` step by
step, driving deterministic traffic between fault actions and running
the :class:`~ratelimiter_tpu.chaos.monitor.InvariantMonitor` after
every step.

Determinism contract (what makes minimize/replay work):

- the decision clock is a shared simulated millisecond counter plus a
  per-cell skew offset (the ``clock_jump`` actor's target — the same
  injection surface ``storage/tpu.py`` exposes per process);
- traffic at step ``s`` is a pure function of ``(plan.seed, s)`` —
  removing actions from the schedule never shifts what traffic any
  surviving step carries, which is the property delta-debugging needs;
- the oracle mirror reproduces the storage stamp discipline exactly:
  each wave's expected stamp is ``max(serving_storage._last_stamp,
  cell_now)`` per serving storage (the backward clamp), and after
  every orchestrator tick all of a cell's storages are synced to the
  cell's stamp high-water mark so a promotion can never hand a key a
  stamp from the past.

In-process fictions, stated honestly: a "killed" shard is a probe that
answers False — replication is shipped at the kill and at every step
end, so the state a promotion restores is exactly what a real crash
with a drained wire leaves (the drills' discipline), and traffic the
doomed backend serves before the fence lands stays oracle-tracked.
Pause/resume is the zombie shape: on resume, if the shard's serving
backend was replaced mid-pause, the OLD backend is dispatched directly
and must raise ``FencedError`` — serving instead is the
``zombie-serving`` violation.  Real-subprocess kills/SIGSTOP live in
:class:`~ratelimiter_tpu.chaos.actors.ProcActor` and the slow soak.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

import numpy as np

from ratelimiter_tpu.chaos.actors import (
    Actors,
    GatedTransport,
    LeaseFaultGate,
)
from ratelimiter_tpu.chaos.monitor import InvariantMonitor, InvariantViolation
from ratelimiter_tpu.chaos.plan import FaultPlan
from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.leases import DirectTransport, LeaseClient, LeaseManager
from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh
from ratelimiter_tpu.parallel.sharded import shard_of_int_keys, shard_of_key
from ratelimiter_tpu.replication import (
    FailoverOrchestrator,
    OrchestratorConfig,
    ShardedReplicationLog,
    ShardedReplicator,
    ShardFailoverRouter,
    ShardStandbySet,
)
from ratelimiter_tpu.replication.control import ControllerSeat
from ratelimiter_tpu.semantics.oracle import TokenBucketOracle
from ratelimiter_tpu.storage.errors import FencedError, StorageException
from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

_EPOCH_MS = 1_753_000_000_000


class _Cell:
    """One cell: sharded primary + router + standbys + replication +
    orchestrator + controller seat, all on the harness's clocks."""

    def __init__(self, idx: int, topo: Dict, base: Dict,
                 skew: List[int], sim: Dict):
        self.idx = int(idx)
        self.topo = topo
        self.n_shards = int(topo["shards_per_cell"])
        slots = int(topo["slots_per_shard"])
        self.now = lambda: int(base["t"]) + int(skew[self.idx])
        self.engine = ShardedDeviceEngine(
            slots_per_shard=slots, table=LimiterTable(),
            mesh=make_mesh(n_devices=self.n_shards))
        self.primary = TpuBatchedStorage(engine=self.engine,
                                         clock_ms=self.now)
        self.router = ShardFailoverRouter(self.primary)
        self.cfg_tb = RateLimitConfig(max_permits=25, window_ms=2000,
                                      refill_rate=8.0)
        self.lid_tb = self.primary.register_limiter("tb", self.cfg_tb)
        # Lease-tier lids (registered in every cell so topologies stay
        # congruent; only cell 0 runs lease/edge traffic).
        self.cfg_lease = RateLimitConfig(max_permits=1 << 14,
                                         window_ms=60_000,
                                         refill_rate=1000.0)
        self.lid_lease = self.primary.register_limiter(
            "tb", self.cfg_lease)
        self.lid_edge = self.primary.register_limiter(
            "tb", self.cfg_lease)

        def standby_factory():
            return TpuBatchedStorage(num_slots=slots, clock_ms=self.now)

        self.standby_factory = standby_factory
        self.mesh_set = ShardStandbySet(self.n_shards, standby_factory)
        self.repl = ShardedReplicator(
            ShardedReplicationLog(self.primary),
            self.mesh_set.in_process_sinks())
        # Per-shard fault flags the conductor's actors flip; the probe
        # reads them (a "down" shard answers False until ITS replacement
        # is installed, exactly the drills' dead-flag discipline).
        self.flags = [{"down": False, "paused": False,
                       "at_promotions": 0, "backend": None}
                      for _ in range(self.n_shards)]
        ocfg = OrchestratorConfig(
            probe_interval_ms=float(topo["probe_interval_ms"]),
            suspect_threshold=int(topo["suspect_threshold"]),
            hysteresis_ms=float(topo["hysteresis_ms"]),
            promote_backoff_ms=1.0)
        self.ocfg = ocfg

        def probe(q):
            f = self.flags[q]
            if f["down"] and self.orch.promotions == f["at_promotions"]:
                return False
            return True

        self.orch = FailoverOrchestrator(
            self.router, self.mesh_set, self.repl,
            standby_factory=standby_factory, config=ocfg, probe=probe,
            clock=lambda: sim["s"], sleep=lambda s: None)
        self.seat = ControllerSeat(clock=lambda: sim["s"])
        # Direct-path keyspace: ids 0..n_direct-1 are traffic, id
        # n_direct is the liveness probe's reserved key.
        n_direct = int(topo["n_direct_keys"])
        self.key_shard = shard_of_int_keys(
            np.arange(n_direct + 1, dtype=np.int64), self.n_shards)
        self.oracle = TokenBucketOracle(self.cfg_tb)

    def serving_backend(self, q: int):
        return self.router.replacements.get(int(q), self.primary)

    def blocked(self, q: int) -> bool:
        f = self.flags[int(q)]
        return bool(f["down"]
                    and self.orch.promotions == f["at_promotions"])

    def policy_generation(self) -> Optional[int]:
        try:
            return int(self.engine.table.row_generation(self.lid_lease))
        except Exception:  # noqa: BLE001 — optional introspection
            return None

    def sync_stamps(self) -> None:
        """Raise every storage in the cell to the cell's stamp
        high-water mark, so a promotion never serves a key a stamp
        older than one it already saw (the per-key monotonicity the
        oracle mirror depends on)."""
        storages = ([self.primary]
                    + list(self.router.replacements.values())
                    + list(self.mesh_set.storages))
        m = max(getattr(s, "_last_stamp", 0) for s in storages)
        for s in storages:
            if getattr(s, "_last_stamp", 0) < m:
                s._last_stamp = m

    def close(self) -> None:
        for closer in (self.orch.close, self.repl.stop,
                       self.router.close, self.mesh_set.close):
            try:
                closer()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


class DirectEdgeLink:
    """In-process edge link: faults collapse to an atomic cut of the
    gated upstream transport (delay has no in-process analogue and is
    only counted; garbage/flap desync a framed link, so both read as an
    outage until healed)."""

    def __init__(self, gate: GatedTransport):
        self.gate = gate
        self.faults = 0

    @property
    def healthy(self) -> bool:
        return not self.gate.cut

    def partition(self, direction: str = "both") -> None:
        self.faults += 1
        self.gate.cut = True

    def flap(self, period_s: float = 0.1) -> None:
        self.faults += 1
        self.gate.cut = True

    def garbage(self, n: int = 32) -> None:
        self.faults += 1
        self.gate.cut = True

    def delay(self, delay_ms: float = 2.0) -> None:
        self.faults += 1  # counted; zero in-process latency dimension

    def heal(self) -> None:
        self.gate.cut = False

    def close(self) -> None:
        self.gate.cut = False


class TcpEdgeLink:
    """Real-wire edge link: a ``FaultInjectingProxy`` between the
    aggregator's ``SidecarClient`` and a sidecar front for the core.
    ``heal`` reconnects the upstream client (a partitioned/garbaged
    stream is desynced for good — exactly like production)."""

    def __init__(self, proxy, agg, client_factory):
        self.proxy = proxy
        self.agg = agg
        self._client_factory = client_factory
        self.faults = 0
        self._cut = False

    @property
    def healthy(self) -> bool:
        return not self._cut

    def partition(self, direction: str = "both") -> None:
        self.faults += 1
        self._cut = True
        self.proxy.partition(direction)

    def flap(self, period_s: float = 0.1) -> None:
        self.faults += 1
        self._cut = True
        self.proxy.flap(float(period_s))

    def garbage(self, n: int = 32) -> None:
        self.faults += 1
        self._cut = True
        self.proxy.set_fault("garbage", n=int(n))

    def delay(self, delay_ms: float = 2.0) -> None:
        self.faults += 1
        self._cut = True
        self.proxy.set_fault("delay", delay_ms=float(delay_ms))

    def heal(self) -> None:
        self.proxy.heal()
        old, self.agg.upstream = self.agg.upstream, \
            self._client_factory()
        try:
            old.close()
        except Exception:  # noqa: BLE001 — old stream may be dead
            pass
        self._cut = False

    def close(self) -> None:
        try:
            self.agg.upstream.close()
        except Exception:  # noqa: BLE001
            pass
        self.proxy.stop()


class FleetHarness:
    """Execute one FaultPlan against a freshly-booted fleet."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.topo = dict(plan.topology)
        self.base = {"t": _EPOCH_MS}
        self.sim = {"s": 0.0}
        self.skew = [0] * int(self.topo["cells"])
        self.cells = [_Cell(i, self.topo, self.base, self.skew,
                            self.sim)
                      for i in range(int(self.topo["cells"]))]
        c0 = self.cells[0]
        self.n_direct = int(self.topo["n_direct_keys"])
        # Lease tier (cell 0): manager behind the deterministic
        # storage-fault gate, one strict leased client.
        self.gate = LeaseFaultGate(c0.router)
        self.mgr = LeaseManager(
            self.gate,
            default_budget=int(self.topo["budget"]),
            max_budget=int(self.topo["budget"]),
            max_bulk_budget=int(self.topo["bulk_budget"]),
            ttl_ms=float(self.topo["lease_ttl_ms"]),
            record_ops=True, clock_ms=c0.now)
        self.cli_lease = LeaseClient(
            DirectTransport(self.mgr), c0.lid_lease,
            budget=int(self.topo["budget"]), clock_ms=c0.now,
            direct_fallback=False, telemetry=False)
        self.lease_keys = [f"lk-{i}"
                           for i in range(int(self.topo["n_lease_keys"]))]
        # Edge tier (cell 0): aggregator + two edge clients.
        self.edge_keys = [f"ek-{i}"
                          for i in range(int(self.topo["n_edge_keys"]))]
        self._tcp = None
        if self.topo.get("edge") == "tcp":
            self.edge_link, self.agg = self._build_tcp_edge(c0)
        else:
            gated = GatedTransport(DirectTransport(self.mgr))
            from ratelimiter_tpu.edge.aggregator import EdgeAggregator

            self.agg = EdgeAggregator(
                gated, bulk_budget=int(self.topo["bulk_budget"]),
                slice_budget=int(self.topo["slice_budget"]),
                flush_ms=200.0, clock_ms=c0.now)
            self.edge_link = DirectEdgeLink(gated)
        self.edge_clients = [
            LeaseClient(self.agg.session(), c0.lid_edge,
                        budget=int(self.topo["slice_budget"]),
                        clock_ms=c0.now, direct_fallback=False,
                        telemetry=False)
            for _ in range(2)]
        self.monitor = InvariantMonitor(self)
        self.actors = Actors(self)
        self.pending_pool_leak = False
        self.zombies_fenced = 0
        # Per-step oracle tallies the monitor reads.
        self.step_decisions = 0
        self.step_mismatches = 0
        self.decisions_total = 0
        self.lease_admitted = 0
        self.edge_admitted = 0

    def _build_tcp_edge(self, c0):
        from ratelimiter_tpu.edge.aggregator import EdgeAggregator
        from ratelimiter_tpu.edge.edgeproc import LockedSidecarClient
        from ratelimiter_tpu.service.sidecar import (
            SidecarClient,
            SidecarServer,
        )
        from ratelimiter_tpu.storage.chaos import FaultInjectingProxy

        server = SidecarServer(c0.router, host="127.0.0.1", port=0,
                               drain_timeout_ms=200.0)
        server.expose(c0.lid_edge, "tb", c0.cfg_lease)
        server.attach_leases(self.mgr)
        server.start()
        proxy = FaultInjectingProxy(server.port,
                                    seed=int(self.plan.seed)).start()

        def client_factory():
            return LockedSidecarClient(
                SidecarClient("127.0.0.1", proxy.port, timeout=2.0))

        agg = EdgeAggregator(
            client_factory(),
            bulk_budget=int(self.topo["bulk_budget"]),
            slice_budget=int(self.topo["slice_budget"]),
            flush_ms=200.0, clock_ms=c0.now)
        link = TcpEdgeLink(proxy, agg, client_factory)
        self._tcp = server
        return link, agg

    # -- clocks ----------------------------------------------------------------
    def tick(self, n: int = 1) -> None:
        for _ in range(int(n)):
            self.sim["s"] += self.cells[0].ocfg.probe_interval_ms / 1000.0
            for c in self.cells:
                c.orch.tick()
        for c in self.cells:
            c.sync_stamps()

    # -- the zombie probe (called by the resume actor) -------------------------
    def zombie_probe(self, cell, shard: int, backend, step: int) -> None:
        if backend is None:
            return
        ids = [i for i in range(self.n_direct)
               if int(cell.key_shard[i]) == int(shard)][:8]
        if not ids:
            return
        try:
            backend.acquire_stream_ids(
                "tb", cell.lid_tb, np.asarray(ids, dtype=np.int64))
        except FencedError:
            self.zombies_fenced += 1
            return
        self.monitor.violation(
            "zombie-serving", step,
            f"cell {cell.idx} shard {shard}: paused-then-resumed "
            f"backend served direct dispatches after its keyspace was "
            f"promoted away (fence lease failed to stop the zombie)")

    # -- traffic ---------------------------------------------------------------
    def _direct_wave(self, c: _Cell, rng: random.Random,
                     step: int) -> None:
        ids = [rng.randrange(self.n_direct) for _ in range(24)]
        ids.append(self.n_direct)  # the liveness probe key
        blocked = {q for q in range(c.n_shards) if c.blocked(q)}
        use = [i for i in ids if int(c.key_shard[i]) not in blocked]
        if not use:
            return
        # Expected stamps mirror storage._stamp per SERVING storage:
        # max(last stamp, cell now) — the backward clamp, byte for byte.
        now = c.now()
        stamps: Dict[int, int] = {}
        for i in use:
            b = c.serving_backend(int(c.key_shard[i]))
            if id(b) not in stamps:
                stamps[id(b)] = max(getattr(b, "_last_stamp", 0), now)
        out = c.router.acquire_stream_ids(
            "tb", c.lid_tb, np.asarray(use, dtype=np.int64))
        live_served = None
        for i, got in zip(use, out):
            b = c.serving_backend(int(c.key_shard[i]))
            d = c.oracle.try_acquire(int(i), 1, stamps[id(b)])
            self.step_decisions += 1
            if bool(got) != d.allowed:
                self.step_mismatches += 1
            if i == self.n_direct:
                live_served = bool(got)
        if c.idx == 0 and live_served is not None:
            self.monitor.note_probe("direct", step, live_served, True)

    def _lease_traffic(self, c0: _Cell, rng: random.Random,
                       step: int) -> None:
        blocked = {q for q in range(c0.n_shards) if c0.blocked(q)}
        healthy = self.gate._forced == 0
        for key in self.lease_keys:
            if shard_of_key((c0.lid_lease, key), c0.n_shards) in blocked:
                continue
            for _ in range(rng.choice([1, 1, 2])):
                if self._guarded(step, self.cli_lease.try_acquire, key):
                    self.lease_admitted += 1
        live = "lk-live"
        if shard_of_key((c0.lid_lease, live), c0.n_shards) not in blocked:
            served = self._guarded(step, self.cli_lease.try_acquire,
                                   live)
            self.monitor.note_probe("lease", step, bool(served),
                                    healthy and not blocked)

    def _edge_traffic(self, c0: _Cell, rng: random.Random,
                      step: int) -> None:
        blocked = {q for q in range(c0.n_shards) if c0.blocked(q)}
        healthy = (self.edge_link.healthy and self.gate._forced == 0
                   and not blocked)
        for key in self.edge_keys:
            if shard_of_key((c0.lid_edge, key), c0.n_shards) in blocked:
                continue
            for cli in self.edge_clients:
                if self._guarded(step, cli.try_acquire, key):
                    self.edge_admitted += 1
        live = "ek-live"
        if shard_of_key((c0.lid_edge, live), c0.n_shards) not in blocked:
            served = self._guarded(step,
                                   self.edge_clients[0].try_acquire,
                                   live)
            self.monitor.note_probe("edge", step, bool(served), healthy)

    def _guarded(self, step: int, fn, *args) -> bool:
        """One client call under chaos: transport/storage faults read
        as a denial; a broken conservation assertion surfaces as the
        violation it is."""
        try:
            return bool(fn(*args))
        except AssertionError as e:
            self.monitor.violation("conservation", step, str(e))
        except (StorageException, OSError):
            return False
        return False

    # -- the run loop ----------------------------------------------------------
    def run(self) -> Dict:
        by_step = self.plan.by_step()
        report: Dict = {"violation": None, "steps_completed": 0,
                        "actions_applied": 0}
        try:
            for step in range(int(self.plan.steps)):
                for action in by_step.get(step, []):
                    self.actors.apply(action, step)
                self.step_decisions = 0
                self.step_mismatches = 0
                rng = random.Random(f"{self.plan.seed}:{step}")
                self.base["t"] += rng.choice([1, 7, 250, 999, 2000, 2001])
                for c in self.cells:
                    self._direct_wave(c, rng, step)
                self._lease_traffic(self.cells[0], rng, step)
                self._edge_traffic(self.cells[0], rng, step)
                if self.pending_pool_leak and self.agg._pools:
                    sorted(self.agg._pools.items())[0][1].remaining += 1
                    self.pending_pool_leak = False
                self.decisions_total += self.step_decisions
                for c in self.cells:
                    c.repl.ship_now()
                self.tick(2)
                self.monitor.check(step)
                report["steps_completed"] = step + 1
            self._finish(report)
        except InvariantViolation as v:
            report["violation"] = v.to_dict()
        finally:
            report["actions_applied"] = len(self.actors.applied)
            report.update(self._counters())
            self.close()
        return report

    # -- drain + reconciliation ------------------------------------------------
    def _finish(self, report: Dict) -> None:
        step = int(self.plan.steps)
        self.edge_link.heal()
        self.gate.heal()
        for c in self.cells:
            for q, f in enumerate(c.flags):
                if f.get("paused"):
                    promoted = (c.serving_backend(q)
                                is not f.get("backend"))
                    f["down"] = False
                    f["paused"] = False
                    if promoted:
                        self.zombie_probe(c, q, f.get("backend"), step)
        for _ in range(64):
            if not any(c.blocked(q) for c in self.cells
                       for q in range(c.n_shards)):
                break
            self.tick(1)
        for c in self.cells:
            c.repl.ship_now()
        self.tick(4)
        for cli in self.edge_clients:
            cli.release_all()
        self.agg.release_all()
        self.cli_lease.release_all()
        for c in self.cells:
            c.router.flush()
        # Advance the decision clock past every stamp any storage ever
        # issued, so the availability comparison below reads wall time
        # on both sides regardless of residual skew.
        hw = 0
        for c in self.cells:
            for s in ([c.primary]
                      + list(c.router.replacements.values())):
                hw = max(hw, getattr(s, "_last_stamp", 0))
        self.base["t"] = hw + 10_000 - min(0, min(self.skew))
        self._reconcile(step)

    def _reconcile(self, step: int) -> None:
        """Replay the manager's recorded reserve/credit stream into the
        oracle and demand bit-identity — grants AND final availability
        (the lease drill's Phase D, under the whole schedule's chaos)."""
        c0 = self.cells[0]
        oracle = TokenBucketOracle(c0.cfg_lease)
        for op in self.mgr.ops:
            if op[0] == "reserve":
                _, _algo, _lid, key, req, granted, ws, stamp = op
                g, w = oracle.reserve(key, req, stamp)
                if (g, w) != (granted, ws):
                    self.monitor.violation(
                        "oracle-divergence", step,
                        f"replayed lease reserve diverged for {key!r}: "
                        f"oracle ({g}, {w}) vs device "
                        f"({granted}, {ws})")
            else:
                _, _algo, _lid, key, unused, ws, stamp = op
                oracle.credit(key, unused, ws, stamp)
        now = c0.now()
        checks = [(c0.lid_lease, self.lease_keys + ["lk-live"]),
                  (c0.lid_edge, self.edge_keys + ["ek-live"])]
        for lid, keys in checks:
            for key in keys:
                got = int(c0.router.available_many("tb", lid, [key])[0])
                want = oracle.get_available_permits(key, now)
                if got != want:
                    self.monitor.violation(
                        "oracle-divergence", step,
                        f"final availability diverged for lid {lid} "
                        f"{key!r}: device {got} vs oracle {want}")

    def _counters(self) -> Dict:
        c0 = self.cells[0]
        return {
            "decisions": self.decisions_total,
            "lease_admitted": self.lease_admitted,
            "edge_admitted": self.edge_admitted,
            "zombies_fenced": self.zombies_fenced,
            "invariant_checks": self.monitor.checks_total,
            "promotions": [c.orch.promotions for c in self.cells],
            "fence_epochs": [c.orch.fence_epoch for c in self.cells],
            "seat_epochs": [c.seat.epoch for c in self.cells],
            "lease_status": self.mgr.status(),
            "edge_status": self.agg.status(),
            "forward_clamps": self.mgr.table.forward_clamps,
            "backward_clamps": sum(
                getattr(c.primary, "backward_clamps", 0)
                for c in self.cells),
            "storage_faults_injected": self.gate.injected,
            "edge_faults": self.edge_link.faults,
        }

    def close(self) -> None:
        for closer in ([self.edge_link.close]
                       + ([self._tcp.stop] if self._tcp else [])):
            try:
                closer()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for c in self.cells:
            c.close()


def run_plan(plan: FaultPlan) -> Dict:
    """Boot a fresh fleet, run the plan, tear down.  The conductor's
    one-shot entry point — same plan in, same report out."""
    return FleetHarness(plan).run()
