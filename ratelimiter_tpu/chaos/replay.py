"""Replayable chaos artifacts (ARCHITECTURE §17).

When a conductor run violates an invariant, the failure is written as
a JSON artifact capturing the COMPLETE identity of the run — the plan
(seed, steps, topology, fault_rate, the exact action list) plus the
observed ``(invariant, step, detail)``.  Because traffic is a pure
function of ``(seed, step)`` and actions carry all their parameters,
re-running the artifact's plan reproduces the same trajectory and the
same violation deterministically:

    python -m ratelimiter_tpu.chaos.replay --artifact failure.json

The module is also the library surface the soak gate and tests use:
``dump_artifact`` / ``load_artifact`` / ``replay``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, Optional

from ratelimiter_tpu.chaos.plan import FaultPlan

ARTIFACT_VERSION = 1


def dump_artifact(path: str, plan: FaultPlan, violation: Dict,
                  minimized: bool = False,
                  original_actions: Optional[int] = None) -> str:
    """Write a replayable failure artifact; returns ``path``."""
    doc = {
        "version": ARTIFACT_VERSION,
        "kind": "chaos-artifact",
        "plan": plan.to_json(),
        "violation": {
            "invariant": str(violation["invariant"]),
            "step": int(violation["step"]),
            "detail": str(violation.get("detail", "")),
        },
        "minimized": bool(minimized),
        "original_actions": int(
            len(plan.actions) if original_actions is None
            else original_actions),
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, sort_keys=True, indent=2)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_artifact(path: str) -> Dict:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("kind") != "chaos-artifact":
        raise ValueError(f"{path}: not a chaos artifact")
    doc["plan"] = FaultPlan.from_json(doc["plan"])
    return doc


def replay(artifact: Dict) -> Dict:
    """Re-run the artifact's plan; returns the harness report with a
    ``reproduced`` flag (same invariant observed again)."""
    from ratelimiter_tpu.chaos.harness import run_plan

    report = run_plan(artifact["plan"])
    expected = artifact["violation"]["invariant"]
    got = (report.get("violation") or {}).get("invariant")
    report["expected_invariant"] = expected
    report["reproduced"] = (got == expected)
    return report


def _main(argv=None) -> int:
    # Environment before any jax import (the harness pulls it in).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

    ap = argparse.ArgumentParser(
        description="Replay a chaos conductor failure artifact.")
    ap.add_argument("--artifact", required=True,
                    help="path to a chaos-artifact JSON file")
    args = ap.parse_args(argv)

    art = load_artifact(args.artifact)
    v = art["violation"]
    print(f"replaying plan seed={art['plan'].seed} "
          f"steps={art['plan'].steps} actions={len(art['plan'].actions)}"
          f"{' (minimized)' if art.get('minimized') else ''}")
    print(f"expecting [{v['invariant']}] at step {v['step']}: "
          f"{v['detail']}")
    report = replay(art)
    got = report.get("violation")
    if report["reproduced"]:
        print(f"REPRODUCED [{got['invariant']}] at step {got['step']}: "
              f"{got['detail']}")
        return 0
    if got is None:
        print("NOT reproduced: run completed with zero violations")
    else:
        print(f"DIFFERENT failure: [{got['invariant']}] at step "
              f"{got['step']}: {got['detail']}")
    return 1


if __name__ == "__main__":
    sys.exit(_main())
