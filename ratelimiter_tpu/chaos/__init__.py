"""Chaos conductor (ARCHITECTURE §17): seeded multi-fault schedules,
a fleet-wide invariant monitor, and failure minimization.

Everything except the harness imports eagerly — plan, monitor,
minimizer, and replay helpers are all stdlib-light (the minimizer and
replay pull the harness in lazily, inside their functions).  The
harness itself loads the full jax-backed stack, so ``FleetHarness``
and ``run_plan`` resolve through a module ``__getattr__`` — scripts
get to set ``JAX_PLATFORMS``/``XLA_FLAGS`` before the first heavy
import.

The eager function imports for ``minimize`` and ``replay`` double as
shadow-busting: importing those submodules binds the MODULE objects as
package attributes, and the assignments below overwrite them so
``from ratelimiter_tpu.chaos import minimize`` yields the callable,
never the module (the submodules stay importable via sys.modules).
"""

from ratelimiter_tpu.chaos.plan import (  # noqa: F401
    DEFAULT_TOPOLOGY, DEFECT_OPS, FAULT_OPS, FaultAction, FaultPlan)
from ratelimiter_tpu.chaos.monitor import (  # noqa: F401
    InvariantMonitor, InvariantViolation)
from ratelimiter_tpu.chaos.minimize import minimize  # noqa: F401
from ratelimiter_tpu.chaos.replay import (  # noqa: F401
    dump_artifact, load_artifact, replay)

_LAZY = {
    "FleetHarness": ("ratelimiter_tpu.chaos.harness", "FleetHarness"),
    "run_plan": ("ratelimiter_tpu.chaos.harness", "run_plan"),
}

__all__ = [
    "DEFAULT_TOPOLOGY", "DEFECT_OPS", "FAULT_OPS", "FaultAction",
    "FaultPlan", "InvariantMonitor", "InvariantViolation",
    "dump_artifact", "load_artifact", "minimize", "replay",
] + sorted(_LAZY)


def __getattr__(name):
    try:
        module, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    return getattr(importlib.import_module(module), attr)
