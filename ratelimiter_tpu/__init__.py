"""ratelimiter_tpu — a TPU-native distributed rate-limiting framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the Java/Redis
reference ``tharunjasti/distributed-rate-limiter``:

- sliding-window-counter and token-bucket algorithms with the reference's
  decision semantics (see ``ratelimiter_tpu.semantics.oracle``),
- a pluggable storage boundary (``ratelimiter_tpu.storage``) mirroring the
  reference's ``RateLimitStorage`` interface (storage/RateLimitStorage.java:10-70),
- a host-side TTL negative cache (the Caffeine analog, C7 in SURVEY.md),
- per-limiter immutable config with validation and factories
  (core/RateLimitConfig.java:14-81),
- multi-tenant named limiter instances, an HTTP demo API with 429 semantics,
  metrics counters, and a benchmark harness.

Instead of a per-request Redis round-trip, decisions are micro-batched on the
host and dispatched to a TPU-resident, device-sharded counter array updated by
a single vectorized gather->decide->scatter step (``ratelimiter_tpu.engine``).

Timestamps are absolute Unix milliseconds carried as int64 on device; the
package enables JAX x64 support at import so window arithmetic matches the
reference's `System.currentTimeMillis()` math exactly.
"""

import jax

# Device state carries absolute Unix-ms timestamps (int64) so that window
# bucketing (timestampMs / windowMs * windowMs — the reference's
# SlidingWindowRateLimiter.java:185-188) is exact. Must run before any tracing.
jax.config.update("jax_enable_x64", True)

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.core.limiter import RateLimiter

__version__ = "0.1.0"

__all__ = [
    "RateLimitConfig",
    "RateLimiter",
    "__version__",
]
