"""Per-limiter configuration.

Capability parity with the reference's immutable Lombok value class
``core/RateLimitConfig.java:14-81``: ``maxPermits``, ``window``, ``refillRate``
(token bucket only, default 0), ``enableLocalCache`` (default True),
``localCacheTtl`` (default 100 ms), a ``validate()`` method and
``perSecond/perMinute/perHour`` factories (core/RateLimitConfig.java:61-80).

TPU-specific addition: ``refill_rate_fp`` exposes the refill rate in integer
fixed-point micro-tokens per millisecond (scale 2**TOKEN_FP_SHIFT), which is
the exact arithmetic the device kernels use instead of the reference's Lua
float math (TokenBucketRateLimiter.java:55-67).  See
``ratelimiter_tpu.semantics.oracle`` for the equivalence argument.
"""

from __future__ import annotations

import dataclasses
from datetime import timedelta
from typing import Union

# Fixed-point scale for token-bucket accounting: 1 token == 2**20 "fp units".
# Chosen so that a refill rate of 1e-3 tokens/ms (1 token/sec) is ~1049 fp/ms,
# giving sub-micro-token resolution while keeping 1M-token buckets well inside
# int64 (2**20 * 1e6 ~= 2**40).
TOKEN_FP_SHIFT = 20
TOKEN_FP_ONE = 1 << TOKEN_FP_SHIFT

DurationLike = Union[timedelta, int, float]


def _to_millis(d: DurationLike) -> int:
    """Accept a timedelta or a number of milliseconds."""
    if isinstance(d, timedelta):
        return int(d.total_seconds() * 1000)
    return int(d)


@dataclasses.dataclass(frozen=True)
class RateLimitConfig:
    """Immutable rate-limit policy for one limiter instance.

    Parameters mirror core/RateLimitConfig.java:14-56.
    """

    max_permits: int
    window_ms: int
    refill_rate: float = 0.0  # tokens per second (token bucket only)
    enable_local_cache: bool = True
    local_cache_ttl_ms: int = 100

    def __post_init__(self):
        object.__setattr__(self, "max_permits", int(self.max_permits))
        object.__setattr__(self, "window_ms", _to_millis(self.window_ms))
        object.__setattr__(self, "local_cache_ttl_ms", _to_millis(self.local_cache_ttl_ms))

    # -- validation (core/RateLimitConfig.java:44-56) -------------------------
    def validate(self) -> "RateLimitConfig":
        if self.max_permits <= 0:
            raise ValueError("maxPermits must be positive")
        if self.window_ms <= 0:
            raise ValueError("window must be a positive duration")
        if self.refill_rate < 0:
            raise ValueError("refillRate cannot be negative")
        return self

    # -- derived quantities ---------------------------------------------------
    @property
    def refill_rate_fp(self) -> int:
        """Refill rate in fp units per millisecond (integer fixed point).

        The reference converts to tokens/ms as a double
        (TokenBucketRateLimiter.java:85 ``refillRate / 1000.0``); we round the
        same quantity to the nearest fp unit.
        """
        return round(self.refill_rate * TOKEN_FP_ONE / 1000.0)

    @property
    def max_permits_fp(self) -> int:
        return self.max_permits << TOKEN_FP_SHIFT

    # -- factories (core/RateLimitConfig.java:61-80) --------------------------
    @staticmethod
    def per_second(max_permits: int) -> "RateLimitConfig":
        return RateLimitConfig(max_permits=max_permits, window_ms=1_000)

    @staticmethod
    def per_minute(max_permits: int) -> "RateLimitConfig":
        return RateLimitConfig(max_permits=max_permits, window_ms=60_000)

    @staticmethod
    def per_hour(max_permits: int) -> "RateLimitConfig":
        return RateLimitConfig(max_permits=max_permits, window_ms=3_600_000)
