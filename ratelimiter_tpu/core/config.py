"""Per-limiter configuration.

Capability parity with the reference's immutable Lombok value class
``core/RateLimitConfig.java:14-81``: ``maxPermits``, ``window``, ``refillRate``
(token bucket only, default 0), ``enableLocalCache`` (default True),
``localCacheTtl`` (default 100 ms), a ``validate()`` method and
``perSecond/perMinute/perHour`` factories (core/RateLimitConfig.java:61-80).

TPU-specific addition: ``refill_rate_fp`` exposes the refill rate in integer
fixed-point micro-tokens per millisecond (scale 2**TOKEN_FP_SHIFT), which is
the exact arithmetic the device kernels use instead of the reference's Lua
float math (TokenBucketRateLimiter.java:55-67).  See
``ratelimiter_tpu.semantics.oracle`` for the equivalence argument.
"""

from __future__ import annotations

import dataclasses
from datetime import timedelta
from typing import Union

# Fixed-point scale for token-bucket accounting: 1 token == 1000*2**20 "fp
# units".  The factor 1000 makes the tokens/sec -> tokens/ms conversion exact
# in integers: the refill rate becomes round(refill_rate * 2**20) fp-units per
# millisecond — an integer with NO rounding for any rate of the form k/2**20
# (all integral and most practical fractional rates) — and a refill is then a
# pure multiply with no division, so fixed-point token values coincide exactly
# with the mathematical rational semantics.  Billion-token buckets still fit
# int64 (1000*2**20*1e9 ~= 2**60); the refill clamps elapsed time (see
# semantics/oracle.py) so device int64 arithmetic cannot overflow.
TOKEN_FP_SHIFT = 20
TOKEN_FP_ONE = 1000 << TOKEN_FP_SHIFT  # fp units per whole token

DurationLike = Union[timedelta, int, float]


def _to_millis(d: DurationLike) -> int:
    """Accept a timedelta or a number of milliseconds."""
    if isinstance(d, timedelta):
        return int(d.total_seconds() * 1000)
    return int(d)


@dataclasses.dataclass(frozen=True)
class RateLimitConfig:
    """Immutable rate-limit policy for one limiter instance.

    Parameters mirror core/RateLimitConfig.java:14-56.
    """

    max_permits: int
    window_ms: int
    refill_rate: float = 0.0  # tokens per second (token bucket only)
    enable_local_cache: bool = True
    local_cache_ttl_ms: int = 100

    def __post_init__(self):
        object.__setattr__(self, "max_permits", int(self.max_permits))
        object.__setattr__(self, "window_ms", _to_millis(self.window_ms))
        object.__setattr__(self, "local_cache_ttl_ms", _to_millis(self.local_cache_ttl_ms))

    # -- validation (core/RateLimitConfig.java:44-56) -------------------------
    def validate(self) -> "RateLimitConfig":
        if self.max_permits <= 0:
            raise ValueError("maxPermits must be positive")
        if self.max_permits > 2**31 - 1:
            # Java-int parity with the reference (int maxPermits); also what
            # lets device counters travel as one i32 lane (ops/sliding_window).
            raise ValueError("maxPermits must fit a 32-bit signed int")
        if self.window_ms <= 0:
            raise ValueError("window must be a positive duration")
        if self.window_ms > 2**30:
            # ~12.4 days; keeps 2*window deadline offsets within i32 on the
            # device path. The reference's Duration has no bound, but windows
            # beyond days are outside rate-limiting semantics.
            raise ValueError("window must be at most 2^30 ms (~12 days)")
        if self.refill_rate < 0:
            raise ValueError("refillRate cannot be negative")
        return self

    # -- derived quantities ---------------------------------------------------
    @property
    def refill_rate_fp(self) -> int:
        """Refill rate in fp units per MILLISECOND (integer fixed point).

        Equals round(refill_rate * 2**TOKEN_FP_SHIFT): exact (no rounding)
        whenever refill_rate is k/2**TOKEN_FP_SHIFT — in particular for every
        integral rate — because TOKEN_FP_ONE carries the factor 1000.  The
        reference converts tokens/sec to tokens/ms as a double
        (TokenBucketRateLimiter.java:85); this is the same quantity with the
        rounding done once at config time instead of every refill.
        """
        return round(self.refill_rate * (1 << TOKEN_FP_SHIFT))

    @property
    def max_permits_fp(self) -> int:
        return self.max_permits * TOKEN_FP_ONE

    # -- factories (core/RateLimitConfig.java:61-80) --------------------------
    @staticmethod
    def per_second(max_permits: int) -> "RateLimitConfig":
        return RateLimitConfig(max_permits=max_permits, window_ms=1_000)

    @staticmethod
    def per_minute(max_permits: int) -> "RateLimitConfig":
        return RateLimitConfig(max_permits=max_permits, window_ms=60_000)

    @staticmethod
    def per_hour(max_permits: int) -> "RateLimitConfig":
        return RateLimitConfig(max_permits=max_permits, window_ms=3_600_000)
