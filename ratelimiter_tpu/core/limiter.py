"""Core rate-limiter contract.

Mirrors the reference's algorithm-agnostic interface
``core/RateLimiter.java:16-43``: ``tryAcquire(key)``,
``tryAcquire(key, permits)``, ``getAvailablePermits(key)``, ``reset(key)``.

TPU-native extension: the batch entry points ``try_acquire_many`` /
``available_permits_many`` accept vectors of keys so callers (the HTTP
service, the benchmark harness, the micro-batcher) can amortize one device
dispatch over many decisions — the framework's replacement for the
reference's per-request Redis round-trip.
"""

from __future__ import annotations

import abc
from typing import Sequence

import numpy as np


class RateLimiter(abc.ABC):
    """Abstract rate limiter (core/RateLimiter.java:7-44)."""

    @abc.abstractmethod
    def try_acquire(self, key: str, permits: int = 1) -> bool:
        """Try to acquire ``permits`` permits for ``key`` without blocking.

        Returns True if acquired, False if the rate limit is exceeded.
        Raises ValueError if ``permits <= 0`` (the reference throws
        IllegalArgumentException, SlidingWindowRateLimiter.java:87-89).
        """

    @abc.abstractmethod
    def get_available_permits(self, key: str) -> int:
        """Remaining permits for ``key`` (core/RateLimiter.java:31-37)."""

    @abc.abstractmethod
    def reset(self, key: str) -> None:
        """Reset the limit for ``key`` (core/RateLimiter.java:39-43)."""

    # -- batch extensions (TPU-native) ---------------------------------------
    def try_acquire_many(
        self, keys: Sequence[str], permits: Sequence[int] | None = None
    ) -> np.ndarray:
        """Vectorized tryAcquire. Default: loop over the scalar path."""
        if permits is None:
            permits = [1] * len(keys)
        return np.array(
            [self.try_acquire(k, int(p)) for k, p in zip(keys, permits)], dtype=bool
        )

    def available_permits_many(self, keys: Sequence[str]) -> np.ndarray:
        return np.array([self.get_available_permits(k) for k in keys], dtype=np.int64)
