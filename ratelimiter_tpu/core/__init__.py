from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.core.limiter import RateLimiter

__all__ = ["RateLimitConfig", "RateLimiter"]
