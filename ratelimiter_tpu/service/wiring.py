"""Application wiring (C3 parity).

The reference's Spring ``@Configuration`` builds one storage bean, a meter
registry, and three named limiters (config/RateLimiterConfig.java:31-95):

- ``apiRateLimiter``   — sliding window, 100/min, local cache on (100 ms TTL)
- ``authRateLimiter``  — sliding window, 10/min, cache OFF (strictness)
- ``burstRateLimiter`` — token bucket, capacity 50, refill 10/sec

This module builds the identical trio over this framework's storage
backends, selected by ``storage.backend`` (tpu | memory), plus the shared
registry and the fail-open policy object.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter, TokenBucketRateLimiter
from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.core.limiter import RateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.service.props import AppProperties
from ratelimiter_tpu.storage import (
    FaultInjectingStorage,
    InMemoryStorage,
    RateLimitStorage,
    TpuBatchedStorage,
)


@dataclasses.dataclass
class ReplicationHandle:
    """What replication wiring hands the app: the primary's replicator
    or the standby's receiver+server, behind one close()."""

    role: str
    replicator: object = None
    receiver: object = None
    server: object = None

    def status(self) -> Dict:
        out = {"role": self.role}
        if self.replicator is not None:
            log = self.replicator.log
            if hasattr(log, "epochs"):  # sharded: per-shard epoch streams
                out.update(epochs=list(log.epochs),
                           shards=self.replicator.shard_status(),
                           journal=log.journal_kind)
            else:
                out.update(epoch=log.epoch,
                           journal=getattr(log, "journal_kind", "host"))
            out.update(lag_ms=self.replicator.lag_ms(),
                       frames_shipped=self.replicator.frames_shipped,
                       bytes_shipped=self.replicator.bytes_shipped,
                       errors=self.replicator.errors)
            if hasattr(self.replicator, "coalesced"):
                out["coalesced"] = self.replicator.coalesced
        if self.receiver is not None:
            out.update(applied_epoch=self.receiver.last_epoch,
                       consistent=self.receiver.consistent,
                       promoted=self.receiver.promoted,
                       frames_applied=self.receiver.frames_applied)
        return out

    def close(self) -> None:
        if self.replicator is not None:
            self.replicator.close()
        if self.server is not None:
            self.server.stop()


@dataclasses.dataclass
class OrchestratorHandle:
    """Self-healing failover wiring (ratelimiter.orchestrator.*): the
    orchestrator, the router the app serves through, the per-shard
    replicator feeding the in-process standby mesh."""

    orchestrator: object
    router: object
    replicator: object
    standby_set: object

    def status(self) -> Dict:
        out = {"enabled": True, **self.orchestrator.status()}
        out["router"] = {str(q): v
                         for q, v in self.router.shard_status().items()}
        out["replication"] = {str(q): v for q, v in
                              self.replicator.shard_status().items()}
        return out

    def close(self) -> None:
        self.orchestrator.close()
        self.replicator.close()
        # A standby whose receiver was PROMOTED is now the serving
        # replacement (closed with the router's chain); re-seeded fresh
        # standbys are ours to close.
        promoted = tuple(
            q for q, rx in enumerate(self.standby_set.receivers)
            if getattr(rx, "promoted", False))
        self.standby_set.close(except_shards=promoted)


@dataclasses.dataclass
class FleetControlHandle:
    """Fleet-true control wiring (ratelimiter.control.fleet.*): the
    epoch-fenced FleetControlPlane the adaptive controller actuates
    through, plus the ControllerElection repairing leader death."""

    plane: object
    election: object

    def lagging_nodes(self) -> list:
        """Members whose last applied policy generation sits behind the
        leader's last broadcast — the generation-convergence invariant's
        health-fold signal (reads the plane's cached view; no RPC)."""
        target = int(self.plane.last_broadcast_generation)
        if target <= 0:
            return []
        return sorted(
            name for name, gen in self.plane.node_generations.items()
            if int(gen) < target)

    def status(self) -> Dict:
        out = {"enabled": True, "fleet": True,
               **self.plane.fleet_status()}
        out["election"] = self.election.status()
        out["lagging_nodes"] = self.lagging_nodes()
        return out

    def close(self) -> None:
        self.election.close()
        self.plane.close()


@dataclasses.dataclass
class AppContext:
    props: AppProperties
    storage: RateLimitStorage
    registry: MeterRegistry
    limiters: Dict[str, RateLimiter]
    fail_open: bool
    replication: ReplicationHandle | None = None
    # The CircuitBreakerStorage layer (None when breaker.enabled=false or
    # the storage was injected) — the health state machine reads it.
    breaker: object = None
    # The TCP decision sidecar (ratelimiter.sidecar.enabled) — the health
    # state machine folds its shed/connection stats in.
    sidecar: object = None
    # The flight recorder behind GET /actuator/flightrecorder (the
    # process-global instance unless a test injected one).
    recorder: object = None
    # Self-healing failover (ratelimiter.orchestrator.enabled) — the
    # autonomous fence/promote/re-seed loop over a sharded primary.
    orchestrator: OrchestratorHandle | None = None
    # Token-lease manager (ratelimiter.lease.enabled) — serves the
    # sidecar's v3 LEASE/RENEW/RELEASE ops and in-process LeaseClients.
    leases: object = None
    # Control-plane RPC listener (ratelimiter.control.port) — this
    # node's remote fence/lease/probe/promote authority surface.
    control: object = None
    # Adaptive policy controller (ratelimiter.control.enabled) — the
    # AIMD loop behind GET /actuator/policies (ARCHITECTURE §15).
    controller: object = None
    # Fleet NodeManager (ratelimiter.fleet.enabled) — node lifecycle +
    # autopilot substrate behind GET /actuator/fleet (ARCHITECTURE §16).
    fleet: object = None
    # Fleet-true control plane (ratelimiter.control.fleet.enabled) —
    # epoch-fenced controller leadership + cross-host policy broadcast
    # behind GET /actuator/controller (ARCHITECTURE §15).
    fleet_control: FleetControlHandle | None = None
    # In-process edge aggregator (ratelimiter.edge.enabled) — bulk
    # leases subleased to in-process clients behind GET /actuator/edge
    # (ARCHITECTURE §14b).
    edge: object = None

    def close(self) -> None:
        if self.edge is not None:
            # Return every outstanding bulk budget before the lease
            # manager (and its storage) goes away.
            try:
                self.edge.release_all()
            except Exception:  # noqa: BLE001 — best-effort drain
                pass
        if self.fleet is not None:
            self.fleet.close()
        if self.controller is not None:
            self.controller.close()
        if self.fleet_control is not None:
            self.fleet_control.close()
        if self.control is not None:
            self.control.stop()
        if self.sidecar is not None:
            self.sidecar.stop()
        if self.replication is not None:
            self.replication.close()
        if self.orchestrator is not None:
            self.orchestrator.close()
        self.storage.close()


def warmup_shapes(storage: RateLimitStorage, max_batch: int = 8192) -> None:
    """Compile the hot dispatch shapes before traffic arrives.

    A cold service otherwise spends its first requests inside 40-90 s jit
    compiles, during which token buckets legitimately refill — confusing
    and latency-hostile.  Padding-only batches (slot -1) compile the exact
    shapes the micro-batcher uses without touching any real slot state.

    Warms the smallest bucket (single requests) and the full-flush bucket;
    intermediate power-of-two buckets compile on demand (or come from the
    persistent cache).  Each call is independently best-effort (padding-only
    batches route as shard-0 padding on the sharded engine, so both engine
    kinds warm their acquire and peek shapes).
    """
    engine = getattr(storage, "engine", None)
    if engine is None:
        return
    now = 1  # any positive stamp; padding batches never write state
    calls = [
        lambda: engine.sw_acquire([-1], [0], [1], now),
        lambda: engine.tb_acquire([-1], [0], [1], now),
        lambda: engine.sw_acquire([-1] * max_batch, [0] * max_batch,
                                  [1] * max_batch, now),
        lambda: engine.tb_acquire([-1] * max_batch, [0] * max_batch,
                                  [1] * max_batch, now),
        lambda: engine.sw_available([0], [0], now),
        lambda: engine.tb_available([0], [0], now),
    ]
    for call in calls:
        try:
            call()
        except Exception:  # noqa: BLE001 — warmup is best-effort
            pass
    try:
        engine.block_until_ready()
    except Exception:  # noqa: BLE001
        pass


def build_storage(props: AppProperties, meter_registry=None) -> RateLimitStorage:
    backend = (props.get("storage.backend") or "tpu").lower()
    if backend == "memory":
        return InMemoryStorage()
    if backend == "tpu":
        num_slots = props.get_int("storage.num_slots", 1 << 20)
        shard = (props.get("parallel.shard") or "auto").lower()
        engine = None
        if shard in ("auto", "true", "on"):
            import jax

            devices = jax.devices()
            if len(devices) > 1 and shard != "off":
                from ratelimiter_tpu.engine.state import LimiterTable
                from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh

                mesh = make_mesh(devices)
                engine = ShardedDeviceEngine(
                    slots_per_shard=max(num_slots // len(devices), 1),
                    table=LimiterTable(capacity=props.get_int(
                        "ratelimiter.table.capacity", 64)),
                    mesh=mesh,
                )
        return TpuBatchedStorage(
            num_slots=num_slots,
            max_batch=props.get_int("batcher.max_batch", 8192),
            max_delay_ms=props.get_float("batcher.max_delay_ms", 0.5),
            max_inflight=props.get_int("batcher.max_inflight", 4),
            # Admission control (engine/batcher.py): bounded pending queue
            # + per-request queue-deadline budgets; sheds raise
            # OverloadedError, which service/app.py maps to 429+Retry-After.
            max_pending=props.get_int("ratelimiter.overload.max_pending",
                                      65536),
            queue_deadline_ms=props.get_float(
                "ratelimiter.overload.deadline_ms", 1000.0),
            engine=engine,
            meter_registry=meter_registry,
            # Observability (ARCHITECTURE §13): 1-in-N full-trace
            # sampling + the slow-dispatch anomaly threshold.
            trace_sample=props.get_int("ratelimiter.obs.trace_sample", 0),
            obs_slo_ms=props.get_float("ratelimiter.obs.slo_ms", 0.0),
            # Adaptive flush + hybrid serving tier (ARCHITECTURE §6d).
            adaptive_flush=props.get_bool(
                "ratelimiter.microbatch.adaptive_flush", True),
            flush_floor_ms=props.get_float(
                "ratelimiter.microbatch.flush_floor_ms", 0.05),
            serving_cache=props.get_bool(
                "ratelimiter.cache.hybrid.enabled", False),
            serving_cache_ttl_ms=props.get_float(
                "ratelimiter.cache.hybrid.ttl_ms", 50.0),
            serving_cache_max_keys=props.get_int(
                "ratelimiter.cache.hybrid.max_keys", 65536),
            serving_cache_unconfirmed_cap=props.get_int(
                "ratelimiter.cache.hybrid.unconfirmed_cap", 64),
            serving_cache_guard_ms=props.get_float(
                "ratelimiter.cache.hybrid.guard_ms", 5.0),
            # Fleet telemetry plane + trace lineage (ARCHITECTURE §13e).
            usage_max_tenants=props.get_int(
                "ratelimiter.usage.max_tenants", 256),
            telemetry_max_clients=props.get_int(
                "ratelimiter.telemetry.max_clients", 1024),
            lineage_capacity=props.get_int(
                "ratelimiter.obs.lineage_capacity", 256),
            # Pre-sized policy table (an implicit mid-traffic grow
            # recompiles the device step — engine/state.py:_grow).
            table_capacity=props.get_int("ratelimiter.table.capacity", 64),
        )
    raise ValueError(f"unknown storage.backend: {backend!r}")


def _maybe_chaos(storage: RateLimitStorage, props: AppProperties):
    """Wrap the backend in the fault injector when a chaos drill is on."""
    rate = props.get_float("chaos.failure_rate", 0.0)
    latency = props.get_float("chaos.latency_ms", 0.0)
    if rate <= 0 and latency <= 0:
        return storage
    return FaultInjectingStorage(storage, failure_rate=rate,
                                 latency_ms=latency)


def _maybe_breaker(storage: RateLimitStorage, props: AppProperties,
                   registry: MeterRegistry):
    """Circuit breaker between retry and chaos — ``retry(breaker(chaos(
    storage)))`` — so every retry attempt against a dead backend counts
    toward the threshold, and once open, decisions short-circuit to the
    degraded host limiter instead of paying retry exhaustion per request.
    Returns ``(wrapped_storage, breaker_or_None)``."""
    if not props.get_bool("breaker.enabled", True):
        return storage, None
    from ratelimiter_tpu.storage.breaker import CircuitBreakerStorage

    fallback = None
    if (props.get_bool("ratelimiter.degraded.enabled", True)
            and getattr(storage, "supports_device_batching", False)):
        from ratelimiter_tpu.storage.degraded import DegradedHostLimiter

        # Walk the wrapper chain for the raw storage's telemetry plane
        # so degraded decisions stay in the fleet counters.
        plane, inner, seen = None, storage, set()
        while inner is not None and id(inner) not in seen:
            seen.add(id(inner))
            plane = getattr(inner, "telemetry", None)
            if plane is not None:
                break
            inner = getattr(inner, "_inner", None)
        fallback = DegradedHostLimiter(
            registry=registry,
            max_keys=props.get_int("ratelimiter.degraded.max_keys", 65536),
            telemetry=plane)
    breaker = CircuitBreakerStorage(
        storage,
        failure_threshold=props.get_int("breaker.failure_threshold", 8),
        open_ms=props.get_float("breaker.open_ms", 5000.0),
        half_open_probes=props.get_int("breaker.half_open_probes", 1),
        fallback=fallback,
        registry=registry,
    )
    return breaker, breaker


def _maybe_sidecar(storage: RateLimitStorage, props: AppProperties,
                   registry: MeterRegistry):
    """Config-gated TCP decision sidecar (OFF by default).

    Attaches to the RAW device-batched storage — the sidecar's pipelined
    ``acquire_async`` path needs the micro-batcher, and its per-frame
    admission control composes with (not under) the breaker/retry
    wrappers that serve the HTTP tier."""
    if not props.get_bool("ratelimiter.sidecar.enabled", False):
        return None
    if not getattr(storage, "supports_device_batching", False):
        import logging

        logging.getLogger("ratelimiter").warning(
            "ratelimiter.sidecar.enabled but the %s backend has no "
            "batched decision protocol; sidecar disabled",
            type(storage).__name__)
        return None
    from ratelimiter_tpu.service.sidecar import SidecarServer

    return SidecarServer.from_props(storage, props, registry).start()


def _maybe_leases(storage: RateLimitStorage, sidecar, props: AppProperties,
                  registry: MeterRegistry):
    """Config-gated token-lease tier (OFF by default; ARCHITECTURE §14).

    Builds a ``LeaseManager`` over the SERVING storage (the failover
    router when the orchestrator is on — lease grants must route to a
    promoted replacement exactly like decisions) and attaches it to the
    sidecar's v3 LEASE/RENEW/RELEASE ops when one is running.  Without
    a sidecar the manager still serves in-process ``LeaseClient``s
    through ``DirectTransport``."""
    if not props.get_bool("ratelimiter.lease.enabled", False):
        return None
    if not getattr(storage, "supports_device_batching", False) \
            and not hasattr(storage, "lease_reserve"):
        import logging

        logging.getLogger("ratelimiter").warning(
            "ratelimiter.lease.enabled but the %s backend has no "
            "lease_reserve surface; leases disabled",
            type(storage).__name__)
        return None
    from ratelimiter_tpu.leases import LeaseManager

    manager = LeaseManager(
        storage,
        default_budget=props.get_int("ratelimiter.lease.default_budget",
                                     64),
        max_budget=props.get_int("ratelimiter.lease.max_budget", 1024),
        ttl_ms=props.get_float("ratelimiter.lease.ttl_ms", 2000.0),
        deny_ttl_ms=props.get_float("ratelimiter.lease.deny_ttl_ms", 25.0),
        max_leases=props.get_int("ratelimiter.lease.max_leases", 65536),
        # Concurrency slots (ARCHITECTURE §15): bound every tenant's
        # aggregate outstanding lease budget (0 = unbounded).
        max_concurrent=props.get_int("ratelimiter.control.max_concurrent",
                                     0),
        # Aggregator-tier bulk leases (ARCHITECTURE §14b) may exceed
        # the per-client cap; 0 keeps bulk clamped like ordinary grants.
        max_bulk_budget=props.get_int("ratelimiter.lease.max_bulk_budget",
                                      0),
        registry=registry,
    )
    if sidecar is not None:
        sidecar.attach_leases(manager)
    return manager


def _maybe_edge(leases, props: AppProperties, registry: MeterRegistry):
    """Config-gated in-process edge aggregator (OFF by default;
    ARCHITECTURE §14b).

    Fronts the lease manager with an ``EdgeAggregator`` over a
    ``DirectTransport``: in-process ``LeaseClient``s built on
    ``ctx.edge.session()`` burn memory-speed subleases carved from one
    bulk lease per hot (lid, key), and the aggregator renews its whole
    portfolio in one batch per flush interval.  The standalone-process
    shape of the same tier is ``python -m ratelimiter_tpu.edge.edgeproc``
    pointed at this node's sidecar."""
    if not props.get_bool("ratelimiter.edge.enabled", False):
        return None
    if leases is None:
        import logging

        logging.getLogger("ratelimiter").warning(
            "ratelimiter.edge.enabled requires ratelimiter.lease.enabled; "
            "edge aggregator disabled")
        return None
    from ratelimiter_tpu.edge import EdgeAggregator
    from ratelimiter_tpu.leases import DirectTransport

    return EdgeAggregator(
        DirectTransport(leases),
        bulk_budget=props.get_int("ratelimiter.edge.bulk_budget", 4096),
        slice_budget=props.get_int("ratelimiter.edge.slice_budget", 64),
        flush_ms=props.get_float("ratelimiter.edge.flush_ms", 50.0),
        registry=registry,
    )


def _maybe_controller(serving: RateLimitStorage, props: AppProperties,
                      registry: MeterRegistry, breaker, recorder):
    """Config-gated adaptive policy control plane (OFF by default;
    ARCHITECTURE §15).

    Builds the tick-driven AIMD controller over the SERVING storage
    (the failover router when the orchestrator is on — policy updates
    must broadcast to promoted replacements exactly like decisions),
    observing the fleet telemetry plane's ``UsageSignals`` and the
    breaker's overload state, actuating live ``set_policy`` row updates.
    """
    if not props.get_bool("ratelimiter.control.enabled", False):
        return None
    if not hasattr(serving, "set_policy") \
            or getattr(serving, "telemetry", None) is None:
        import logging

        logging.getLogger("ratelimiter").warning(
            "ratelimiter.control.enabled but the %s backend has no "
            "set_policy/telemetry surface; adaptive control disabled",
            type(serving).__name__)
        return None
    from ratelimiter_tpu.control import (
        AdaptivePolicyController,
        ControlConfig,
    )

    return AdaptivePolicyController(
        serving,
        ControlConfig(
            interval_ms=props.get_float("ratelimiter.control.interval_ms",
                                        1000.0),
            window_ms=props.get_int("ratelimiter.control.window_ms", 2000),
            target_excess=props.get_float(
                "ratelimiter.control.target_excess", 0.5),
            increase_fraction=props.get_float(
                "ratelimiter.control.increase_fraction", 0.1),
            decrease_factor=props.get_float(
                "ratelimiter.control.decrease_factor", 0.5),
            floor_fraction=props.get_float(
                "ratelimiter.control.floor_fraction", 0.1),
            global_cap_per_s=props.get_float(
                "ratelimiter.control.global_cap_per_s", 0.0),
            staleness_bound_ms=props.get_float(
                "ratelimiter.control.staleness_bound_ms", 0.0),
        ),
        breaker=breaker,
        registry=registry,
        recorder=recorder,
    ).start()


def _maybe_fleet_control(serving: RateLimitStorage, props: AppProperties,
                         registry: MeterRegistry, recorder, fleet):
    """Config-gated fleet-true control plane (OFF by default;
    ARCHITECTURE §15).

    When enabled, the adaptive controller runs over a
    :class:`~ratelimiter_tpu.control.FleetControlPlane` instead of the
    local serving storage: fleet-summed UsageSignals in, epoch-fenced
    generation-stamped ``set_policy`` broadcasts out.  The companion
    :class:`~ratelimiter_tpu.control.ControllerElection` rides the
    fleet NodeManager's probe tick when one is running, else its own
    cadence thread.  Returns ``(handle_or_None, controller_storage)``
    — when enabled, the PLANE is what ``_maybe_controller`` builds on.
    """
    if not props.get_bool("ratelimiter.control.fleet.enabled", False):
        return None, serving
    import logging
    import os

    peers = [p.strip() for p in
             (props.get("ratelimiter.control.fleet.peers") or "").split(",")
             if p.strip()]
    if not peers:
        # Single-node cell: this process's own control port is the one
        # member seat (leadership is then trivially held, but the
        # epoch/generation discipline — and the actuator surface — are
        # identical to the multi-host shape).
        port = props.get_int("ratelimiter.control.port", 0)
        if port <= 0:
            logging.getLogger("ratelimiter").warning(
                "ratelimiter.control.fleet.enabled needs peers or an "
                "own ratelimiter.control.port to form a member set; "
                "fleet control disabled")
            return None, serving
        host = props.get("ratelimiter.control.host") or "127.0.0.1"
        peers = [f"{host}:{port}"]
    from ratelimiter_tpu.control import ControllerElection, FleetControlPlane
    from ratelimiter_tpu.replication.control import ControlClient
    from ratelimiter_tpu.replication.remote import RemoteBackend

    members = {}
    for part in peers:
        peer_host, _, peer_port = part.rpartition(":")
        backend = RemoteBackend(
            ControlClient(peer_host or "127.0.0.1", int(peer_port)),
            label=part)
        members[backend.label] = backend
    node = (props.get("ratelimiter.control.fleet.node")
            or f"ctrl-{os.getpid()}")
    plane = FleetControlPlane(
        node, members,
        ttl_ms=props.get_float("ratelimiter.control.fleet.ttl_ms", 3000.0),
        recorder=recorder)
    election = ControllerElection(
        [plane],
        interval_ms=props.get_float(
            "ratelimiter.control.fleet.interval_ms", 500.0),
        registry=registry, recorder=recorder)
    if fleet is not None:
        # Re-election rides the NodeManager's probe tick — leader death
        # is detected and repaired from the same cadence that detects
        # node death, no second thread.
        fleet.attach(election)
    else:
        election.start()
    return FleetControlHandle(plane=plane, election=election), plane


def _maybe_fleet(props: AppProperties, registry: MeterRegistry, recorder):
    """Config-gated fleet NodeManager (OFF by default; ARCHITECTURE
    §16).  Starts the probe cadence with an empty fleet — nodes are
    spawned/adopted by operator tooling (or a FleetAutopilot attached
    at runtime); the service plane contributes the actuator surface,
    the health fold, and the ``ratelimiter.fleet.*`` metrics."""
    if not props.get_bool("ratelimiter.fleet.enabled", False):
        return None
    from ratelimiter_tpu.fleet import LocalExecutor, NodeManager

    return NodeManager(
        executor=LocalExecutor(boot_timeout_s=props.get_float(
            "ratelimiter.fleet.boot_timeout_s", 180.0)),
        probe_interval_ms=props.get_float(
            "ratelimiter.fleet.probe_interval_ms", 500.0),
        probe_fail_threshold=props.get_int(
            "ratelimiter.fleet.probe_fail_threshold", 3),
        registry=registry, recorder=recorder,
    ).start()


def _maybe_retry(storage: RateLimitStorage, props: AppProperties):
    """Per-op retry around the (possibly chaos-wrapped) backend — the
    RedisRateLimitStorage.java:155-178 analog, composed so transient faults
    are absorbed here and only retry exhaustion reaches fail-open."""
    from ratelimiter_tpu.storage.errors import RetryPolicy
    from ratelimiter_tpu.storage.retry import RetryingStorage

    attempts = props.get_int("storage.retry.max_retries", 3)
    if attempts <= 0:
        return storage
    return RetryingStorage(storage, RetryPolicy(
        max_retries=attempts,
        retry_delay_ms=props.get_float("storage.retry.delay_ms", 10.0)))


def _maybe_replication(storage: RateLimitStorage, props: AppProperties,
                       registry: MeterRegistry) -> ReplicationHandle | None:
    """Config-gated replication wiring (OFF by default).

    ``replication.role=primary`` journals this storage and ships epoch
    frames to ``replication.target`` (host:port of a standby's
    listener); ``replication.role=standby`` starts the frame listener
    on ``replication.listen_port`` over this storage — which then idles
    as a shadow until an operator (or orchestrator) promotes it.

    A SHARDED primary (parallel/sharded.py engine) replicates per
    shard: ``replication.targets`` lists one standby ``host:port`` per
    shard (comma-separated, shard order) and each shard ships its own
    epoch stream to an ordinary flat standby of ``slots_per_shard``
    geometry — promotion replaces one shard, never the world.
    """
    if not props.get_bool("replication.enabled", False):
        return None
    import logging

    logger = logging.getLogger("ratelimiter")
    if not getattr(getattr(storage, "engine", None), "supports_replication",
                   False):
        logger.warning("replication.enabled but the %s backend has no "
                       "journaled engine; replication disabled",
                       type(storage).__name__)
        return None
    from ratelimiter_tpu.replication import (
        ReplicationLog,
        ReplicationServer,
        Replicator,
        ShardedReplicationLog,
        ShardedReplicator,
        SocketSink,
        StandbyReceiver,
    )

    role = (props.get("replication.role") or "primary").lower()
    if role == "primary":
        engine = storage.engine
        if hasattr(engine, "n_shards"):
            targets = (props.get("replication.targets")
                       or props.get("replication.target") or "")
            parts = [t.strip() for t in targets.split(",") if t.strip()]
            if len(parts) != engine.n_shards:
                logger.warning(
                    "sharded replication needs one replication.targets "
                    "entry per shard (%d given, %d shards); replication "
                    "disabled", len(parts), engine.n_shards)
                return None
            ack_s = props.get_float("replication.ack_timeout_ms",
                                    5000.0) / 1000.0
            sinks = {}
            for q, part in enumerate(parts):
                host, _, port = part.rpartition(":")
                sinks[q] = SocketSink(host or "127.0.0.1", int(port),
                                      ack_timeout=ack_s)
            repl = ShardedReplicator(
                ShardedReplicationLog(storage), sinks,
                interval_ms=props.get_float("replication.interval_ms",
                                            200.0),
                registry=registry,
            ).start()
            return ReplicationHandle(role="primary", replicator=repl)
        target = props.get("replication.target")
        if not target:
            logger.warning("replication.role=primary without "
                           "replication.target; replication disabled")
            return None
        host, _, port = target.rpartition(":")
        repl = Replicator(
            ReplicationLog(storage),
            SocketSink(host or "127.0.0.1", int(port),
                       ack_timeout=props.get_float(
                           "replication.ack_timeout_ms", 5000.0) / 1000.0),
            interval_ms=props.get_float("replication.interval_ms", 200.0),
            registry=registry,
        ).start()
        return ReplicationHandle(role="primary", replicator=repl)
    if role == "standby":
        receiver = StandbyReceiver(storage, registry=registry)
        server = ReplicationServer(
            receiver, port=props.get_int("replication.listen_port", 7401),
        ).start()
        return ReplicationHandle(role="standby", receiver=receiver,
                                 server=server)
    raise ValueError(f"unknown replication.role: {role!r}")


def _maybe_control(storage: RateLimitStorage, props: AppProperties,
                   replication: ReplicationHandle | None):
    """Config-gated control-plane RPC port (OFF by default).

    Exposes THIS process's fence/lease/probe authority over the small
    length-prefixed-JSON wire (replication/control.py) so a remote
    orchestrator — or an operator with a socket — can PROBE it, FENCE
    it, grant/renew its serving lease, and RESTORE (unfence) it.  A
    standby-role process additionally serves the remote-promotion RPC
    and the lease-relay mailbox (its ``repl_rx_age_ms`` is the witness
    signal).  Always binds the RAW device storage: fencing authority is
    node-local and must not route through failover wrappers."""
    port = props.get_int("ratelimiter.control.port", 0)
    if port <= 0:
        return None
    if not hasattr(storage, "fence"):
        import logging

        logging.getLogger("ratelimiter").warning(
            "ratelimiter.control.port set but the %s backend has no "
            "fence/lease surface; control port disabled",
            type(storage).__name__)
        return None
    from ratelimiter_tpu.replication.control import (
        ControlServer,
        primary_handlers,
        standby_handlers,
    )

    host = props.get("ratelimiter.control.host") or "127.0.0.1"
    if replication is not None and replication.receiver is not None:
        handlers = standby_handlers(storage, replication.receiver,
                                    repl_server=replication.server)
    else:
        handlers = primary_handlers(
            storage,
            replicator=(replication.replicator
                        if replication is not None else None))
    return ControlServer(handlers, host=host, port=port).start()


def _maybe_orchestrator(storage: RateLimitStorage, props: AppProperties,
                        registry: MeterRegistry):
    """Config-gated self-healing failover (OFF by default).

    Requires a SHARDED device engine.  Builds the single-host N+1
    topology: an in-process standby mesh (one flat standby per shard),
    per-shard replication streams, a ``ShardFailoverRouter`` the app
    serves through, and the ``FailoverOrchestrator`` watching it all —
    a dead shard is fenced, its standby promoted, its keys re-routed,
    and a fresh standby re-seeded with zero operator involvement.

    Returns ``(handle_or_None, serving_storage)`` — when enabled, the
    ROUTER becomes the storage the breaker/retry wrappers compose
    around.
    """
    if not props.get_bool("ratelimiter.orchestrator.enabled", False):
        return None, storage
    import logging

    logger = logging.getLogger("ratelimiter")
    engine = getattr(storage, "engine", None)
    if not hasattr(engine, "n_shards"):
        logger.warning(
            "ratelimiter.orchestrator.enabled but the %s backend has no "
            "sharded engine (orchestrated failover promotes one shard of "
            "N); orchestrator disabled", type(storage).__name__)
        return None, storage
    from ratelimiter_tpu.replication import (
        BackendLeaseChannel,
        FailoverOrchestrator,
        OrchestratorConfig,
        ShardedReplicationLog,
        ShardedReplicator,
        ShardFailoverRouter,
        ShardStandbySet,
    )

    sps = int(engine.slots_per_shard)

    def standby_factory():
        return TpuBatchedStorage(num_slots=sps)

    mesh_set = ShardStandbySet(int(engine.n_shards), standby_factory,
                               registry=registry)
    repl = ShardedReplicator(
        ShardedReplicationLog(storage), mesh_set.in_process_sinks(),
        interval_ms=props.get_float("replication.interval_ms", 200.0),
        registry=registry,
    ).start()
    router = ShardFailoverRouter(storage)
    # Distributed fence lease (ARCHITECTURE §10c): with a TTL set, every
    # shard's channel grants the one in-process primary — the lease then
    # guards "the orchestrator loop is alive and talking to us" (a hung
    # or killed orchestrator self-fences the storage within one TTL
    # instead of leaving fencing authority silently dead).  Cross-host
    # deployments build remote channels (replication/remote.py) instead.
    lease_ttl = props.get_float(
        "ratelimiter.orchestrator.fence_lease_ttl_ms", 0.0)
    lease_channels = ({q: BackendLeaseChannel(storage)
                       for q in range(int(engine.n_shards))}
                      if lease_ttl > 0 else None)
    orch = FailoverOrchestrator(
        router, mesh_set, repl, standby_factory=standby_factory,
        config=OrchestratorConfig(
            probe_interval_ms=props.get_float(
                "ratelimiter.orchestrator.probe_interval_ms", 100.0),
            suspect_threshold=props.get_int(
                "ratelimiter.orchestrator.suspect_threshold", 3),
            hysteresis_ms=props.get_float(
                "ratelimiter.orchestrator.hysteresis_ms", 500.0),
            promote_retries=props.get_int(
                "ratelimiter.orchestrator.promote_retries", 3),
            promote_backoff_ms=props.get_float(
                "ratelimiter.orchestrator.promote_backoff_ms", 50.0),
            reseed=props.get_bool("ratelimiter.orchestrator.reseed", True),
            fence_lease_ttl_ms=lease_ttl,
            fence_wait_slack_ms=props.get_float(
                "ratelimiter.orchestrator.fence_wait_slack_ms", 100.0),
        ),
        lease_channels=lease_channels,
        registry=registry,
    ).start()
    handle = OrchestratorHandle(orchestrator=orch, router=router,
                                replicator=repl, standby_set=mesh_set)
    return handle, router


def build_app(props: AppProperties | None = None,
              storage: RateLimitStorage | None = None) -> AppContext:
    props = props or AppProperties.load()
    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache
    from ratelimiter_tpu.utils.logging import setup_logging

    setup_logging(props)
    enable_compile_cache(props.get("jax.cache.dir"))
    registry = MeterRegistry()
    # Flight recorder (observability/flightrecorder.py): the process-
    # global ring every subsystem appends state transitions to; sized +
    # SLO-armed from config here, served at /actuator/flightrecorder.
    from ratelimiter_tpu.observability import flight_recorder

    recorder = flight_recorder()
    recorder.resize(props.get_int("ratelimiter.obs.flight_capacity", 1024))
    slo_ms = props.get_float("ratelimiter.obs.slo_ms", 0.0)
    if slo_ms > 0:
        recorder.set_slo_ms(slo_ms)
    own_storage = storage is None
    storage = storage or build_storage(props, meter_registry=registry)
    replication = None
    breaker = None
    sidecar = None
    orchestrator = None
    leases = None
    edge = None
    control = None
    controller = None
    fleet = None
    fleet_control = None
    if own_storage:
        # Self-healing failover (the orchestrator owns its OWN per-shard
        # replication into an in-process standby mesh, so it supersedes
        # the replication.* wiring — both would fight over the journal).
        orchestrator, serving = _maybe_orchestrator(storage, props,
                                                    registry)
        if orchestrator is not None and props.get_bool(
                "replication.enabled", False):
            import logging

            logging.getLogger("ratelimiter").warning(
                "ratelimiter.orchestrator.enabled supersedes "
                "replication.* wiring (the orchestrator runs its own "
                "per-shard streams); replication.* ignored")
        elif orchestrator is None:
            # Replication attaches to the RAW TPU storage (the journal
            # hooks the engine), before the chaos/retry wrappers compose
            # around it.
            replication = _maybe_replication(storage, props, registry)
        sidecar = _maybe_sidecar(storage, props, registry)
        # Control port over the RAW storage's fence/lease authority
        # (plus the standby receiver's promote surface when this node
        # runs replication.role=standby).
        control = _maybe_control(storage, props, replication)
        if props.get_bool("warmup.enabled", True):
            warmup_shapes(storage,
                          max_batch=props.get_int("batcher.max_batch", 8192))
        # Fused-kernel fallback gauge at boot (the PR 4 silent-degrade
        # fix): the engine's settle_all() has resolved the probe by now,
        # so a probe failure on real hardware is visible from the first
        # scrape, not only after the first health hit.
        from ratelimiter_tpu.ops.pallas import relay_step

        registry.gauge(
            "ratelimiter.pallas.fused_fallback",
            "1 when the fused relay kernel's differential probe failed "
            "on this hardware (serving composed XLA instead)",
        ).set(1.0 if relay_step.fallback_info()["probe_failed"] else 0.0)
        # Boot-time link probe (r5): feeds the streaming loops' chunk-plan
        # and wire-format elections.  Best-effort — a backend without a
        # device link (memory) or a probe failure leaves the loops on the
        # profile-less defaults (giant growth, device-first sort policy).
        if props.get_bool("link.probe.enabled", True):
            if hasattr(storage, "probe_link"):
                try:
                    storage.probe_link()
                except Exception as exc:  # noqa: BLE001 — degraded boot
                    import logging

                    logging.getLogger("ratelimiter").warning(
                        "boot link probe failed (%s): streaming loops run "
                        "on profile-less defaults", exc)
        # The router (when the orchestrator is on) becomes the storage
        # the breaker/retry wrappers compose around — warmup and the
        # link probe above ran against the raw device storage.
        storage = serving
        # Leases grant against the SERVING storage (router when
        # present) so a promoted replacement receives the charges for
        # its keys exactly like decisions.
        leases = _maybe_leases(serving, sidecar, props, registry)
        edge = _maybe_edge(leases, props, registry)
        wrapped, breaker = _maybe_breaker(_maybe_chaos(storage, props),
                                          props, registry)
        storage = _maybe_retry(wrapped, props)
        # Degraded-mode seeds must follow live policy updates: an outage
        # after a set_policy approximates under the generation that is
        # actually serving, not the boot-time registration.
        if breaker is not None and breaker.fallback is not None \
                and hasattr(serving, "add_policy_listener"):
            serving.add_policy_listener(breaker.fallback.update_policy)
        fleet = _maybe_fleet(props, registry, recorder)
        # The adaptive controller actuates on the SERVING storage
        # (router when present) and reads the breaker's overload state
        # — or, in fleet mode, on the epoch-fenced FleetControlPlane
        # broadcasting to the whole cell.
        fleet_control, control_target = _maybe_fleet_control(
            serving, props, registry, recorder, fleet)
        controller = _maybe_controller(control_target, props, registry,
                                       breaker, recorder)

    limiters: Dict[str, RateLimiter] = {
        # Default API limiter: 100 req/min sliding window with local cache
        # (config/RateLimiterConfig.java:46-59).
        "api": SlidingWindowRateLimiter(
            storage,
            RateLimitConfig(max_permits=100, window_ms=60_000,
                            enable_local_cache=True, local_cache_ttl_ms=100),
            registry,
        ),
        # Strict auth limiter: 10/min, no cache (:65-77).
        "auth": SlidingWindowRateLimiter(
            storage,
            RateLimitConfig(max_permits=10, window_ms=60_000,
                            enable_local_cache=False),
            registry,
        ),
        # Burst-friendly token bucket: 50 capacity, 10/sec refill (:83-95).
        "burst": TokenBucketRateLimiter(
            storage,
            RateLimitConfig(max_permits=50, window_ms=60_000, refill_rate=10.0),
            registry,
        ),
    }
    if sidecar is not None:
        # Expose the HTTP tier's limiters to sidecar clients under their
        # existing lids — both front doors share the same device
        # counters per key (ids are distributed via config, like the
        # reference's named Spring beans; see /actuator/health.sidecar).
        for name, limiter in limiters.items():
            lid = getattr(limiter, "_lid", None)
            if lid is not None:
                algo = "tb" if isinstance(limiter, TokenBucketRateLimiter) \
                    else "sw"
                sidecar.expose(lid, algo, limiter._config)
    return AppContext(
        props=props,
        storage=storage,
        registry=registry,
        limiters=limiters,
        fail_open=props.get_bool("ratelimiter.fail_open", True),
        replication=replication,
        breaker=breaker,
        sidecar=sidecar,
        recorder=recorder,
        orchestrator=orchestrator,
        leases=leases,
        control=control,
        controller=controller,
        fleet=fleet,
        fleet_control=fleet_control,
        edge=edge,
    )
