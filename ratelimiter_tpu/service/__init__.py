from ratelimiter_tpu.service.app import make_server, serve_forever
from ratelimiter_tpu.service.props import AppProperties
from ratelimiter_tpu.service.wiring import AppContext, build_app

__all__ = ["make_server", "serve_forever", "AppProperties", "AppContext", "build_app"]
