"""HTTP demo API (C2 parity).

The five endpoints of the reference's controller (DemoController.java:39-140)
with the same request/response shapes and 429 semantics:

- ``GET  /api/data``               — api limiter, key = X-User-ID or "anonymous"
- ``POST /api/login``              — auth limiter, key = body username
- ``POST /api/batch``              — burst limiter, permits = body size,
                                     key = required X-User-ID
- ``GET  /api/health``             — not rate limited
- ``DELETE /api/admin/reset/{id}`` — resets all three limiters for the user
  (note: the reference's README documents this as /admin/reset, but the
  controller actually mounts it under /api — quirk Q4; we implement BOTH
  paths so either set of docs works)

Plus actuator-style observability (application.properties:14-15):
``GET /actuator/health`` and ``GET /actuator/metrics``.

Improvements over the reference, both of which its own docs promise:

- **Fail-open** on storage failure (ARCHITECTURE notes prescribe it; the
  reference actually 500s — SURVEY.md §5.3): configurable, on by default.
- **X-RateLimit-Limit / X-RateLimit-Remaining headers** (described in
  API_EXAMPLES but never sent by the reference).

Implementation is a stdlib ThreadingHTTPServer: the service tier is a thin
shim — concurrency and throughput live in the micro-batched device engine,
not in the web framework, so no external dependency is warranted.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ratelimiter_tpu.engine.errors import OverloadedError, ShutdownError
from ratelimiter_tpu.service.wiring import AppContext, build_app
from ratelimiter_tpu.storage.errors import StorageException
from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("service.app")

_RESET_RE = re.compile(r"^/(?:api/)?admin/reset/([^/]+)$")
_PIN_RE = re.compile(r"^/actuator/policies/(\d+)/pin$")


def _now_ms() -> int:
    return time.time_ns() // 1_000_000


def _find_surface(storage, name: str):
    """Walk the storage wrapper chain (retry -> breaker -> chaos -> ...)
    for a named callable surface (e.g. the failover router's
    ``shard_health`` / ``shard_status``)."""
    seen = set()
    while storage is not None and id(storage) not in seen:
        seen.add(id(storage))
        fn = getattr(storage, name, None)
        if callable(fn):
            return fn
        storage = getattr(storage, "_inner", None)
    return None


def _find_shard_health(storage):
    return _find_surface(storage, "shard_health")


def _find_attr(storage, name: str):
    """Like :func:`_find_surface` but for non-callable attributes (the
    telemetry plane, the lineage ring)."""
    seen = set()
    while storage is not None and id(storage) not in seen:
        seen.add(id(storage))
        value = getattr(storage, name, None)
        if value is not None:
            return value
        storage = getattr(storage, "_inner", None)
    return None


def health_payload(ctx: AppContext) -> dict:
    """UP / DEGRADED / SHEDDING / DOWN, most severe condition wins.

    - DOWN: the backend is unavailable (or the breaker is open with no
      degraded fallback and fail-open off), OR the orchestrator holds a
      shard in terminal ``FAILED`` (fail-closed, every standby
      candidate exhausted — an outage for that keyspace until an
      operator unfences, not a degradation) — only DOWN returns 503.
    - DEGRADED: the breaker is open/half-open; decisions are served by
      the degraded host limiter (or fail-open).  ALSO: a sharded
      deployment with a failed or promoted-replacement shard — the
      surviving shards keep serving, so a single dead shard is a
      DEGRADED-shard state, never DOWN.
    - SHEDDING: admission control shed requests within the health
      window — the micro-batcher's queue bound / deadline sheds AND the
      sidecar's per-connection pipeline sheds both count (the TCP front
      door participates in the same state machine as the HTTP tier).
    - UP: everything on the device path.

    The payload also carries the fused Pallas relay kernel's live/
    fallback state (``pallas.relay_fused_live``): a probe failure on
    real hardware silently reverts the headline dispatch to composed
    XLA, and this is where that shows up.

    Module-level so drills can evaluate the state machine without an
    HTTP server in the loop.
    """
    try:
        storage_up = bool(ctx.storage.is_available())
    except Exception:  # noqa: BLE001 — an erroring health probe is DOWN
        storage_up = False
    breaker = getattr(ctx, "breaker", None)
    batcher = getattr(ctx.storage, "_batcher", None)
    sidecar = getattr(ctx, "sidecar", None)
    payload: dict = {"storage": {"available": storage_up}}
    from ratelimiter_tpu.ops.pallas import relay_step

    pallas = relay_step.fallback_info()
    payload["pallas"] = pallas
    if ctx.registry is not None:
        ctx.registry.gauge(
            "ratelimiter.pallas.fused_fallback",
            "1 when the fused relay kernel's differential probe failed "
            "on this hardware (serving composed XLA instead)",
        ).set(1.0 if pallas["probe_failed"] else 0.0)
    degraded_shards = []
    shard_health_fn = _find_shard_health(ctx.storage)
    if shard_health_fn is not None:
        shards = shard_health_fn()
        payload["shards"] = {str(q): v for q, v in shards.items()}
        degraded_shards = [q for q, v in shards.items() if v != "active"]
        status_fn = _find_surface(ctx.storage, "shard_status")
        if status_fn is not None:
            # DEGRADED-shard detail: time-in-state + last-transition
            # timestamp per shard, so operators (and the orchestrator
            # drill) can assert promotion-window bounds from the health
            # payload alone.
            payload["shards_detail"] = {
                str(q): v for q, v in status_fn().items()}
    orch = getattr(ctx, "orchestrator", None)
    failed_terminal: list = []
    if orch is not None:
        st = orch.orchestrator.status()
        # Terminal FAILED = the orchestrator exhausted every standby
        # candidate and failed the shard closed: that keyspace is denying
        # 100% of its traffic with NO recovery in flight — an outage, not
        # a degradation (the operator exit is /actuator/orchestrator/
        # unfence).
        failed_terminal = sorted(
            q for q, s in st["shards"].items() if s["state"] == "FAILED")
        payload["orchestrator"] = {
            "fence_epoch": st["fence_epoch"],
            "promotions": st["promotions"],
            "false_alarms": st["false_alarms"],
            "failed_shards": failed_terminal,
            "states": {q: s["state"] for q, s in st["shards"].items()},
        }
        if "shards_detail" in payload:
            for q, s in st["shards"].items():
                detail = payload["shards_detail"].get(str(q))
                if detail is not None:
                    detail["orchestrator_state"] = s["state"]
    controller = getattr(ctx, "controller", None)
    if controller is not None:
        # Control-plane mirror (ARCHITECTURE §15): pinned lids and the
        # policy generation belong in the health payload so an operator
        # can see a frozen or actively-scaling control loop without a
        # second request.
        st = controller.status()
        payload["control"] = {
            "generation": st["generation"],
            "global_scale": st["global_scale"],
            "pinned": st["pinned"],
            "adjustments": st["adjustments"],
        }
    fc = getattr(ctx, "fleet_control", None)
    control_lagging: list = []
    if fc is not None:
        # Generation-convergence fold (ARCHITECTURE §15): a member node
        # whose applied policy generation sits BEHIND the leader's last
        # broadcast is serving stale limits — degraded correctness for
        # its slice of the cell, never DOWN (decisions still flow).
        # Reads the plane's cached per-node view; no RPC on the health
        # path.
        control_lagging = fc.lagging_nodes()
        plane = fc.plane
        payload["controller"] = {
            "node": plane.node,
            "is_leader": plane.is_leader,
            "epoch": plane.epoch,
            "last_broadcast_generation": plane.last_broadcast_generation,
            "lagging_nodes": control_lagging,
        }
    fleet = getattr(ctx, "fleet", None)
    fleet_degraded: list = []
    if fleet is not None:
        # Fleet fold (ARCHITECTURE §16): a FAILED node means keyspace
        # moved (or is moving) off a dead process; a DRAINING node is
        # capacity scheduled out mid-rolling-upgrade.  Either is
        # degraded capacity for the cell this process manages — never
        # DOWN (the orchestrator's terminal-FAILED covers hard-down).
        fleet_degraded = fleet.degraded_nodes()
        payload["fleet"] = {
            "live_nodes": fleet.live_nodes(),
            "degraded_nodes": fleet_degraded,
            "respawns": fleet.respawns,
            "reseeds": fleet.reseeds,
            "upgrade_steps": fleet.upgrade_steps,
        }
    shedding = False
    window_s = ctx.props.get_float(
        "ratelimiter.overload.shed_health_window_ms", 5000.0) / 1000.0

    def _recent(stamp: float) -> bool:
        return stamp > 0 and (time.monotonic() - stamp) <= window_s

    if batcher is not None:
        shedding = _recent(float(getattr(batcher, "last_shed_s", 0.0)))
        payload["overload"] = {
            "queue_depth": batcher.queue_depth(),
            "max_pending": batcher.max_pending,
            "shed_total": batcher.shed_total,
            "deadline_expired_total": batcher.deadline_total,
        }
    if sidecar is not None:
        shedding = shedding or _recent(
            float(getattr(sidecar, "last_shed_s", 0.0)))
        payload["sidecar"] = {
            "connections": sidecar.connections(),
            "in_flight": sidecar.inflight(),
            "malformed_total": sidecar.malformed_total,
            "idle_closed_total": sidecar.idle_closed_total,
            "pipeline_shed_total": sidecar.pipeline_shed_total,
            "refused_total": sidecar.refused_total,
        }
    if breaker is not None:
        payload["breaker"] = breaker.status()
        if breaker.fallback is not None:
            payload["degraded"] = {
                "touched_keys": len(breaker.fallback.touched())}
    if failed_terminal:
        # A fail-closed shard with no standby left outranks every other
        # condition: part of the keyspace is hard-down until an operator
        # unfences, so the instance must read DOWN (503) for it.
        payload["status"] = "DOWN"
    elif breaker is not None and breaker.state != "closed":
        degraded_serving = (breaker.fallback is not None
                            or ctx.fail_open)
        payload["status"] = "DEGRADED" if degraded_serving else "DOWN"
    elif not storage_up:
        payload["status"] = "DOWN"
    elif degraded_shards or fleet_degraded or control_lagging:
        # One shard failed or running on a promoted replacement while
        # the survivors serve — or a managed fleet node is FAILED/
        # DRAINING, or a member serves a policy generation behind the
        # controller leader's broadcast: degraded capacity (or
        # correctness), not an outage.
        payload["status"] = "DEGRADED"
    elif shedding:
        payload["status"] = "SHEDDING"
    else:
        payload["status"] = "UP"
    recorder = getattr(ctx, "recorder", None)
    if recorder is not None:
        # Only transitions land in the flight recorder's timeline —
        # a steady-state health poll records nothing.
        recorder.record_transition("health", payload["status"])
    return payload


class RateLimiterHandler(BaseHTTPRequestHandler):
    ctx: AppContext  # injected by make_server

    # -- plumbing -------------------------------------------------------------
    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _json(self, status: int, payload: dict, headers: dict | None = None):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if length == 0:
            return {}
        try:
            return json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError:
            return {}

    def _overloaded(self, exc: OverloadedError):
        """429 + Retry-After: the request was SHED by admission control
        (bounded queue / queue deadline), distinct from both the policy
        429 (_rate_limit_exceeded) and the storage-down 503."""
        retry_ms = float(getattr(exc, "retry_after_ms", 0.0)) or 1000.0
        secs = max(1, int(-(-retry_ms // 1000.0)))
        self.ctx.registry.counter(
            "ratelimiter.overload.rejected",
            "Requests answered 429 by overload admission control",
        ).increment()
        return self._json(429, {
            "error": "Overloaded",
            "message": "Server is shedding load. Please retry later.",
            "reason": getattr(exc, "reason", "overloaded"),
        }, headers={"Retry-After": secs})

    def _storage_unavailable(self):
        return self._json(503, {"error": "storage unavailable"},
                          headers={"Retry-After": 1})

    def _rate_limit_exceeded(self, limiter, key: str, limit: int):
        # 429 with the same error body shape (DemoController.java:129-140).
        remaining = self._safe_available(limiter, key)
        self._json(429, {
            "error": "Rate limit exceeded",
            "message": "Too many requests. Please try again later.",
            "remaining": remaining,
        }, headers={"X-RateLimit-Limit": limit, "X-RateLimit-Remaining": remaining})

    def _safe_available(self, limiter, key: str) -> int:
        try:
            return int(limiter.get_available_permits(key))
        except StorageException:
            return -1  # "unable to determine" (core/RateLimiter.java:31-37)

    def _try_acquire(self, limiter, key: str, permits: int = 1) -> bool:
        """Apply the fail-open policy: on storage failure, allow (and count)
        rather than erroring the request — the availability-over-strictness
        trade the reference documents."""
        try:
            return limiter.try_acquire(key, permits)
        except StorageException as exc:
            if self.ctx.fail_open:
                _log.warning("storage failure for key=%s: %s — failing open",
                             key, exc)
                self.ctx.registry.counter(
                    "ratelimiter.failopen.allowed",
                    "Requests allowed due to fail-open on storage errors",
                ).increment()
                return True
            raise

    # -- routes ---------------------------------------------------------------
    def do_GET(self):
        if self.path == "/api/data":
            return self._get_data()
        if self.path == "/api/health":
            return self._json(200, {"status": "UP", "timestamp": str(_now_ms())})
        if self.path == "/actuator/health":
            payload = self._health_payload()
            return self._json(503 if payload["status"] == "DOWN" else 200,
                              payload)
        if self.path == "/actuator/metrics":
            return self._json(200, {"meters": self.ctx.registry.scrape()})
        if self.path.startswith("/actuator/prometheus"):
            return self._prometheus()
        if self.path.startswith("/actuator/tenants"):
            return self._tenants()
        if self.path == "/actuator/policies":
            return self._policies()
        if self.path.startswith("/actuator/flightrecorder"):
            return self._flightrecorder()
        if self.path == "/actuator/replication":
            repl = self.ctx.replication
            if repl is None:
                return self._json(200, {"enabled": False})
            return self._json(200, {"enabled": True, **repl.status()})
        if self.path == "/actuator/orchestrator":
            orch = getattr(self.ctx, "orchestrator", None)
            if orch is None:
                return self._json(200, {"enabled": False})
            return self._json(200, orch.status())
        if self.path == "/actuator/fleet":
            fleet = getattr(self.ctx, "fleet", None)
            if fleet is None:
                return self._json(200, {"enabled": False})
            return self._json(200, {"enabled": True, **fleet.status()})
        if self.path == "/actuator/controller":
            return self._controller_actuator()
        if self.path == "/actuator/edge":
            edge = getattr(self.ctx, "edge", None)
            if edge is None:
                return self._json(200, {"enabled": False})
            return self._json(200, {"enabled": True, **edge.status()})
        if self.path.startswith("/actuator/trace"):
            trace = getattr(self.ctx.storage, "trace", None)
            if trace is None:
                return self._json(200, {"total_dispatches": 0, "recent": []})
            return self._json(200, trace.snapshot())
        self._json(404, {"error": "not found"})

    def _prometheus(self):
        """Prometheus text exposition over every registered meter, plus
        the telemetry plane's labeled per-tenant / per-key-class
        series."""
        from ratelimiter_tpu.observability import prometheus

        plane = _find_attr(self.ctx.storage, "telemetry")
        collectors = (plane,) if plane is not None else ()
        body = prometheus.render(self.ctx.registry,
                                 collectors=collectors).encode()
        self.send_response(200)
        self.send_header("Content-Type", prometheus.CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _tenants(self):
        """Per-tenant usage accounting + telemetry staleness
        (ARCHITECTURE §13e — the human-readable face of UsageSignals)."""
        plane = _find_attr(self.ctx.storage, "telemetry")
        if plane is None:
            return self._json(200, {"enabled": False, "tenants": {}})
        payload = {"enabled": True, **plane.tenants_payload()}
        leases = getattr(self.ctx, "leases", None)
        if leases is not None:
            payload["leases"] = leases.status()
        return self._json(200, payload)

    def _policies(self):
        """Per-lid effective policy, generation and controller state
        (ARCHITECTURE §15 — the control plane's operator face).  Serves
        the storage's policy_info even with the controller off, so the
        generation metadata is always inspectable."""
        info_fn = _find_surface(self.ctx.storage, "policy_info")
        payload: dict = {"enabled": False}
        if info_fn is not None:
            payload.update(info_fn())
        controller = getattr(self.ctx, "controller", None)
        if controller is not None:
            payload["enabled"] = True
            payload["controller"] = controller.status()
        return self._json(200, payload)

    def _controller_actuator(self):
        """Controller leadership surface (ARCHITECTURE §15): who leads
        the cell, at what fence epoch, the last broadcast policy
        generation, and every member node's applied generation — the
        operator's one-request view of the generation-convergence
        invariant.  Without fleet mode, falls back to the local
        controller's generation view."""
        fc = getattr(self.ctx, "fleet_control", None)
        if fc is not None:
            return self._json(200, fc.status())
        controller = getattr(self.ctx, "controller", None)
        if controller is None:
            return self._json(200, {"enabled": False})
        st = controller.status()
        return self._json(200, {
            "enabled": True, "fleet": False,
            "generation": st["generation"],
            "adjustments": st["adjustments"],
            "signals_stale_ticks": st["signals_stale_ticks"],
        })

    def _pin_policy(self, lid: str):
        """Operator override: freeze a lid out of the control loop
        (body ``{"pinned": false}`` releases it)."""
        controller = getattr(self.ctx, "controller", None)
        if controller is None:
            return self._json(409, {"error": "adaptive control not "
                                             "enabled"})
        pinned = bool(self._body().get("pinned", True))
        try:
            out = controller.pin(int(lid), pinned)
        except (KeyError, ValueError) as exc:
            return self._json(404, {"error": str(exc)})
        return self._json(200, out)

    def _flightrecorder(self):
        """Flight-recorder snapshot; ``?kind=`` (exact or dotted
        prefix), ``?since_ms=`` (wall-clock ms), and ``?last=`` filter
        ring-side."""
        import urllib.parse

        recorder = self.ctx.recorder
        if recorder is None:
            return self._json(200, {"total_events": 0, "events": [],
                                    "anomalies": []})
        query = urllib.parse.urlparse(self.path).query
        params = urllib.parse.parse_qs(query)

        def _one(name):
            vals = params.get(name)
            return vals[0] if vals else None

        kind = _one("kind")
        since_ms = _one("since_ms")
        last = _one("last")
        try:
            since_ms = int(since_ms) if since_ms is not None else None
            last = int(last) if last is not None else 256
        except ValueError:
            return self._json(400, {
                "error": "since_ms and last must be integers"})
        return self._json(200, recorder.snapshot(
            last=last, kind=kind, since_ms=since_ms))

    def do_POST(self):
        if self.path == "/api/login":
            return self._login()
        if self.path == "/api/batch":
            return self._batch()
        if self.path == "/actuator/replication/promote":
            return self._promote()
        if self.path == "/actuator/orchestrator/unfence":
            return self._unfence()
        m = _PIN_RE.match(self.path)
        if m:
            return self._pin_policy(m.group(1))
        self._json(404, {"error": "not found"})

    def _unfence(self):
        """Operator recovery for a terminal FAILED shard: lift the
        fence(s), repair the router back to the primary, re-seed a
        fresh standby — without a Python shell.  Body: {"shard": N}."""
        orch = getattr(self.ctx, "orchestrator", None)
        if orch is None:
            return self._json(409, {"error": "orchestrator not enabled"})
        shard = self._body().get("shard")
        if shard is None:
            return self._json(400, {"error": "body must carry {\"shard\": N}"})
        try:
            out = orch.orchestrator.unfence(int(shard))
        except (TypeError, ValueError) as exc:
            return self._json(409, {"error": str(exc)})
        return self._json(200, out)

    def _promote(self):
        """Failover control: promote a standby to serving primary."""
        repl = self.ctx.replication
        if repl is None or repl.receiver is None:
            return self._json(409, {"error": "not a replication standby"})
        from ratelimiter_tpu.replication import ReplicationStateError

        force = bool(self._body().get("force", False))
        try:
            repl.receiver.promote(force=force)
        except ReplicationStateError as exc:
            return self._json(409, {"error": str(exc)})
        return self._json(200, repl.status())

    def do_DELETE(self):
        m = _RESET_RE.match(self.path)
        if m:
            return self._reset(m.group(1))
        self._json(404, {"error": "not found"})

    # -- health state machine -------------------------------------------------
    def _health_payload(self) -> dict:
        return health_payload(self.ctx)

    # -- endpoint bodies ------------------------------------------------------
    def _get_data(self):
        limiter = self.ctx.limiters["api"]
        key = self.headers.get("X-User-ID") or "anonymous"
        try:
            if not self._try_acquire(limiter, key):
                return self._rate_limit_exceeded(limiter, key, 100)
        except OverloadedError as exc:
            return self._overloaded(exc)
        except ShutdownError:
            return self._storage_unavailable()
        except StorageException:
            return self._storage_unavailable()
        remaining = self._safe_available(limiter, key)
        self._json(200, {
            "message": "Success!",
            "remaining": remaining,
            "data": {"timestamp": _now_ms()},
        }, headers={"X-RateLimit-Limit": 100, "X-RateLimit-Remaining": remaining})

    def _login(self):
        limiter = self.ctx.limiters["auth"]
        username = self._body().get("username", "unknown")
        try:
            if not self._try_acquire(limiter, username):
                return self._rate_limit_exceeded(limiter, username, 10)
        except OverloadedError as exc:
            return self._overloaded(exc)
        except ShutdownError:
            return self._storage_unavailable()
        except StorageException:
            return self._storage_unavailable()
        self._json(200, {
            "message": "Login successful",
            "remaining_attempts": self._safe_available(limiter, username),
        })

    def _batch(self):
        limiter = self.ctx.limiters["burst"]
        user_id = self.headers.get("X-User-ID")
        if not user_id:
            return self._json(400, {"error": "X-User-ID header required"})
        size = int(self._body().get("size", 1))
        if size <= 0:
            return self._json(400, {"error": "size must be positive"})
        try:
            if not self._try_acquire(limiter, user_id, size):
                return self._rate_limit_exceeded(limiter, user_id, 50)
        except OverloadedError as exc:
            return self._overloaded(exc)
        except ShutdownError:
            return self._storage_unavailable()
        except StorageException:
            return self._storage_unavailable()
        self._json(200, {
            "message": "Batch processed",
            "items_processed": size,
            "tokens_remaining": self._safe_available(limiter, user_id),
        })

    def _reset(self, user_id: str):
        for limiter in self.ctx.limiters.values():
            limiter.reset(user_id)
        self._json(200, {"message": f"Rate limits reset for user: {user_id}"})


def make_server(ctx: AppContext | None = None, port: int | None = None) -> ThreadingHTTPServer:
    ctx = ctx or build_app()
    if port is None:
        port = ctx.props.get_int("server.port", 8080)
    handler = type("BoundHandler", (RateLimiterHandler,), {"ctx": ctx})
    server = ThreadingHTTPServer(("0.0.0.0", port), handler)
    server.ctx = ctx  # type: ignore[attr-defined]
    return server


def serve_forever(ctx: AppContext | None = None, port: int | None = None) -> None:
    server = make_server(ctx, port)
    try:
        server.serve_forever()
    finally:
        server.ctx.close()  # type: ignore[attr-defined]


def main() -> None:  # python -m ratelimiter_tpu.service.app
    import sys

    from ratelimiter_tpu.service.props import AppProperties

    path = sys.argv[1] if len(sys.argv) > 1 else "application.properties"
    ctx = build_app(AppProperties.load(path))
    port = ctx.props.get_int("server.port", 8080)
    print(f"ratelimiter_tpu serving on :{port} "
          f"(backend={ctx.props.get('storage.backend')})")
    serve_forever(ctx, port)


if __name__ == "__main__":
    main()
