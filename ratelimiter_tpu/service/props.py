"""Application properties (C13 parity).

The reference configures itself via Spring ``application.properties``
(redis.host/redis.port/server.port, application.properties:1-15) with env
overrides from docker-compose.  Here: the same ``key=value`` file format,
env-var overrides (``RATELIMITER_<KEY with . -> _ uppercased>``), and typed
accessors with defaults.

Values are validated at construction: a malformed int/float/bool for a
known key logs a warning naming the offending key and falls back to the
default (a typo'd ``batcher.max_batch=81q2`` must not crash — or silently
zero — the batcher at first access), and unknown ``RATELIMITER_*`` env
keys / unknown file keys are warned about instead of passing silently.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from ratelimiter_tpu.utils.logging import get_logger

log = get_logger("service.props")


DEFAULTS = {
    "server.port": "8080",
    # "tpu" (device-batched) or "memory" (host dict) — the storage plugin.
    "storage.backend": "tpu",
    "storage.num_slots": str(1 << 20),
    "batcher.max_batch": "8192",
    "batcher.max_delay_ms": "0.5",
    # Device batches allowed in flight at once (dispatched, fetch pending).
    # >1 overlaps fetch latency with the next dispatches.
    "batcher.max_inflight": "4",
    # Fail-open on storage failure: documented in the reference's
    # architecture notes but never implemented there (SURVEY.md §5.3);
    # implemented here and ON by default as documented.
    "ratelimiter.fail_open": "true",
    # Admission control (engine/batcher.py): bound on each algo's pending
    # micro-batch queue (0 = unbounded) and the per-request QUEUE deadline
    # budget in ms (0 = none) — a request not dispatched within it is shed
    # with a 429 + Retry-After instead of waiting forever.
    "ratelimiter.overload.max_pending": "65536",
    "ratelimiter.overload.deadline_ms": "1000",
    # /actuator/health reports SHEDDING while a shed happened within this
    # window (sheds are bursty; an instantaneous queue-depth read flaps).
    "ratelimiter.overload.shed_health_window_ms": "5000",
    # Circuit breaker (storage/breaker.py), composed retry(breaker(chaos(
    # storage))): consecutive backend faults open it; while open, decisions
    # short-circuit to the degraded host limiter (storage/degraded.py)
    # instead of paying retry exhaustion per request.
    "breaker.enabled": "true",
    "breaker.failure_threshold": "8",
    "breaker.open_ms": "5000",
    "breaker.half_open_probes": "1",
    # Degraded-mode host limiter: fail-approximate instead of fail-open
    # while the breaker is open (device-batching backends only).
    # max_keys bounds the last-seen-counter snapshot cache.
    "ratelimiter.degraded.enabled": "true",
    "ratelimiter.degraded.max_keys": "65536",
    # Decision sidecar (service/sidecar.py): binary TCP ingress funneling
    # every connection into the shared micro-batcher.  OFF by default —
    # when enabled, build_app starts it next to the HTTP tier on
    # sidecar.port.  The hardening bounds (0 disables each): frame/key
    # size caps answered in-protocol with BAD_FRAME, per-connection
    # pipeline cap shed with a typed retry-after status, global
    # connection limit, idle/read deadlines (slowloris), the bound on
    # waiting for a wedged batch, and the graceful-drain budget of stop().
    "ratelimiter.sidecar.enabled": "false",
    "ratelimiter.sidecar.port": "7400",
    "ratelimiter.sidecar.max_frame_bytes": "4096",
    "ratelimiter.sidecar.max_key_bytes": "1024",
    "ratelimiter.sidecar.max_pipeline": "1024",
    "ratelimiter.sidecar.max_connections": "1024",
    "ratelimiter.sidecar.idle_timeout_ms": "60000",
    "ratelimiter.sidecar.read_timeout_ms": "5000",
    "ratelimiter.sidecar.resolve_timeout_ms": "30000",
    "ratelimiter.sidecar.drain_timeout_ms": "1000",
    # Micro-batch assembly (r11, ARCHITECTURE §6d).  adaptive_flush: the
    # flush deadline/size trigger track the measured device-step time
    # (engine/flush_control.py), hard-clamped within
    # [flush_floor_ms, batcher.max_delay_ms] / [32, batcher.max_batch].
    "ratelimiter.microbatch.adaptive_flush": "true",
    "ratelimiter.microbatch.flush_floor_ms": "0.05",
    # Hybrid host-side serving tier (cache/hybrid.py): answers hot
    # repeat-reject and safely-under-limit keys host-side from exact
    # adopted state, device-confirmed asynchronously; over-admission
    # bounded like the degraded path (one extra max_permits per key per
    # window, worst case).  OFF by default.  ttl_ms bounds staleness
    # since the last device confirmation; unconfirmed_cap bounds
    # forwarded-but-unconfirmed mutations per key; guard_ms refuses
    # host serves in the last slice of a sliding window.
    "ratelimiter.cache.hybrid.enabled": "false",
    "ratelimiter.cache.hybrid.ttl_ms": "50",
    "ratelimiter.cache.hybrid.max_keys": "65536",
    "ratelimiter.cache.hybrid.unconfirmed_cap": "64",
    "ratelimiter.cache.hybrid.guard_ms": "5",
    # Token leases (leases/, ARCHITECTURE §14): the server grants
    # clients bounded per-key permit budgets burned locally (protocol
    # v3 LEASE/RENEW/RELEASE on the sidecar) — one wire frame per
    # budget instead of one per decision.  OFF by default.
    # default_budget/max_budget bound grants (wire cap 65535); ttl_ms
    # bounds a dead client's strand (sliding-window leases also clamp
    # to the remaining window); deny_ttl_ms is the retry hint a zero
    # grant carries; max_leases bounds the server table.
    "ratelimiter.lease.enabled": "false",
    "ratelimiter.lease.default_budget": "64",
    "ratelimiter.lease.max_budget": "1024",
    "ratelimiter.lease.ttl_ms": "2000",
    "ratelimiter.lease.deny_ttl_ms": "25",
    "ratelimiter.lease.max_leases": "65536",
    # Bulk (aggregator-tier, §14b) grants may exceed max_budget up to
    # this cap; 0 keeps them clamped like ordinary grants.
    "ratelimiter.lease.max_bulk_budget": "0",
    # Edge aggregator tier (edge/, ARCHITECTURE §14b): one bulk lease
    # per hot (lid, key) subleased to in-process clients, the whole
    # portfolio renewed in ONE columnar frame per flush interval.
    # Requires ratelimiter.lease.enabled.  OFF by default.
    "ratelimiter.edge.enabled": "false",
    "ratelimiter.edge.bulk_budget": "4096",
    "ratelimiter.edge.slice_budget": "64",
    "ratelimiter.edge.flush_ms": "50",
    # Observability (observability/, ARCHITECTURE §13).  trace_sample:
    # record one full per-request lifecycle trace per ~N requests into
    # the enriched /actuator/trace ring (0 = off).  slo_ms: any dispatch
    # slower than this snapshots its stage breakdown + recent flight-
    # recorder events as an anomaly (0 = off).  flight_capacity: bound
    # on the structured-event ring behind /actuator/flightrecorder.
    "ratelimiter.obs.trace_sample": "0",
    "ratelimiter.obs.slo_ms": "0",
    "ratelimiter.obs.flight_capacity": "1024",
    # Fleet telemetry plane (observability/telemetry.py + usage.py,
    # ARCHITECTURE §13e): per-tenant usage ring bound (tenants over the
    # cap are counted, not tracked), the LRU window of distinct clients
    # tracked for the staleness gauge, and the trace-lineage ring bound
    # (sampled trace ids whose hop paths are retained).
    "ratelimiter.usage.max_tenants": "256",
    "ratelimiter.telemetry.max_clients": "1024",
    "ratelimiter.obs.lineage_capacity": "256",
    # Shard the slot array over all visible devices when > 1.
    "parallel.shard": "auto",
    # Compile hot dispatch shapes at boot (moves 40-90s/shape jit stalls
    # out of the first requests).
    "warmup.enabled": "true",
    # Boot-time host<->device link probe feeding the streaming loops'
    # chunk plans (storage/tpu.py).
    "link.probe.enabled": "true",
    # Persistent XLA compile-cache dir; empty -> ~/.cache/ratelimiter_tpu/jax.
    "jax.cache.dir": "",
    # Chaos drill: inject StorageException on this fraction of storage ops
    # (0 = off) and/or add latency to every op (fault-tolerance rehearsal).
    "chaos.failure_rate": "0",
    "chaos.latency_ms": "0",
    # Console logging (application.properties:9-11 analog): level for the
    # ratelimiter_tpu logger hierarchy + the console pattern (single
    # source of truth for the default lives in utils/logging.py).
    "logging.level": "INFO",
    "logging.pattern": "",  # empty -> utils/logging.DEFAULT_PATTERN
    # Per-op storage retry (RedisRateLimitStorage.java:155-178 analog):
    # attempts with linear backoff delay*attempt, then StorageException
    # escalates to fail-open. 0 retries disables the wrapper.
    "storage.retry.max_retries": "3",
    "storage.retry.delay_ms": "10",
    # Live state replication (replication/): OFF by default.  A primary
    # journals dirty slots and ships epoch frames to replication.target
    # (host:port of a standby's listener); a standby listens on
    # replication.listen_port, applies frames to its shadow engine, and
    # promotes via POST /actuator/replication/promote on failover.
    "replication.enabled": "false",
    "replication.role": "primary",
    "replication.target": "",
    "replication.targets": "",
    "replication.listen_port": "7401",
    "replication.interval_ms": "200",
    # Standby-link ack deadline (replication/transport.py): a send or
    # heartbeat unacked within this window fails fast, and enough
    # consecutive failures mark the link DEAD (standby gone, replica
    # going stale) instead of silently growing the coalescing queue.
    "replication.ack_timeout_ms": "5000",
    # Self-healing failover orchestrator (replication/orchestrator.py):
    # OFF by default.  When enabled on a SHARDED primary it builds an
    # in-process standby mesh (one flat standby per shard), replicates
    # per shard, routes through a ShardFailoverRouter, and watches
    # per-shard liveness through the MONITORING -> SUSPECT (consecutive
    # failures + hysteresis) -> FENCING (monotonic fence epoch; zombie
    # dispatches refused with FencedError) -> PROMOTING (bounded
    # retry/backoff) -> RESTORED (fresh standby re-seeded, back to N+1)
    # state machine — zero manual actuator calls.
    "ratelimiter.orchestrator.enabled": "false",
    "ratelimiter.orchestrator.probe_interval_ms": "100",
    "ratelimiter.orchestrator.suspect_threshold": "3",
    "ratelimiter.orchestrator.hysteresis_ms": "500",
    "ratelimiter.orchestrator.promote_retries": "3",
    "ratelimiter.orchestrator.promote_backoff_ms": "50",
    "ratelimiter.orchestrator.reseed": "true",
    # Distributed fence lease (ARCHITECTURE §10c): > 0 makes the
    # orchestrator grant the serving storage an epoch lease of this TTL,
    # renewed while probes answer — a primary partitioned from its
    # orchestrator self-fences within one TTL (bounded over-admission
    # with no quorum machinery).  0 keeps the PR 9 process-local fence.
    # Keep the TTL at or above the detection budget
    # ((suspect_threshold+1)*probe_interval + hysteresis) or a healthy
    # flap can expire the lease mid-hysteresis.  fence_wait_slack_ms
    # pads the wait for an UNREACHABLE zombie's lease to expire before
    # its replacement is installed.
    "ratelimiter.orchestrator.fence_lease_ttl_ms": "0",
    "ratelimiter.orchestrator.fence_wait_slack_ms": "100",
    # Control-plane RPC port (replication/control.py; 0 = off).  Exposes
    # PROBE / FENCE / LEASE / RESTORE over length-prefixed JSON so a
    # REMOTE orchestrator (or an operator's script) can drive this
    # process's fence/lease authority — the cross-host topology's
    # per-node surface.  Binds ratelimiter.control.host (default
    # loopback; set to a mesh-reachable address in a real deployment).
    "ratelimiter.control.port": "0",
    "ratelimiter.control.host": "127.0.0.1",
    # Adaptive policy control plane (control/, ARCHITECTURE §15): OFF by
    # default.  When enabled, a tick-driven AIMD controller adjusts each
    # tenant's effective rate between an operator floor
    # (floor_fraction * the registered ceiling) and the ceiling —
    # additive raises while the tenant's denied+shed share of its
    # observed load stays under target_excess, multiplicative cuts
    # (decrease_factor) on overload — actuated as live set_policy row
    # updates stamped with a monotonic policy generation.
    # global_cap_per_s adds the hierarchical aggregate cap (0 = off):
    # when fleet observed load exceeds it, every tenant's effective
    # rate is scaled by cap/admitted.  Operators pin lids out of the
    # loop via POST /actuator/policies/<lid>/pin.
    "ratelimiter.control.enabled": "false",
    "ratelimiter.control.interval_ms": "1000",
    "ratelimiter.control.window_ms": "2000",
    "ratelimiter.control.target_excess": "0.5",
    "ratelimiter.control.increase_fraction": "0.1",
    "ratelimiter.control.decrease_factor": "0.5",
    "ratelimiter.control.floor_fraction": "0.1",
    "ratelimiter.control.global_cap_per_s": "0",
    # Telemetry staleness bound for the controller (ms; 0 = off): when
    # the plane's worst reporter staleness exceeds it, the controller
    # FREEZES raises (stale signals must never justify giving a tenant
    # more) while cuts stay allowed; each frozen tick emits a coalesced
    # ``control.signals_stale`` flight event.
    "ratelimiter.control.staleness_bound_ms": "0",
    # Fleet-true control plane (control/fleet.py, ARCHITECTURE §15):
    # OFF by default.  When enabled, the adaptive controller runs over
    # a FleetControlPlane instead of the local storage: observations
    # are the SUMMED UsageSignals of every peer (the global cap sees
    # fleet load), and actuations broadcast generation-stamped
    # set_policy rows to every peer — but only while this process
    # HOLDS the cell's controller lease (a majority of peer seats at
    # its fence epoch, renewed within ttl_ms on its own clock; losing
    # either self-demotes and refuses to actuate).  node is this
    # controller's identity (empty -> ctrl-<pid>); peers is a comma-
    # separated host:port list of member control ports (empty -> this
    # process's own ratelimiter.control.port, the single-node cell);
    # interval_ms is the election/renewal cadence.
    "ratelimiter.control.fleet.enabled": "false",
    "ratelimiter.control.fleet.node": "",
    "ratelimiter.control.fleet.peers": "",
    "ratelimiter.control.fleet.ttl_ms": "3000",
    "ratelimiter.control.fleet.interval_ms": "500",
    # Concurrency slots (leases as slots, ARCHITECTURE §15): bound every
    # tenant's aggregate outstanding lease budget to this many permits
    # (0 = unbounded).  Per-lid overrides via
    # LeaseManager.set_concurrency_cap.
    "ratelimiter.control.max_concurrent": "0",
    # Policy-table capacity (rows).  The table grows implicitly when
    # full, but a mid-traffic grow recompiles the device step for the
    # new table shape (LimiterTable._grow warns) — pre-size to the
    # expected tenant count.
    "ratelimiter.table.capacity": "64",
    # Fleet autopilot (fleet/, ARCHITECTURE §16): OFF by default.  When
    # enabled, this process runs a NodeManager that probes its managed
    # hostproc nodes every probe_interval_ms (one muxed probe_all RPC
    # per NODE), declares a node FAILED after probe_fail_threshold
    # consecutive probe misses or a process exit, and surfaces the
    # fleet on GET /actuator/fleet (FAILED/DRAINING nodes fold the
    # health state machine to DEGRADED).  boot_timeout_s bounds a
    # spawned node's wait for its ready line; reseed_deadline_s bounds
    # every automated cross-host re-seed job (a job past it is failed
    # loudly instead of wedging the cell at N+0); node_version is the
    # deploy version tag replacement nodes are spawned at — a rolling
    # upgrade bumps it, then drains nodes.
    "ratelimiter.fleet.enabled": "false",
    "ratelimiter.fleet.probe_interval_ms": "500",
    "ratelimiter.fleet.probe_fail_threshold": "3",
    "ratelimiter.fleet.boot_timeout_s": "180",
    "ratelimiter.fleet.reseed_deadline_s": "120",
    "ratelimiter.fleet.node_version": "v0",
}

# Typed keys: anything listed here is parse-checked at construction.
_INT_KEYS = (
    "server.port", "storage.num_slots", "batcher.max_batch",
    "batcher.max_inflight", "storage.retry.max_retries",
    "replication.listen_port", "ratelimiter.overload.max_pending",
    "breaker.failure_threshold", "breaker.half_open_probes",
    "ratelimiter.degraded.max_keys", "ratelimiter.sidecar.port",
    "ratelimiter.sidecar.max_frame_bytes",
    "ratelimiter.sidecar.max_key_bytes",
    "ratelimiter.sidecar.max_pipeline",
    "ratelimiter.sidecar.max_connections",
    "ratelimiter.obs.trace_sample",
    "ratelimiter.obs.flight_capacity",
    "ratelimiter.usage.max_tenants",
    "ratelimiter.telemetry.max_clients",
    "ratelimiter.obs.lineage_capacity",
    "ratelimiter.orchestrator.suspect_threshold",
    "ratelimiter.orchestrator.promote_retries",
    "ratelimiter.control.port",
    "ratelimiter.cache.hybrid.max_keys",
    "ratelimiter.cache.hybrid.unconfirmed_cap",
    "ratelimiter.lease.default_budget",
    "ratelimiter.lease.max_budget",
    "ratelimiter.lease.max_leases",
    "ratelimiter.lease.max_bulk_budget",
    "ratelimiter.edge.bulk_budget",
    "ratelimiter.edge.slice_budget",
    "ratelimiter.control.window_ms",
    "ratelimiter.control.max_concurrent",
    "ratelimiter.table.capacity",
    "ratelimiter.fleet.probe_fail_threshold",
)
_FLOAT_KEYS = (
    "batcher.max_delay_ms", "chaos.failure_rate", "chaos.latency_ms",
    "storage.retry.delay_ms", "replication.interval_ms",
    "ratelimiter.overload.deadline_ms",
    "ratelimiter.overload.shed_health_window_ms", "breaker.open_ms",
    "ratelimiter.sidecar.idle_timeout_ms",
    "ratelimiter.sidecar.read_timeout_ms",
    "ratelimiter.sidecar.resolve_timeout_ms",
    "ratelimiter.sidecar.drain_timeout_ms",
    "ratelimiter.obs.slo_ms",
    "replication.ack_timeout_ms",
    "ratelimiter.orchestrator.probe_interval_ms",
    "ratelimiter.orchestrator.hysteresis_ms",
    "ratelimiter.orchestrator.promote_backoff_ms",
    "ratelimiter.orchestrator.fence_lease_ttl_ms",
    "ratelimiter.orchestrator.fence_wait_slack_ms",
    "ratelimiter.microbatch.flush_floor_ms",
    "ratelimiter.cache.hybrid.ttl_ms",
    "ratelimiter.cache.hybrid.guard_ms",
    "ratelimiter.lease.ttl_ms",
    "ratelimiter.lease.deny_ttl_ms",
    "ratelimiter.edge.flush_ms",
    "ratelimiter.control.interval_ms",
    "ratelimiter.control.target_excess",
    "ratelimiter.control.increase_fraction",
    "ratelimiter.control.decrease_factor",
    "ratelimiter.control.floor_fraction",
    "ratelimiter.control.global_cap_per_s",
    "ratelimiter.control.staleness_bound_ms",
    "ratelimiter.control.fleet.ttl_ms",
    "ratelimiter.control.fleet.interval_ms",
    "ratelimiter.fleet.probe_interval_ms",
    "ratelimiter.fleet.boot_timeout_s",
    "ratelimiter.fleet.reseed_deadline_s",
)
_BOOL_KEYS = (
    "ratelimiter.fail_open", "warmup.enabled", "replication.enabled",
    "link.probe.enabled", "breaker.enabled", "ratelimiter.degraded.enabled",
    "ratelimiter.sidecar.enabled", "ratelimiter.orchestrator.enabled",
    "ratelimiter.orchestrator.reseed",
    "ratelimiter.microbatch.adaptive_flush",
    "ratelimiter.cache.hybrid.enabled",
    "ratelimiter.lease.enabled",
    "ratelimiter.edge.enabled",
    "ratelimiter.control.enabled",
    "ratelimiter.control.fleet.enabled",
    "ratelimiter.fleet.enabled",
)
_BOOL_TOKENS = ("1", "true", "yes", "on", "0", "false", "no", "off")

# RATELIMITER_* env vars read directly by engine/ops modules, not through
# this properties layer — the unknown-env scan must not warn about them.
_ENV_DIRECT = frozenset({
    "RATELIMITER_SORT_UNIQUES", "RATELIMITER_RATE_PROBE",
    "RATELIMITER_PALLAS", "RATELIMITER_PALLAS_INTERPRET",
    "RATELIMITER_BLOCK_SCATTER", "RATELIMITER_BLOCK_SCATTER_INTERPRET",
})


def _env_key(key: str) -> str:
    return "RATELIMITER_" + key.replace(".", "_").replace("-", "_").upper()


def _parses(key: str, value: str) -> bool:
    try:
        if key in _INT_KEYS:
            int(value)
        elif key in _FLOAT_KEYS:
            float(value)
        elif key in _BOOL_KEYS:
            return value.strip().lower() in _BOOL_TOKENS
        return True
    except (TypeError, ValueError):
        return False


class AppProperties:
    def __init__(self, values: Optional[Dict[str, str]] = None):
        self._values = dict(DEFAULTS)
        if values:
            for key in values:
                if key not in DEFAULTS:
                    log.warning("unknown property key %r (kept, but no "
                                "component reads it — typo?)", key)
            self._values.update(values)
        self._validate()

    def _validate(self) -> None:
        """Replace malformed typed values with their defaults, loudly."""
        for key, value in list(self._values.items()):
            if key in DEFAULTS and not _parses(key, value):
                log.warning(
                    "malformed value %r for property %r; using default %r",
                    value, key, DEFAULTS[key])
                self._values[key] = DEFAULTS[key]

    @classmethod
    def load(cls, path: Optional[str] = None) -> "AppProperties":
        values: Dict[str, str] = {}
        if path and os.path.exists(path):
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line or line.startswith(("#", "!")):
                        continue
                    if "=" in line:
                        k, v = line.split("=", 1)
                        values[k.strip()] = v.strip()
        known_env = {_env_key(k): k for k in DEFAULTS}
        for env_name, env_value in os.environ.items():
            if not env_name.startswith("RATELIMITER_"):
                continue
            key = known_env.get(env_name)
            if key is not None:
                values[key] = env_value
            elif env_name not in _ENV_DIRECT:
                log.warning("unknown env override %s (no property maps to "
                            "it — typo?)", env_name)
        return cls(values)

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._values.get(key, default)

    def get_int(self, key: str, default: int = 0) -> int:
        value = self._values.get(key)
        if value is None:
            return default
        try:
            return int(value)
        except (TypeError, ValueError):
            log.warning("malformed int %r for property %r; using %r",
                        value, key, default)
            return default

    def get_float(self, key: str, default: float = 0.0) -> float:
        value = self._values.get(key)
        if value is None:
            return default
        try:
            return float(value)
        except (TypeError, ValueError):
            log.warning("malformed float %r for property %r; using %r",
                        value, key, default)
            return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        value = self._values.get(key)
        if value is None:
            return default
        return value.strip().lower() in ("1", "true", "yes", "on")
