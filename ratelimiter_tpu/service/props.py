"""Application properties (C13 parity).

The reference configures itself via Spring ``application.properties``
(redis.host/redis.port/server.port, application.properties:1-15) with env
overrides from docker-compose.  Here: the same ``key=value`` file format,
env-var overrides (``RATELIMITER_<KEY with . -> _ uppercased>``), and typed
accessors with defaults.
"""

from __future__ import annotations

import os
from typing import Dict, Optional


DEFAULTS = {
    "server.port": "8080",
    # "tpu" (device-batched) or "memory" (host dict) — the storage plugin.
    "storage.backend": "tpu",
    "storage.num_slots": str(1 << 20),
    "batcher.max_batch": "8192",
    "batcher.max_delay_ms": "0.5",
    # Device batches allowed in flight at once (dispatched, fetch pending).
    # >1 overlaps fetch latency with the next dispatches.
    "batcher.max_inflight": "4",
    # Fail-open on storage failure: documented in the reference's
    # architecture notes but never implemented there (SURVEY.md §5.3);
    # implemented here and ON by default as documented.
    "ratelimiter.fail_open": "true",
    # Shard the slot array over all visible devices when > 1.
    "parallel.shard": "auto",
    # Compile hot dispatch shapes at boot (moves 40-90s/shape jit stalls
    # out of the first requests).
    "warmup.enabled": "true",
    # Persistent XLA compile-cache dir; empty -> ~/.cache/ratelimiter_tpu/jax.
    "jax.cache.dir": "",
    # Chaos drill: inject StorageException on this fraction of storage ops
    # (0 = off) and/or add latency to every op (fault-tolerance rehearsal).
    "chaos.failure_rate": "0",
    "chaos.latency_ms": "0",
    # Console logging (application.properties:9-11 analog): level for the
    # ratelimiter_tpu logger hierarchy + the console pattern (single
    # source of truth for the default lives in utils/logging.py).
    "logging.level": "INFO",
    "logging.pattern": "",  # empty -> utils/logging.DEFAULT_PATTERN
    # Per-op storage retry (RedisRateLimitStorage.java:155-178 analog):
    # attempts with linear backoff delay*attempt, then StorageException
    # escalates to fail-open. 0 retries disables the wrapper.
    "storage.retry.max_retries": "3",
    "storage.retry.delay_ms": "10",
    # Live state replication (replication/): OFF by default.  A primary
    # journals dirty slots and ships epoch frames to replication.target
    # (host:port of a standby's listener); a standby listens on
    # replication.listen_port, applies frames to its shadow engine, and
    # promotes via POST /actuator/replication/promote on failover.
    "replication.enabled": "false",
    "replication.role": "primary",
    "replication.target": "",
    "replication.listen_port": "7401",
    "replication.interval_ms": "200",
}


def _env_key(key: str) -> str:
    return "RATELIMITER_" + key.replace(".", "_").replace("-", "_").upper()


class AppProperties:
    def __init__(self, values: Optional[Dict[str, str]] = None):
        self._values = dict(DEFAULTS)
        if values:
            self._values.update(values)

    @classmethod
    def load(cls, path: Optional[str] = None) -> "AppProperties":
        values: Dict[str, str] = {}
        if path and os.path.exists(path):
            with open(path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line or line.startswith(("#", "!")):
                        continue
                    if "=" in line:
                        k, v = line.split("=", 1)
                        values[k.strip()] = v.strip()
        props = cls(values)
        for key in list(props._values):
            env = os.environ.get(_env_key(key))
            if env is not None:
                props._values[key] = env
        return props

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._values.get(key, default)

    def get_int(self, key: str, default: int = 0) -> int:
        value = self._values.get(key)
        return int(value) if value is not None else default

    def get_float(self, key: str, default: float = 0.0) -> float:
        value = self._values.get(key)
        return float(value) if value is not None else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        value = self._values.get(key)
        if value is None:
            return default
        return value.strip().lower() in ("1", "true", "yes", "on")
