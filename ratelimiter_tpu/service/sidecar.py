"""Decision sidecar: a binary TCP protocol replacing the Redis round-trip.

The reference's distributed story is "every app instance speaks RESP to one
Redis".  This framework's equivalent is the sidecar: non-Python services
(e.g. a JVM API gateway) connect over TCP and stream decision requests; the
server funnels every connection into the shared micro-batcher, so requests
from *all* clients coalesce into the same device batches — the many-clients
/one-authority topology of Redis, with the TPU engine as the authority.

Wire format v2 (little-endian), deliberately RESP-simple so any language
can speak it in ~30 lines:

  request  :=  u32 len | u8 op | u32 a | u32 b | key bytes
  response :=  u32 len | u8 status | u8 allowed | i64 remaining

  op: 1 = TRY_ACQUIRE   (a=limiter id, b=permits; allowed + remaining hint)
      2 = AVAILABLE     (a=limiter id; remaining permits; allowed unused)
      3 = RESET         (a=limiter id; admin)
      4 = PING          (health; allowed=1 when storage is up)
      5 = HELLO         (handshake: a=client protocol version, b=flags;
                         response: allowed=negotiated version,
                         remaining=server max frame bytes)
      6 = LEASE         (v3: a=limiter id, b=requested budget)
      7 = RENEW         (v3: a=limiter id, b=used | requested << 16)
      8 = RELEASE       (v3: a=limiter id, b=used)
      9 = TELEMETRY     (v4: key bytes carry a client burn report;
                         RESPONSE-LESS — see below)
     10 = BATCH         (v5: columnar decision batch — see below)
     11 = BULK_RENEW    (v6: columnar lease-portfolio renewal — see below)
  status: 0 = OK
          1 = ERROR          (generic; remaining carries an errno — the only
                              error status v1 clients ever see)
          2 = SHED           (admission control refused the frame; remaining
                              carries a retry-after hint in ms)
          3 = SHUTTING_DOWN  (server is draining; reconnect elsewhere)
          4 = BAD_FRAME      (malformed frame, answered in-protocol;
                              remaining carries an errno)
          5 = LEASE_REVOKED  (v3 only: the lease predates the current fence
                              epoch or the backend is fenced; re-grant)

**Versioning.**  A v2+ client's first frame is HELLO; the server answers
with the negotiated version (``min(client, server)``) and its frame-size
cap, and from then on may use the typed statuses of that version.  A v1
client never sends HELLO — the server serves it unchanged, downgrading
every v2-only status to the generic ``ERROR`` (status 1) with a matching
errno, so old clients keep their "status != 0 means error" contract and
never desync.  The v3 LEASE/RENEW/RELEASE ops exist only on connections
negotiated at v3: a v2 connection sending them gets ``BAD_FRAME``
(unknown op) and NEVER sees a lease status — v2 ingress is served
byte-identically to a v2 server.

**Token leases (v3; leases/).**  LEASE charges a bounded per-key permit
budget atomically against the device counters and the client burns it
locally — one wire frame per budget instead of one per decision (the
10-100x ingress collapse).  The lease response packs three fields into
``remaining``: ``granted | ttl_ms << 16 | fence_epoch << 40`` (granted
<= 65535, ttl < 2^24 ms, epoch < 2^23).  RENEW reports burns and
re-charges in one frame; ``LEASE_REVOKED`` forces a re-grant after a
failover (the fence epoch advanced — leases/manager.py).  Budgets are
capped at 65535 by the wire format.

**Wire v4: trace ids + client telemetry (observability/telemetry.py).**
On a connection negotiated at v4, every request frame EXCEPT HELLO
carries a 64-bit trace id between the header and the key bytes::

  v4 request := u32 len | u8 op | u32 a | u32 b | u64 trace_id | key

``trace_id == 0`` means untraced (the server mints one when lineage
sampling is armed); a nonzero id is force-sampled — the caller asked
for this trace — and threads client -> sidecar -> batcher -> shard ->
resolve through the lineage ring.  v<=3 clients never send the extra
field and are served byte-identically to a v3 server.  The TELEMETRY
op (9) ships a ``LeaseClient``'s accumulated burn report; it is
**response-less** by design (drop-don't-block: telemetry must never
add a wire round trip), so clients pipeline it in front of RENEW
frames for free or fire it on a cadence without reading anything back.
The server folds reports into the fleet telemetry plane
(``storage.telemetry``); a report during drain or on a plane-less
server is silently dropped (still no response — op 9 never answers).

**Wire v5: columnar batch frames (op 10).**  One BATCH frame carries a
whole burst as packed columns instead of N per-request frames::

  v5 batch  := u32 len | u8 op=10 | u32 lid | u32 rows | u64 trace_id
             | u32 klen | key bytes[klen]          (interned UTF-8 buffer)
             | u32 offsets[rows + 1]               (key i = bytes[offsets[i]
                                                    : offsets[i+1]])
             | u8 flags                            (bit 0: permits column)
             | u32 permits[rows]                   (iff flags & 1; else all 1)
  response  := u32 len=10+ceil(rows/8) | u8 status=OK | u8 1 | i64 rows
             | allow bits (np.packbits order: row r = bit 7-r%8 of byte r//8)

The key column is EXACTLY the native index's input
(``rl_index_assign_bytes``: packed UTF-8 + offsets), so the server
assigns slots straight off the wire buffer and submits ONE
batcher block (``submit_block``) — zero per-request Python objects
between socket and device.  Column validation is answered in-protocol:
truncated columns are ``BAD_FRAME``/``ERR_SHORT_FRAME``, trailing-length
or offset violations (offsets[0] != 0, decreasing, offsets[rows] !=
klen) are ``BAD_FRAME``/``ERR_BAD_COLUMN``, ``rows`` above the pipeline
cap is ``BAD_FRAME``/``ERR_FRAME_TOO_LONG``, and a per-key length over
``max_key_bytes`` is ``BAD_FRAME``/``ERR_KEY_TOO_LONG`` — the length
prefix keeps the stream in sync through all of them.  Error statuses
keep the plain 14-byte response shape (the length field disambiguates).
The op exists only on connections negotiated at v5 (HELLO, exactly like
v2->v4): a v<=4 connection sending op 10 gets the same unknown-op
``BAD_FRAME`` a v4 server would give, and v<=4 ingress is served
byte-identically to a v4 server.

**Wire v6: wide lease budgets + bulk portfolio renewal (edge/).**
Bulk leases (one aggregate budget subleased to many clients by an edge
aggregator) routinely exceed the v3 packing's 65535 cap, so a v6
connection widens every lease budget field:

- v6 lease REQUESTS carry a u32 ``ext`` field between the (v4) trace
  id and the key bytes — LEASE: ``b`` = requested (full u32), ``ext``
  bit 0 = bulk flag; RENEW: ``b`` = used (u32), ``ext`` = requested
  (u32); RELEASE: ``b`` = used (u32), ``ext`` reserved;
- v6 OK lease RESPONSES append a trailing u64 full-width grant after
  the standard 14 bytes (the packed ``remaining`` keeps the clamped v3
  fields; the length field disambiguates, exactly like BATCH).

The BULK_RENEW op (11, v6 only) renews an aggregator's whole portfolio
for one lid in ONE columnar frame::

  v6 bulk  := u32 len | u8 op=11 | u32 lid | u32 rows | u64 trace_id
            | u32 klen | key bytes[klen] | u32 offsets[rows + 1]
            | u64 used[rows] | u64 requested[rows] | u32 epochs[rows]
  response := u32 len | u8 status=OK | u8 1 | i64 rows
            | u64 granted[rows] | u32 ttl_ms[rows] | u32 epoch[rows]
            | u8 flags[rows]            (bit 0: REVOKED — re-grant)

Each row is the exact equivalent of one RENEW frame (same manager
call, same revocation and over-admission accounting).  ``epochs[i]``
names the lease instance row i reports for (0xFFFFFFFF = no check):
burns flushed for a revoked bulk lease that raced a successor grant on
the same key are counted into ``over_admission`` instead of folding
into the successor's accounting.  v<=5
connections never see any of this and are served byte-identically to a
v5 server (op 11 below v6 is the same unknown-op ``BAD_FRAME`` a v5
server would give).  When the attached lease backend is
session-capable (an ``edge.EdgeAggregator`` fronting subleases), each
connection gets its own session — one client's subleases never alias
another's.

**Ingress hardening.**  Every byte on the wire is untrusted:

- frames are validated (max frame length, max key length, UTF-8 key,
  short-frame and unknown-op checks) and violations are answered with a
  typed ``BAD_FRAME`` status *in protocol* — the length prefix keeps the
  stream in sync, so one bad frame never kills the connection.  A frame
  DECLARING more than ``max_frame_bytes`` is rejected immediately and its
  payload is discarded as it streams (never buffered), so a hostile
  length prefix cannot balloon memory;
- per-connection deadlines: ``idle_timeout_ms`` between requests and the
  stricter ``read_timeout_ms`` once a frame has started (slowloris — a
  half-written frame must not pin a handler thread), enforced by socket
  timeouts on both reads and writes (a client that stops reading its
  responses hits the same bound);
- per-connection pipeline cap: at most ``max_pipeline`` decision frames
  in flight per connection; excess frames are shed with the typed
  ``SHED`` status + retry-after hint (mirroring the micro-batcher's
  ``queue_full`` admission control, which the sidecar also relays);
- a global ``max_connections`` bound (excess accepts are closed);
- graceful drain: ``stop()`` first marks the server draining — in-flight
  frames resolve, new decision frames answer ``SHUTTING_DOWN`` — and
  only then tears connections down.  A client that disconnects mid-burst
  never leaks a batcher future: still-queued frames are withdrawn from
  the batcher (``MicroBatcher.forget``) and dispatched ones are consumed
  via done-callbacks.

Requests may be pipelined: a client can write N frames before reading N
responses (the provided ``SidecarClient.acquire_batch`` does exactly this),
which amortizes syscalls the way Redis pipelining does.  The server honors
the pipelining on the decision path: every TRY_ACQUIRE frame of a read
burst is SUBMITTED to the micro-batcher before any is resolved
(``TpuBatchedStorage.acquire_async``), so a 64-deep pipeline coalesces
into one device flush instead of paying 64 sequential batcher round
trips — responses still return in request order.

Limiters are registered server-side by name -> (algo, config); clients
address them by the integer id returned at registration (distributed via
config, exactly like the reference's named Spring beans).
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from concurrent.futures import CancelledError
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.engine.errors import OverloadedError, ShutdownError
from ratelimiter_tpu.storage.tpu import TpuBatchedStorage
from ratelimiter_tpu.utils.logging import get_logger

log = get_logger("service.sidecar")

OP_TRY_ACQUIRE = 1
OP_AVAILABLE = 2
OP_RESET = 3
OP_PING = 4
OP_HELLO = 5
OP_LEASE = 6
OP_RENEW = 7
OP_RELEASE = 8
OP_TELEMETRY = 9
OP_BATCH = 10
OP_BULK_RENEW = 11

PROTOCOL_VERSION = 6

ST_OK = 0
ST_ERROR = 1
ST_SHED = 2
ST_SHUTTING_DOWN = 3
ST_BAD_FRAME = 4
ST_LEASE_REVOKED = 5

ERR_UNKNOWN_OP = 1
ERR_UNKNOWN_LIMITER = 2
ERR_INTERNAL = 3
ERR_SHORT_FRAME = 4
ERR_KEY_TOO_LONG = 5
ERR_FRAME_TOO_LONG = 6
ERR_OVERLOADED = 7
ERR_SHUTTING_DOWN = 8
ERR_BAD_KEY = 9
ERR_LEASE_DISABLED = 10
ERR_LEASE_REVOKED = 11
ERR_BAD_COLUMN = 12

# Lease-response field packing (remaining i64):
#   granted | ttl_ms << 16 | fence_epoch << 40
_LEASE_GRANT_MAX = 0xFFFF
_LEASE_TTL_MAX = 0xFFFFFF
_LEASE_EPOCH_MAX = 0x7FFFFF
# v6: budgets ride the wire full-width (bulk budgets are aggregate and
# routinely exceed the old 65535 cap).
_LEASE_GRANT_MAX_V6 = 0xFFFFFFFF
# v6 bulk-renew response columns, per row: u64 granted + u32 ttl_ms
# + u32 epoch + u8 flags (bit 0: REVOKED — re-grant at the new epoch).
_BULK_ROW_BYTES = 8 + 4 + 4 + 1
# Bulk-renew request epoch column sentinel: "no lease-instance check"
# (a plain client that does not track instance epochs).
_EPOCH_ANY = 0xFFFFFFFF


def _pack_lease(granted: int, ttl_ms: int, epoch: int) -> int:
    return (min(int(granted), _LEASE_GRANT_MAX)
            | min(max(int(ttl_ms), 0), _LEASE_TTL_MAX) << 16
            | min(max(int(epoch), 0), _LEASE_EPOCH_MAX) << 40)


def _unpack_lease(remaining: int):
    return (remaining & 0xFFFF, (remaining >> 16) & 0xFFFFFF,
            (remaining >> 40) & 0x7FFFFF)

_REQ_BODY = struct.Struct("<BII")    # op, a, b (after the u32 len)
_REQ_BODY4 = struct.Struct("<BIIQ")  # v4: op, a, b, trace_id
_RESP = struct.Struct("<IBBq")       # len, status, allowed, remaining

# v2-only statuses carry these errnos when downgraded for a v1 client.
_V1_ERRNO = {ST_SHED: ERR_OVERLOADED, ST_SHUTTING_DOWN: ERR_SHUTTING_DOWN}


def _mk_resp(status: int, allowed: int, remaining: int) -> bytes:
    return _RESP.pack(_RESP.size - 4, status, allowed, remaining)


def _consume_future(fut) -> None:
    """Retrieve an abandoned future's outcome so nothing stays orphaned
    (attached as a done-callback; fires immediately if already done)."""
    try:
        if not fut.cancelled():
            fut.exception()
    except (CancelledError, Exception):  # noqa: BLE001 — consumption only
        pass


class _ConnState:
    """Per-connection protocol state (owned by one handler thread)."""

    __slots__ = ("version", "buf", "skip", "pending", "leases")

    def __init__(self):
        self.version = 1       # until a HELLO negotiates up
        self.buf = b""         # unparsed wire bytes
        self.skip = 0          # bytes of an oversized frame left to discard
        self.pending: List = []  # burst: response bytes | futures | batches
        # Per-connection lease identity: when the attached lease backend
        # is session-capable (an EdgeAggregator), each connection gets
        # its own sublease bookkeeping (lazily created on first lease
        # op).  A plain LeaseManager is shared across connections.
        self.leases = None


class _BatchPending:
    """One submitted v5 BATCH frame awaiting resolution: either a single
    block future (columnar storage path — resolves to array slices) or a
    per-key future list (decoded-string fallback)."""

    __slots__ = ("fut", "futs", "rows")

    def __init__(self, fut_or_futs, rows: int):
        if isinstance(fut_or_futs, list):
            self.fut, self.futs = None, fut_or_futs
        else:
            self.fut, self.futs = fut_or_futs, None
        self.rows = int(rows)

    def futures(self) -> list:
        return self.futs if self.fut is None else [self.fut]


class SidecarServer:
    """Threaded TCP server over a TpuBatchedStorage.

    All hardening bounds accept 0/None to disable (the library default is
    hardened; ``service/props.py`` exposes them as ``ratelimiter.sidecar.*``).
    """

    def __init__(self, storage: TpuBatchedStorage, host: str = "0.0.0.0",
                 port: int = 0, *,
                 leases=None,
                 meter_registry=None,
                 max_frame_bytes: int = 4096,
                 max_key_bytes: int = 1024,
                 max_pipeline: int = 1024,
                 max_connections: int = 1024,
                 idle_timeout_ms: float = 60_000.0,
                 read_timeout_ms: float = 5_000.0,
                 resolve_timeout_ms: float = 30_000.0,
                 drain_timeout_ms: float = 1_000.0):
        self.storage = storage
        # Token-lease manager (leases/manager.py) behind the v3 LEASE/
        # RENEW/RELEASE ops; None answers them ERR_LEASE_DISABLED.
        self._leases = leases
        self.max_frame_bytes = int(max_frame_bytes or 0)
        self.max_key_bytes = int(max_key_bytes or 0)
        self.max_pipeline = int(max_pipeline or 0)
        self.max_connections = int(max_connections or 0)
        self.idle_timeout_s = float(idle_timeout_ms or 0.0) / 1000.0
        self.read_timeout_s = float(read_timeout_ms or 0.0) / 1000.0
        self.resolve_timeout_s = float(resolve_timeout_ms or 0.0) / 1000.0
        self.drain_timeout_s = float(drain_timeout_ms or 0.0) / 1000.0
        self._limiters: Dict[int, Tuple[str, RateLimitConfig]] = {}
        self._conns: set = set()
        self._conn_lock = threading.Lock()
        self._stopped = False
        self._draining = False
        self._inflight = 0           # submitted-unresolved decision futures
        # Plain counters (always on — drills read them without a registry).
        self.malformed_total = 0
        self.idle_closed_total = 0
        self.pipeline_shed_total = 0
        self.drained_total = 0       # frames answered SHUTTING_DOWN
        self.refused_total = 0       # accepts over max_connections
        self.futures_abandoned = 0   # futures a dead client left behind
        self.telemetry_frames_total = 0   # TELEMETRY frames received
        self.telemetry_dropped_total = 0  # dropped (drain/no plane/bad)
        self.last_shed_s = 0.0       # monotonic stamp of the last shed
        reg = meter_registry
        self._m_conns = (reg.gauge(
            "ratelimiter.sidecar.connections",
            "Open sidecar connections") if reg is not None else None)
        self._m_malformed = (reg.counter(
            "ratelimiter.sidecar.malformed",
            "Malformed sidecar frames answered with BAD_FRAME")
            if reg is not None else None)
        self._m_idle = (reg.counter(
            "ratelimiter.sidecar.idle_closed",
            "Sidecar connections closed by idle/read deadline")
            if reg is not None else None)
        self._m_shed = (reg.counter(
            "ratelimiter.sidecar.pipeline_shed",
            "Sidecar frames shed by the per-connection pipeline cap")
            if reg is not None else None)
        self._m_drained = (reg.counter(
            "ratelimiter.sidecar.drained",
            "Sidecar frames answered SHUTTING_DOWN during drain")
            if reg is not None else None)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def setup(self):
                self.accepted = False
                with outer._conn_lock:
                    over = (outer.max_connections
                            and len(outer._conns) >= outer.max_connections)
                    if outer._stopped or over:
                        if over and not outer._stopped:
                            outer.refused_total += 1
                        # Refused (limit) or accepted in the shutdown race
                        # window: close now rather than serving.
                        try:
                            self.request.shutdown(socket.SHUT_RDWR)
                        except OSError:
                            pass
                        self.request.close()
                        return
                    outer._conns.add(self.request)
                    self.accepted = True
                    if outer._m_conns is not None:
                        outer._m_conns.set(len(outer._conns))

            def finish(self):
                with outer._conn_lock:
                    outer._conns.discard(self.request)
                    if outer._m_conns is not None:
                        outer._m_conns.set(len(outer._conns))

            def handle(self):
                if self.accepted:
                    outer._serve_conn(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="sidecar", daemon=True)

    @classmethod
    def from_props(cls, storage, props, meter_registry=None,
                   host: str = "0.0.0.0") -> "SidecarServer":
        """Build from ``ratelimiter.sidecar.*`` properties."""
        g_int, g_float = props.get_int, props.get_float
        return cls(
            storage, host=host,
            port=g_int("ratelimiter.sidecar.port", 7400),
            meter_registry=meter_registry,
            max_frame_bytes=g_int("ratelimiter.sidecar.max_frame_bytes", 4096),
            max_key_bytes=g_int("ratelimiter.sidecar.max_key_bytes", 1024),
            max_pipeline=g_int("ratelimiter.sidecar.max_pipeline", 1024),
            max_connections=g_int("ratelimiter.sidecar.max_connections", 1024),
            idle_timeout_ms=g_float(
                "ratelimiter.sidecar.idle_timeout_ms", 60_000.0),
            read_timeout_ms=g_float(
                "ratelimiter.sidecar.read_timeout_ms", 5_000.0),
            resolve_timeout_ms=g_float(
                "ratelimiter.sidecar.resolve_timeout_ms", 30_000.0),
            drain_timeout_ms=g_float(
                "ratelimiter.sidecar.drain_timeout_ms", 1_000.0),
        )

    # -- limiter registry -----------------------------------------------------
    def register(self, algo: str, config: RateLimitConfig) -> int:
        lid = self.storage.register_limiter(algo, config)
        self._limiters[lid] = (algo, config)
        return lid

    def expose(self, lid: int, algo: str, config: RateLimitConfig) -> int:
        """Expose an ALREADY-registered limiter (e.g. the HTTP tier's) to
        sidecar clients under its existing id — both front doors then
        decide against the same device counters."""
        self._limiters[int(lid)] = (algo, config)
        return int(lid)

    def attach_leases(self, manager) -> "SidecarServer":
        """Attach a LeaseManager serving the v3 LEASE/RENEW/RELEASE ops
        (wiring calls this when ``ratelimiter.lease.enabled``)."""
        self._leases = manager
        return self

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "SidecarServer":
        self._thread.start()
        return self

    def inflight(self) -> int:
        """Submitted-unresolved decision frames across all connections."""
        with self._conn_lock:
            return self._inflight

    def connections(self) -> int:
        with self._conn_lock:
            return len(self._conns)

    def stop(self, drain_timeout_s: float | None = None) -> None:
        """Graceful drain, then hard stop.

        Drain phase: new decision frames are answered ``SHUTTING_DOWN``
        while every already-submitted frame resolves normally — bounded
        by ``drain_timeout_s`` (default from the constructor).  Hard
        phase: the listener stops and every accepted connection is shut
        down, so no zombie handler thread answers clients from a closed
        storage."""
        self._draining = True
        budget = (self.drain_timeout_s if drain_timeout_s is None
                  else float(drain_timeout_s))
        deadline = time.monotonic() + max(budget, 0.0)
        while time.monotonic() < deadline:
            if self.inflight() == 0:
                break
            time.sleep(0.005)
        self._server.shutdown()
        self._server.server_close()
        with self._conn_lock:
            self._stopped = True
            conns = list(self._conns)
            self._conns.clear()
            if self._m_conns is not None:
                self._m_conns.set(0)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    # -- accounting helpers ---------------------------------------------------
    def _count_malformed(self) -> None:
        self.malformed_total += 1
        if self._m_malformed is not None:
            self._m_malformed.increment()

    def _count_idle_closed(self) -> None:
        self.idle_closed_total += 1
        if self._m_idle is not None:
            self._m_idle.increment()

    def _count_pipeline_shed(self) -> None:
        self.pipeline_shed_total += 1
        self.last_shed_s = time.monotonic()
        if self._m_shed is not None:
            self._m_shed.increment()
        from ratelimiter_tpu.observability import flight_recorder

        flight_recorder().record("overload.shed", coalesce_ms=1000.0,
                                 reason="sidecar_pipeline")

    def _count_drained(self) -> None:
        self.drained_total += 1
        if self._m_drained is not None:
            self._m_drained.increment()

    def _track_submit(self, n: int) -> None:
        with self._conn_lock:
            self._inflight += n

    # -- connection loop ------------------------------------------------------
    def _serve_conn(self, sock: socket.socket) -> None:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        st = _ConnState()
        try:
            self._conn_loop(sock, st)
        finally:
            self._abandon_pending(st)

    def _conn_loop(self, sock: socket.socket, st: _ConnState) -> None:
        while True:
            # Idle deadline between requests; the stricter read deadline
            # once a frame has started (st.buf holds a partial frame, or
            # an oversized frame is still being discarded) — a half
            # frame must not pin this thread (slowloris).
            mid_frame = bool(st.buf) or st.skip > 0
            timeout = self.read_timeout_s if mid_frame else self.idle_timeout_s
            sock.settimeout(timeout if timeout > 0 else None)
            try:
                chunk = sock.recv(65536)
            except socket.timeout:
                self._count_idle_closed()
                return
            except OSError:
                return
            if not chunk:
                return
            if st.skip:
                # Discard an oversized frame's payload as it streams —
                # never buffered, already answered BAD_FRAME.
                n = min(st.skip, len(chunk))
                st.skip -= n
                chunk = chunk[n:]
                if not chunk:
                    continue
            st.buf += chunk
            # Two-phase pipelined burst: submit every decision frame of
            # this read burst (futures), THEN resolve in order — the
            # whole pipeline lands in one micro-batch flush.
            self._parse_burst(st)
            if st.pending:
                out = b"".join(
                    self._finish_frame(p, st) for p in st.pending)
                st.pending = []
                try:
                    sock.sendall(out)
                except (socket.timeout, OSError):
                    return

    def _parse_burst(self, st: _ConnState) -> None:
        while len(st.buf) >= 4:
            (length,) = struct.unpack_from("<I", st.buf)
            if self.max_frame_bytes and length > self.max_frame_bytes:
                # Hostile/corrupt length prefix: answer in-protocol and
                # discard exactly `length` bytes so the stream stays in
                # sync without ever buffering the oversized payload.
                self._count_malformed()
                st.pending.append(self._resp(
                    st, ST_BAD_FRAME, 0, ERR_FRAME_TOO_LONG))
                have = len(st.buf) - 4
                if have >= length:
                    st.buf = st.buf[4 + length:]
                else:
                    st.skip = length - have
                    st.buf = b""
                continue
            if len(st.buf) < 4 + length:
                break
            frame = st.buf[4:4 + length]
            st.buf = st.buf[4 + length:]
            st.pending.append(self._begin_frame(frame, st))

    # -- frame handling -------------------------------------------------------
    def _resp(self, st: _ConnState, status: int, allowed: int,
              remaining: int) -> bytes:
        """Version-aware response: statuses above a connection's
        negotiated version downgrade to the generic ERROR (status 1)
        with a matching errno, so older clients keep their
        status!=0-means-error contract.  (Lease statuses can only arise
        from v3-gated ops, so the v3 downgrade is pure defense.)"""
        if st.version < 3 and status == ST_LEASE_REVOKED:
            status, remaining = ST_ERROR, ERR_LEASE_REVOKED
        if st.version < 2 and status > ST_ERROR:
            if status in _V1_ERRNO:
                remaining = _V1_ERRNO[status]
            status = ST_ERROR
        return _mk_resp(status, allowed, remaining)

    def _begin_frame(self, frame: bytes, st: _ConnState):
        """Phase 1 of a pipelined burst: TRY_ACQUIRE frames are submitted
        to the micro-batcher and return their FUTURE; everything else
        (and every validation failure) resolves immediately to bytes.
        TELEMETRY frames are response-less and return b''."""
        resp = self._resp
        if len(frame) < _REQ_BODY.size:
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_SHORT_FRAME)
        try:
            tid = 0
            if st.version >= 4 and frame[0] != OP_HELLO:
                # v4 frame extension: a u64 trace id rides between the
                # header and the key bytes (HELLO keeps the v1 shape —
                # it IS the negotiation frame).
                if len(frame) < _REQ_BODY4.size:
                    self._count_malformed()
                    return resp(st, ST_BAD_FRAME, 0, ERR_SHORT_FRAME)
                op, a, b, tid = _REQ_BODY4.unpack_from(frame)
                key_bytes = frame[_REQ_BODY4.size:]
            else:
                op, a, b = _REQ_BODY.unpack_from(frame)
                key_bytes = frame[_REQ_BODY.size:]
            ext = 0
            if st.version >= 6 and op in (OP_LEASE, OP_RENEW, OP_RELEASE):
                # v6 lease-frame extension: a u32 ``ext`` field rides
                # between the (v4) trace id and the key bytes, widening
                # lease budgets past the old 16-bit packing — LEASE:
                # b = requested (u32), ext bit 0 = bulk flag; RENEW:
                # b = used (u32), ext = requested (u32); RELEASE:
                # b = used (u32), ext reserved.  v<=5 connections never
                # send it and are served byte-identically to a v5
                # server.
                if len(key_bytes) < 4:
                    self._count_malformed()
                    return resp(st, ST_BAD_FRAME, 0, ERR_SHORT_FRAME)
                (ext,) = struct.unpack_from("<I", key_bytes)
                key_bytes = key_bytes[4:]
            # BATCH payloads are columns, not one key — their per-key
            # lengths are checked in the column validation.  The v5 gate
            # is inside the condition so a v<=4 connection sending op 10
            # stays byte-identical to a v4 server (key check first).
            batch_op = (op == OP_BATCH and st.version >= 5) or (
                op == OP_BULK_RENEW and st.version >= 6)
            if op != OP_TELEMETRY and not batch_op and self.max_key_bytes \
                    and len(key_bytes) > self.max_key_bytes:
                self._count_malformed()
                return resp(st, ST_BAD_FRAME, 0, ERR_KEY_TOO_LONG)
            if op == OP_HELLO:
                # min(client, server): a v2 client stays on v2 — it
                # never sees the v3 ops or statuses (nor the v4 frame
                # extension).
                st.version = min(int(a), PROTOCOL_VERSION) if a >= 2 else 1
                return _mk_resp(ST_OK, st.version, self.max_frame_bytes)
            if op == OP_PING:
                if self._draining:
                    return resp(st, ST_OK, 0, 0)
                return resp(st, ST_OK,
                            1 if self.storage.is_available() else 0, 0)
            if op == OP_TELEMETRY:
                if st.version < 4:
                    self._count_malformed()
                    return resp(st, ST_BAD_FRAME, 0, ERR_UNKNOWN_OP)
                # Response-less by contract: fold (or drop) and emit
                # nothing — a report must never cost a round trip.
                self._fold_telemetry(key_bytes)
                return b""
            if op == OP_BATCH:
                if st.version < 5:
                    # The batch op does not exist below v5: same
                    # unknown-op answer a v4 server would give.
                    self._count_malformed()
                    return resp(st, ST_BAD_FRAME, 0, ERR_UNKNOWN_OP)
                if self._draining:
                    self._count_drained()
                    return resp(st, ST_SHUTTING_DOWN, 0, 0)
                return self._begin_batch(st, a, b, tid, key_bytes)
            if op == OP_BULK_RENEW:
                if st.version < 6:
                    # The bulk-renew op does not exist below v6: same
                    # unknown-op answer a v5 server would give.
                    self._count_malformed()
                    return resp(st, ST_BAD_FRAME, 0, ERR_UNKNOWN_OP)
                if self._draining:
                    self._count_drained()
                    return resp(st, ST_SHUTTING_DOWN, 0, 0)
                return self._bulk_renew_frame(st, a, b, tid, key_bytes)
            lease_op = op in (OP_LEASE, OP_RENEW, OP_RELEASE)
            if lease_op and st.version < 3:
                # The lease ops do not exist below v3: a v2 (or v1)
                # connection sending one gets the same unknown-op
                # answer a v2 server would give — and never a lease
                # status.
                self._count_malformed()
                return resp(st, ST_BAD_FRAME, 0, ERR_UNKNOWN_OP)
            if not lease_op and op not in (OP_TRY_ACQUIRE, OP_AVAILABLE,
                                           OP_RESET):
                self._count_malformed()
                return resp(st, ST_BAD_FRAME, 0, ERR_UNKNOWN_OP)
            if self._draining:
                self._count_drained()
                return resp(st, ST_SHUTTING_DOWN, 0, 0)
            try:
                key = key_bytes.decode()
            except UnicodeDecodeError:
                self._count_malformed()
                return resp(st, ST_BAD_FRAME, 0, ERR_BAD_KEY)
            entry = self._limiters.get(a)
            if entry is None:
                return resp(st, ST_ERROR, 0, ERR_UNKNOWN_LIMITER)
            algo, _cfg = entry
            if tid:
                # An explicit wire trace id: the client asked for this
                # trace — force-sample it and stamp the ingress hop.
                lineage = getattr(self.storage, "lineage", None)
                if lineage is not None:
                    lineage.force(tid)
                    lineage.record(tid, "sidecar", op=op, lid=int(a),
                                   version=st.version)
            if lease_op:
                return self._lease_frame(st, op, a, b, key, tid, ext)
            if op == OP_TRY_ACQUIRE:
                return self._begin_acquire(st, algo, a, key,
                                           max(int(b), 1), tid)
            if op == OP_AVAILABLE:
                avail = int(self.storage.available_many(algo, a, [key])[0])
                return resp(st, ST_OK, 0, avail)
            # OP_RESET
            self.storage.reset_key(algo, a, key)
            return resp(st, ST_OK, 1, 0)
        except Exception:  # noqa: BLE001 — protocol errors must not kill the conn
            return resp(st, ST_ERROR, 0, ERR_INTERNAL)

    def _fold_telemetry(self, blob: bytes) -> None:
        """Fold one TELEMETRY frame into the fleet plane (best-effort:
        drained, plane-less, or malformed reports are dropped+counted,
        and the op never answers either way)."""
        self.telemetry_frames_total += 1
        plane = getattr(self.storage, "telemetry", None)
        if plane is None or self._draining:
            self.telemetry_dropped_total += 1
            return
        if plane.fold(blob) < 0:
            self.telemetry_dropped_total += 1

    def _conn_leases(self, st: _ConnState):
        """The lease backend for THIS connection: a session-capable
        backend (an ``edge.EdgeAggregator``) gets one session per
        connection — each client's subleases are its own — while a
        plain ``LeaseManager`` is shared.  Lazily resolved so
        ``attach_leases`` may run after connections are open."""
        if st.leases is not None:
            return st.leases
        backend = self._leases
        if backend is None:
            return None
        sess = getattr(backend, "session", None)
        st.leases = sess() if callable(sess) else backend
        return st.leases

    @staticmethod
    def _lease_ok_resp(st: _ConnState, allowed: int, granted: int,
                       ttl_ms: int, epoch: int) -> bytes:
        """OK lease response.  v6 appends the full-width u64 grant
        after the standard 14 bytes (the packed ``remaining`` keeps the
        old clamped fields, so the layout degrades readably); the
        length field disambiguates, exactly like BATCH responses.
        v<=5 stays the plain 14-byte shape, clamps intact."""
        packed = _pack_lease(granted, ttl_ms, epoch)
        if st.version >= 6:
            return _RESP.pack(_RESP.size - 4 + 8, ST_OK, allowed,
                              packed) + struct.pack("<Q", max(int(granted),
                                                              0))
        return _mk_resp(ST_OK, allowed, packed)

    def _lease_frame(self, st: _ConnState, op: int, lid: int, b: int,
                     key: str, trace_id: int = 0, ext: int = 0) -> bytes:
        """One v3+ lease op against the attached lease backend.
        Resolves synchronously (a lease frame amortizes over a whole
        budget, so it does not ride the pipelined decision path).  On a
        v6 connection the budget fields are full u32s (``ext`` carries
        RENEW's requested budget and LEASE's bulk flag); below v6 the
        v3 16-bit packing applies unchanged."""
        mgr = self._conn_leases(st)
        if mgr is None:
            return self._resp(st, ST_ERROR, 0, ERR_LEASE_DISABLED)
        v6 = st.version >= 6
        try:
            if op == OP_LEASE:
                if v6:
                    g = mgr.grant(lid, key, requested=int(b),
                                  trace_id=trace_id,
                                  bulk=bool(ext & 1))
                else:
                    g = mgr.grant(lid, key, requested=int(b) & 0xFFFF,
                                  trace_id=trace_id)
            elif op == OP_RENEW:
                if v6:
                    g = mgr.renew(lid, key, used=int(b),
                                  requested=int(ext), trace_id=trace_id)
                else:
                    g = mgr.renew(lid, key, used=int(b) & 0xFFFF,
                                  requested=(int(b) >> 16) & 0xFFFF,
                                  trace_id=trace_id)
                if g is None:
                    return self._resp(st, ST_LEASE_REVOKED, 0,
                                      _pack_lease(0, 0, 0))
            else:  # OP_RELEASE
                used = int(b) if v6 else int(b) & 0xFFFF
                mgr.release(lid, key, used=used, trace_id=trace_id)
                return self._resp(st, ST_OK, 1, 0)
            return self._lease_ok_resp(st, 1 if g.granted > 0 else 0,
                                       g.granted, g.ttl_ms, g.epoch)
        except KeyError:
            return self._resp(st, ST_ERROR, 0, ERR_UNKNOWN_LIMITER)
        except Exception:  # noqa: BLE001 — per-frame errors stay per-frame
            return self._resp(st, ST_ERROR, 0, ERR_INTERNAL)

    def _bulk_renew_frame(self, st: _ConnState, lid: int, rows: int,
                          trace_id: int, payload: bytes) -> bytes:
        """One v6 OP_BULK_RENEW frame: an edge aggregator renews its
        whole bulk portfolio for one lid in ONE columnar round trip.

        request payload (after the v4/v6 header fields)::

          u32 klen | key bytes[klen] | u32 offsets[rows + 1]
          | u64 used[rows] | u64 requested[rows] | u32 epochs[rows]

        ``epochs[i]`` names the lease instance row ``i`` reports for
        (0xFFFFFFFF = no instance check): a burn report for a revoked
        bulk lease must never fold into a successor grant on the same
        key, so the manager counts an epoch-mismatched row straight
        into ``over_admission`` and leaves the live lease untouched.

        response::

          u32 len | u8 status=OK | u8 1 | i64 rows
          | u64 granted[rows] | u32 ttl_ms[rows] | u32 epoch[rows]
          | u8 flags[rows]                  (bit 0: REVOKED — re-grant)

        Each row is the exact equivalent of one RENEW frame (same
        manager call, same revocation/over-admission accounting);
        column validation mirrors OP_BATCH and every violation is
        answered in-protocol with the stream left in sync."""
        resp = self._resp
        mgr = self._conn_leases(st)
        if mgr is None:
            return resp(st, ST_ERROR, 0, ERR_LEASE_DISABLED)
        rows = int(rows)
        if rows < 1 or (self.max_pipeline and rows > self.max_pipeline):
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_FRAME_TOO_LONG)
        if len(payload) < 4:
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_SHORT_FRAME)
        (klen,) = struct.unpack_from("<I", payload)
        off_pos = 4 + klen
        used_pos = off_pos + 4 * (rows + 1)
        req_pos = used_pos + 8 * rows
        ep_pos = req_pos + 8 * rows
        expect = ep_pos + 4 * rows
        if len(payload) != expect:
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0,
                        ERR_SHORT_FRAME if len(payload) < expect
                        else ERR_BAD_COLUMN)
        offsets = np.frombuffer(payload, np.uint32, rows + 1,
                                offset=off_pos).astype(np.int64)
        if (offsets[0] != 0 or offsets[-1] != klen
                or bool(np.any(np.diff(offsets) < 0))):
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_BAD_COLUMN)
        if self.max_key_bytes and rows and \
                int(np.diff(offsets).max()) > self.max_key_bytes:
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_KEY_TOO_LONG)
        try:
            payload[4:off_pos].decode()
        except UnicodeDecodeError:
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_BAD_KEY)
        if self._limiters.get(lid) is None:
            return resp(st, ST_ERROR, 0, ERR_UNKNOWN_LIMITER)
        used = np.frombuffer(payload, np.uint64, rows, offset=used_pos)
        req = np.frombuffer(payload, np.uint64, rows, offset=req_pos)
        eps = np.frombuffer(payload, np.uint32, rows, offset=ep_pos)
        if trace_id:
            lineage = getattr(self.storage, "lineage", None)
            if lineage is not None:
                lineage.force(trace_id)
                lineage.record(trace_id, "sidecar", op=OP_BULK_RENEW,
                               lid=int(lid), version=st.version,
                               rows=rows)
        granted = np.zeros(rows, dtype=np.uint64)
        ttls = np.zeros(rows, dtype=np.uint32)
        epochs = np.zeros(rows, dtype=np.uint32)
        flags = np.zeros(rows, dtype=np.uint8)
        try:
            for i in range(rows):
                key = payload[4 + offsets[i]:4 + offsets[i + 1]].decode()
                ep = int(eps[i])
                g = mgr.renew(lid, key, used=int(used[i]),
                              requested=int(req[i]), trace_id=trace_id,
                              epoch=None if ep == _EPOCH_ANY else ep)
                if g is None:
                    flags[i] = 1
                else:
                    granted[i] = max(int(g.granted), 0)
                    ttls[i] = min(max(int(g.ttl_ms), 0), 0xFFFFFFFF)
                    epochs[i] = min(max(int(g.epoch), 0), 0xFFFFFFFF)
        except UnicodeDecodeError:
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_BAD_KEY)
        except KeyError:
            return resp(st, ST_ERROR, 0, ERR_UNKNOWN_LIMITER)
        except Exception:  # noqa: BLE001 — per-frame errors stay per-frame
            return resp(st, ST_ERROR, 0, ERR_INTERNAL)
        cols = (granted.tobytes() + ttls.tobytes() + epochs.tobytes()
                + flags.tobytes())
        return _RESP.pack(_RESP.size - 4 + len(cols), ST_OK, 1,
                          rows) + cols

    def _begin_acquire(self, st: _ConnState, algo: str, lid: int, key: str,
                       permits: int, trace_id: int = 0):
        """Submit one decision frame, enforcing the pipeline cap and
        relaying the batcher's own admission control in-protocol."""
        n_inflight = self._pending_rows(st)
        if self.max_pipeline and n_inflight >= self.max_pipeline:
            self._count_pipeline_shed()
            plane = getattr(self.storage, "telemetry", None)
            if plane is not None:
                plane.note_shed(lid, 1)
            # The burst drains within roughly one micro-batch flush; the
            # hint mirrors the batcher's queue_full estimate.
            batcher = getattr(self.storage, "_batcher", None)
            hint = max(getattr(batcher, "max_delay_s", 0.001) * 1000.0, 1.0)
            return self._resp(st, ST_SHED, 0, int(hint))
        acquire_async = getattr(self.storage, "acquire_async", None)
        try:
            if acquire_async is not None:
                fut = acquire_async(algo, lid, key, permits,
                                    trace_id=trace_id)
                self._track_submit(1)
                return fut
            out = self.storage.acquire(algo, lid, key, permits)
            remaining = int(out.get("remaining", out.get("cache_value", 0)))
            return self._resp(st, ST_OK, 1 if out["allowed"] else 0,
                              remaining)
        except OverloadedError as exc:
            return self._resp(st, ST_SHED, 0,
                              max(int(exc.retry_after_ms), 1))
        except ShutdownError:
            return self._resp(st, ST_SHUTTING_DOWN, 0, 0)
        except Exception:  # noqa: BLE001 — per-frame errors stay per-frame
            return self._resp(st, ST_ERROR, 0, ERR_INTERNAL)

    @staticmethod
    def _pending_rows(st: _ConnState) -> int:
        """In-flight decision ROWS of the current burst (the pipeline
        cap's operand): a batch frame counts as its row count."""
        n = 0
        for p in st.pending:
            if isinstance(p, bytes):
                continue
            n += p.rows if isinstance(p, _BatchPending) else 1
        return n

    def _begin_batch(self, st: _ConnState, lid: int, rows: int,
                     trace_id: int, payload: bytes):
        """Validate one v5 columnar BATCH frame and submit it.

        Returns a _BatchPending (phase 2 packs the allow bitmask) or
        immediate response bytes for validation failures / shed.  The
        happy path touches no per-request Python objects: the key column
        feeds the native index verbatim and the whole frame rides ONE
        batcher block future."""
        resp = self._resp
        rows = int(rows)
        if rows < 1 or (self.max_pipeline and rows > self.max_pipeline):
            # Declared rows above the pipeline cap: reject before any
            # column math sized by the attacker's number.
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_FRAME_TOO_LONG)
        if self.max_pipeline and \
                self._pending_rows(st) + rows > self.max_pipeline:
            self._count_pipeline_shed()
            plane = getattr(self.storage, "telemetry", None)
            if plane is not None:
                plane.note_shed(lid, rows)
            batcher = getattr(self.storage, "_batcher", None)
            hint = max(getattr(batcher, "max_delay_s", 0.001) * 1000.0, 1.0)
            return resp(st, ST_SHED, 0, int(hint))
        if len(payload) < 4:
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_SHORT_FRAME)
        (klen,) = struct.unpack_from("<I", payload)
        off_pos = 4 + klen
        flag_pos = off_pos + 4 * (rows + 1)
        if len(payload) < flag_pos + 1:
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_SHORT_FRAME)
        flags = payload[flag_pos]
        expect = flag_pos + 1 + (4 * rows if flags & 1 else 0)
        if len(payload) != expect:
            # Column length mismatch: declared columns and frame length
            # disagree (short permits column, trailing garbage, ...).
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0,
                        ERR_SHORT_FRAME if len(payload) < expect
                        else ERR_BAD_COLUMN)
        offsets = np.frombuffer(payload, np.uint32, rows + 1,
                                offset=off_pos).astype(np.int64)
        if (offsets[0] != 0 or offsets[-1] != klen
                or bool(np.any(np.diff(offsets) < 0))):
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_BAD_COLUMN)
        if self.max_key_bytes and rows and \
                int(np.diff(offsets).max()) > self.max_key_bytes:
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_KEY_TOO_LONG)
        try:
            payload[4:off_pos].decode()  # one pass; no per-key objects
        except UnicodeDecodeError:
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_BAD_KEY)
        entry = self._limiters.get(lid)
        if entry is None:
            return resp(st, ST_ERROR, 0, ERR_UNKNOWN_LIMITER)
        algo, _cfg = entry
        permits = None
        if flags & 1:
            # Mirror the per-frame contract: permits floor at 1.
            permits = np.maximum(
                np.frombuffer(payload, np.uint32, rows,
                              offset=flag_pos + 1).astype(np.int64), 1)
        if trace_id:
            lineage = getattr(self.storage, "lineage", None)
            if lineage is not None:
                lineage.force(trace_id)
                lineage.record(trace_id, "sidecar", op=OP_BATCH,
                               lid=int(lid), version=st.version,
                               rows=rows)
        data = np.frombuffer(payload, np.uint8, klen, offset=4)
        try:
            block = getattr(self.storage, "acquire_async_block", None)
            fut = None
            if block is not None:
                fut = block(algo, lid, data, offsets, permits,
                            trace_id=trace_id)
            if fut is not None:
                self._track_submit(1)
                return _BatchPending(fut, rows)
            # Fallback (Python index / fenced shards): decode the keys
            # and ride the per-key async path — identical decisions.
            keys = [payload[4 + offsets[i]:4 + offsets[i + 1]].decode()
                    for i in range(rows)]
            many = getattr(self.storage, "acquire_async_many", None)
            if many is not None:
                futs = many(algo, lid, keys, permits)
                self._track_submit(len(futs))
                return _BatchPending(futs, rows)
            allowed = np.empty(rows, dtype=bool)
            perms = permits if permits is not None else np.ones(
                rows, dtype=np.int64)
            for i, k in enumerate(keys):
                allowed[i] = bool(
                    self.storage.acquire(algo, lid, k,
                                         int(perms[i]))["allowed"])
            return self._batch_resp(rows, allowed)
        except UnicodeDecodeError:
            # A multi-byte char split across key boundaries survives the
            # whole-buffer check but no per-key slice decodes.
            self._count_malformed()
            return resp(st, ST_BAD_FRAME, 0, ERR_BAD_KEY)
        except OverloadedError as exc:
            return resp(st, ST_SHED, 0, max(int(exc.retry_after_ms), 1))
        except ShutdownError:
            return resp(st, ST_SHUTTING_DOWN, 0, 0)
        except Exception:  # noqa: BLE001 — per-frame errors stay per-frame
            return resp(st, ST_ERROR, 0, ERR_INTERNAL)

    @staticmethod
    def _batch_resp(rows: int, allowed: np.ndarray) -> bytes:
        """OK batch response: standard header (remaining = rows) plus
        the packed allow bits; the length field disambiguates."""
        bits = np.packbits(np.asarray(allowed, dtype=bool)).tobytes()
        return _RESP.pack(_RESP.size - 4 + len(bits), ST_OK, 1,
                          rows) + bits

    def _finish_batch(self, item: _BatchPending, st: _ConnState) -> bytes:
        """Phase 2 for a BATCH frame: one bitmask response."""
        try:
            timeout = self.resolve_timeout_s or None
            if item.fut is not None:
                out = item.fut.result(timeout=timeout)
                allowed = np.asarray(out["allowed"], dtype=bool)
            else:
                allowed = np.empty(item.rows, dtype=bool)
                for i, f in enumerate(item.futs):
                    allowed[i] = bool(f.result(timeout=timeout)["allowed"])
            return self._batch_resp(item.rows, allowed)
        except OverloadedError as exc:
            return self._resp(st, ST_SHED, 0,
                              max(int(exc.retry_after_ms), 1))
        except ShutdownError:
            return self._resp(st, ST_SHUTTING_DOWN, 0, 0)
        except _FutureTimeout:
            for f in item.futures():
                f.add_done_callback(_consume_future)
            return self._resp(st, ST_ERROR, 0, ERR_INTERNAL)
        except Exception:  # noqa: BLE001 — per-frame errors stay per-frame
            return self._resp(st, ST_ERROR, 0, ERR_INTERNAL)
        finally:
            self._track_submit(-len(item.futures()))

    def _finish_frame(self, item, st: _ConnState) -> bytes:
        """Phase 2: resolve a submitted future (or pass bytes through)."""
        if isinstance(item, bytes):
            return item
        if isinstance(item, _BatchPending):
            return self._finish_batch(item, st)
        try:
            out = item.result(
                timeout=self.resolve_timeout_s or None)
            remaining = int(out.get("remaining", out.get("cache_value", 0)))
            return self._resp(st, ST_OK, 1 if out["allowed"] else 0,
                              remaining)
        except OverloadedError as exc:
            return self._resp(st, ST_SHED, 0,
                              max(int(exc.retry_after_ms), 1))
        except ShutdownError:
            return self._resp(st, ST_SHUTTING_DOWN, 0, 0)
        except _FutureTimeout:
            # The batch never resolved within the bound (wedged device):
            # answer in-protocol and make sure the future is consumed
            # whenever it does land — never leave this thread pinned.
            item.add_done_callback(_consume_future)
            return self._resp(st, ST_ERROR, 0, ERR_INTERNAL)
        except Exception:  # noqa: BLE001 — per-frame errors stay per-frame
            return self._resp(st, ST_ERROR, 0, ERR_INTERNAL)
        finally:
            self._track_submit(-1)

    def _abandon_pending(self, st: _ConnState) -> None:
        """The connection died mid-burst: no batcher future may leak.

        Still-queued frames are WITHDRAWN from the batcher (they stop
        consuming device capacity and their slots stop pinning eviction);
        frames already dispatched resolve normally and are consumed via a
        done-callback."""
        futs = []
        for p in st.pending:
            if isinstance(p, bytes):
                continue
            futs.extend(p.futures() if isinstance(p, _BatchPending) else [p])
        st.pending = []
        if not futs:
            return
        batcher = getattr(self.storage, "_batcher", None)
        withdrawn = 0
        if batcher is not None and hasattr(batcher, "forget"):
            try:
                withdrawn = batcher.forget(futs)
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
        for fut in futs:
            fut.add_done_callback(_consume_future)
        self.futures_abandoned += len(futs)
        self._track_submit(-len(futs))
        if withdrawn:
            log.debug("withdrew %d queued frame(s) of a dead connection",
                      withdrawn)


class SidecarSendError(ConnectionError):
    """Connection died while SENDING a request — the server cannot have
    processed it, so a caller may safely replay on a fresh connection.
    Read-phase failures stay plain ConnectionError: the server may have
    executed the request before dying, so replay risks double-charging."""


class SidecarShedError(RuntimeError):
    """The server shed the request (pipeline cap or batcher admission
    control); retry after ``retry_after_ms``."""

    def __init__(self, retry_after_ms: float = 0.0):
        super().__init__(
            f"sidecar shed the request; retry after {retry_after_ms} ms")
        self.retry_after_ms = float(retry_after_ms)


class LeaseWire(NamedTuple):
    """Unpacked lease response: (granted, ttl_ms, epoch)."""

    granted: int
    ttl_ms: int
    epoch: int


class SidecarClient:
    """Minimal pipelining client (reference for other-language ports).

    Speaks protocol v4 by default: sends HELLO at connect and records the
    negotiated version + the server's frame cap.  ``protocol=1`` skips
    the handshake (byte-compatible with the pre-v2 client); a v1 server
    answering HELLO with an error also downgrades the client to v1, and
    a v2/v3 server negotiates the connection down (no lease ops below
    v3; no trace ids / telemetry below v4).

    The lease methods (``lease_grant``/``lease_renew``/``lease_release``)
    plus :meth:`telemetry_report` make this a full
    ``leases/client.py:LeaseClient`` transport: burn decisions locally,
    renew one frame per budget, flush burn telemetry response-less.
    """

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 protocol: int = PROTOCOL_VERSION,
                 telemetry_send_timeout: float = 0.25):
        self._host = host
        self._port = int(port)
        self._timeout = float(timeout)
        self._protocol = int(protocol)
        # Drop-don't-block: one TELEMETRY send may stall at most this
        # long; a failed send marks telemetry down for this connection
        # (a partial write would desync the stream, so never retry).
        self._telemetry_send_timeout = float(telemetry_send_timeout)
        self._telemetry_down = False
        self._connect_and_hello()

    def _connect_and_hello(self) -> None:
        self._sock = socket.create_connection((self._host, self._port),
                                              timeout=self._timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rbuf = b""
        self.server_version = 1
        self.server_max_frame = 0
        if self._protocol >= 2:
            # The HELLO response carries the negotiated version in the
            # `allowed` byte — read it raw (no bool coercion).  Sends the
            # CALLER'S protocol (a v2-pinned client must negotiate v2,
            # not whatever this module's ceiling is).
            self._send(self._frame(OP_HELLO, self._protocol, 0, ""))
            status, version, max_frame = self._read_raw()
            if status == ST_OK and version:
                self.server_version = int(version)
                self.server_max_frame = int(max_frame)

    def reconnect(self) -> bool:
        """Tear the connection down and re-establish it (fresh socket +
        re-HELLO).  On success the telemetry latch is RE-ARMED: the
        latch exists because a PARTIAL telemetry write desyncs a shared
        stream, but a brand-new negotiated connection has no desynced
        history — one failed write no longer disables burn reporting for
        the life of the client.  Returns False (latch stays down) when
        the reconnect itself fails.

        Only call between pipelined bursts: any unread in-flight
        responses on the old connection are discarded."""
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            self._connect_and_hello()
        except (OSError, ConnectionError):
            self._telemetry_down = True
            return False
        self._telemetry_down = False
        return True

    def _send(self, payload: bytes) -> None:
        try:
            self._sock.sendall(payload)
        except OSError as exc:
            raise SidecarSendError(str(exc)) from exc

    def close(self) -> None:
        self._sock.close()

    # -- framing --------------------------------------------------------------
    def _frame(self, op: int, lid: int, permits: int, key: str,
               trace_id: int = 0,
               key_bytes: Optional[bytes] = None,
               ext: Optional[int] = None) -> bytes:
        """One request frame in the connection's negotiated format: the
        v4 shape carries a u64 trace id after the header (HELLO always
        keeps the v1 shape — it predates negotiation); ``ext`` is the
        v6 lease-frame u32 extension field (budget widening), inserted
        between the trace id and the key bytes on v6 connections."""
        raw = key.encode() if key_bytes is None else key_bytes
        if ext is not None and self.server_version >= 6:
            raw = struct.pack("<I", int(ext)) + raw
        if self.server_version >= 4 and op != OP_HELLO:
            body = _REQ_BODY4.pack(op, lid, permits,
                                   int(trace_id) & ((1 << 64) - 1)) + raw
        else:
            body = _REQ_BODY.pack(op, lid, permits) + raw
        return struct.pack("<I", len(body)) + body

    def _read_raw(self) -> Tuple[int, int, int]:
        """One response with raw integer fields (the HELLO reply packs
        the negotiated version into the `allowed` byte)."""
        while len(self._rbuf) < _RESP.size:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("sidecar closed connection")
            self._rbuf += chunk
        _, status, allowed, remaining = _RESP.unpack_from(self._rbuf)
        self._rbuf = self._rbuf[_RESP.size:]
        return status, allowed, remaining

    def _read_responses(self, n: int):
        out = []
        while len(out) < n:
            while len(self._rbuf) < _RESP.size:
                chunk = self._sock.recv(65536)
                if not chunk:
                    raise ConnectionError("sidecar closed connection")
                self._rbuf += chunk
            _, status, allowed, remaining = _RESP.unpack_from(self._rbuf)
            self._rbuf = self._rbuf[_RESP.size:]
            out.append((status, bool(allowed), remaining))
        return out

    @staticmethod
    def _check(status: int, remaining: int) -> None:
        if status == ST_OK:
            return
        if status == ST_SHED:
            raise SidecarShedError(retry_after_ms=remaining)
        if status == ST_SHUTTING_DOWN:
            raise SidecarShedError(retry_after_ms=1000.0)
        raise RuntimeError(f"sidecar error (status={status}, "
                           f"errno={remaining})")

    # -- API ------------------------------------------------------------------
    def try_acquire(self, lid: int, key: str, permits: int = 1,
                    trace_id: int = 0) -> bool:
        self._send(self._frame(OP_TRY_ACQUIRE, lid, permits, key,
                               trace_id=trace_id))
        status, allowed, remaining = self._read_responses(1)[0]
        self._check(status, remaining)
        return allowed

    def acquire_batch(
        self, lid: int, keys: Sequence[str],
        permits: Optional[Sequence[int]] = None,
    ):
        """Pipelined batch: N frames out, N responses in, one syscall each way."""
        permits = permits or [1] * len(keys)
        payload = b"".join(
            self._frame(OP_TRY_ACQUIRE, lid, p, k) for k, p in zip(keys, permits))
        self._send(payload)
        return self._read_responses(len(keys))

    # -- columnar batch (protocol v5) -----------------------------------------
    def _batch_frame(self, lid: int, keys: Sequence[str],
                     permits: Optional[Sequence[int]] = None,
                     trace_id: int = 0) -> bytes:
        """One v5 BATCH frame: interned key column + offsets (+ optional
        permits column).  One frame carries the whole chunk — the server
        answers with ONE packed allow bitmask."""
        kbufs = [k.encode() for k in keys]
        rows = len(kbufs)
        offs = np.zeros(rows + 1, dtype=np.uint32)
        np.cumsum(np.fromiter((len(b) for b in kbufs), dtype=np.uint32,
                              count=rows), out=offs[1:])
        key_col = b"".join(kbufs)
        parts = [struct.pack("<I", len(key_col)), key_col, offs.tobytes()]
        if permits is not None:
            parts.append(b"\x01")
            parts.append(np.asarray(permits, dtype=np.uint32).tobytes())
        else:
            parts.append(b"\x00")
        body = _REQ_BODY4.pack(OP_BATCH, lid, rows,
                               int(trace_id) & ((1 << 64) - 1)) + b"".join(parts)
        return struct.pack("<I", len(body)) + body

    def _read_block_response(self, rows: int) -> list:
        """One BATCH response: the standard 14-byte header plus
        ``length - 10`` bitmask bytes (error responses carry none and
        raise via :meth:`_check`, leaving the stream in sync)."""
        while len(self._rbuf) < _RESP.size:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("sidecar closed connection")
            self._rbuf += chunk
        length, status, _, remaining = _RESP.unpack_from(self._rbuf)
        self._rbuf = self._rbuf[_RESP.size:]
        if status != ST_OK:
            self._check(status, remaining)
        nbits = length - (_RESP.size - 4)
        while len(self._rbuf) < nbits:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("sidecar closed connection")
            self._rbuf += chunk
        bits = np.frombuffer(self._rbuf[:nbits], np.uint8)
        self._rbuf = self._rbuf[nbits:]
        return [bool(b) for b in np.unpackbits(bits)[:rows]]

    def acquire_block(self, lid: int, keys: Sequence[str],
                      permits: Optional[Sequence[int]] = None,
                      trace_id: int = 0, max_rows: int = 16) -> list:
        """Columnar batch acquire: ONE v5 frame per ``max_rows`` chunk
        (and one bitmask back), zero per-request frames on the wire.
        Falls back to :meth:`acquire_batch` below v5 with identical
        decisions.  Returns a list of per-row allow booleans; shed /
        shutdown / malformed answers raise like :meth:`_check`.

        ``max_rows`` defaults to the server's default pipeline cap — a
        frame declaring more rows than the cap is rejected whole."""
        rows_total = len(keys)
        if rows_total == 0:
            return []
        if self.server_version < 5:
            allowed = []
            for status, alw, remaining in self.acquire_batch(
                    lid, keys, permits):
                self._check(status, remaining)
                allowed.append(alw)
            return allowed
        allowed = []
        start = 0
        while start < rows_total:
            n = min(max_rows or rows_total, rows_total - start)
            while True:
                p = permits[start:start + n] if permits is not None else None
                frame = self._batch_frame(lid, keys[start:start + n], p,
                                          trace_id)
                if n == 1 or not self.server_max_frame or \
                        len(frame) - 4 <= self.server_max_frame:
                    break
                n = max(n // 2, 1)
            self._send(frame)
            allowed.extend(self._read_block_response(n))
            start += n
        return allowed

    # -- token leases (protocol v3; widened at v6) ----------------------------
    def _read_lease_response(self) -> Optional[LeaseWire]:
        """One lease response, honoring the length field: a v6 OK
        answer carries a trailing u64 full-width grant after the
        standard 14 bytes (authoritative — the packed ``remaining``
        clamps at the old 65535); revoked/error answers carry none."""
        while len(self._rbuf) < _RESP.size:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("sidecar closed connection")
            self._rbuf += chunk
        length, status, _, remaining = _RESP.unpack_from(self._rbuf)
        self._rbuf = self._rbuf[_RESP.size:]
        extra = max(length - (_RESP.size - 4), 0)
        while len(self._rbuf) < extra:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("sidecar closed connection")
            self._rbuf += chunk
        tail = self._rbuf[:extra]
        self._rbuf = self._rbuf[extra:]
        if status == ST_LEASE_REVOKED:
            return None
        self._check(status, remaining)
        granted, ttl_ms, epoch = _unpack_lease(remaining)
        if extra >= 8:
            (granted,) = struct.unpack_from("<Q", tail)
        return LeaseWire(int(granted), ttl_ms, epoch)

    def _lease_roundtrip(self, op: int, lid: int, b: int, key: str,
                         trace_id: int = 0,
                         ext: Optional[int] = None) -> Optional[LeaseWire]:
        if self.server_version < 3:
            raise RuntimeError(
                f"server negotiated protocol v{self.server_version}; "
                "lease ops need v3")
        self._send(self._frame(op, lid, b, key, trace_id=trace_id,
                               ext=ext))
        return self._read_lease_response()

    def lease_grant(self, lid: int, key: str, requested: int = 0,
                    trace_id: int = 0,
                    bulk: bool = False) -> Optional[LeaseWire]:
        """Charge a per-key budget; ``granted == 0`` means the key stays
        on the per-decision path for ``ttl_ms`` (retry hint).  ``bulk``
        (v6) marks an edge-aggregator portfolio lease — the budget is
        aggregate and may exceed the old 65535 wire cap."""
        if self.server_version >= 6:
            return self._lease_roundtrip(
                OP_LEASE, lid,
                min(int(requested), _LEASE_GRANT_MAX_V6), key,
                trace_id=trace_id, ext=1 if bulk else 0)
        return self._lease_roundtrip(OP_LEASE, lid,
                                     min(int(requested), 0xFFFF), key,
                                     trace_id=trace_id)

    def lease_renew(self, lid: int, key: str, used: int,
                    requested: int = 0,
                    trace_id: int = 0) -> Optional[LeaseWire]:
        """Report ``used`` burns + re-charge; None when REVOKED (the
        fence epoch advanced — re-grant via :meth:`lease_grant`)."""
        if self.server_version >= 6:
            return self._lease_roundtrip(
                OP_RENEW, lid, min(int(used), _LEASE_GRANT_MAX_V6), key,
                trace_id=trace_id,
                ext=min(int(requested), _LEASE_GRANT_MAX_V6))
        b = (min(int(used), 0xFFFF)
             | min(int(requested), 0xFFFF) << 16)
        return self._lease_roundtrip(OP_RENEW, lid, b, key,
                                     trace_id=trace_id)

    def lease_release(self, lid: int, key: str, used: int,
                      trace_id: int = 0) -> None:
        """Close a lease: final burn report, unused budget credited."""
        if self.server_version < 3:
            return
        if self.server_version >= 6:
            self._send(self._frame(OP_RELEASE, lid,
                                   min(int(used), _LEASE_GRANT_MAX_V6),
                                   key, trace_id=trace_id, ext=0))
        else:
            self._send(self._frame(OP_RELEASE, lid,
                                   min(int(used), 0xFFFF), key,
                                   trace_id=trace_id))
        try:
            self._read_lease_response()
        except (SidecarShedError, RuntimeError):
            pass  # release is best-effort, exactly as before

    def lease_bulk_renew(self, lid: int, keys: Sequence[str],
                         used: Sequence[int], requested: Sequence[int],
                         epochs: Optional[Sequence[int]] = None,
                         trace_id: int = 0) -> list:
        """Portfolio renewal (v6 OP_BULK_RENEW): one columnar frame
        renews every ``(key, used, requested)`` row — each row the
        exact equivalent of one :meth:`lease_renew` — and one columnar
        response comes back.  ``epochs`` (one per row, optional) names
        the lease instance each report belongs to; rows without one are
        sent with the ANY sentinel (no instance check).  Returns
        ``[(granted, ttl_ms, epoch, revoked), ...]`` in row order."""
        if self.server_version < 6:
            return [
                ((0, 0, 0, True) if r is None
                 else (int(r.granted), int(r.ttl_ms), int(r.epoch),
                       False))
                for r in (self.lease_renew(lid, k, int(u), int(q),
                                           trace_id=trace_id)
                          for k, u, q in zip(keys, used, requested))]
        rows = len(keys)
        if rows == 0:
            return []
        kbufs = [k.encode() for k in keys]
        offs = np.zeros(rows + 1, dtype=np.uint32)
        np.cumsum(np.fromiter((len(b) for b in kbufs), dtype=np.uint32,
                              count=rows), out=offs[1:])
        key_col = b"".join(kbufs)
        ep_col = (np.full(rows, _EPOCH_ANY, dtype=np.uint32)
                  if epochs is None
                  else np.asarray(epochs, dtype=np.uint32))
        payload = (struct.pack("<I", len(key_col)) + key_col
                   + offs.tobytes()
                   + np.asarray(used, dtype=np.uint64).tobytes()
                   + np.asarray(requested, dtype=np.uint64).tobytes()
                   + ep_col.tobytes())
        body = _REQ_BODY4.pack(OP_BULK_RENEW, lid, rows,
                               int(trace_id) & ((1 << 64) - 1)) + payload
        self._send(struct.pack("<I", len(body)) + body)
        while len(self._rbuf) < _RESP.size:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("sidecar closed connection")
            self._rbuf += chunk
        length, status, _, remaining = _RESP.unpack_from(self._rbuf)
        self._rbuf = self._rbuf[_RESP.size:]
        extra = max(length - (_RESP.size - 4), 0)
        while len(self._rbuf) < extra:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("sidecar closed connection")
            self._rbuf += chunk
        cols = self._rbuf[:extra]
        self._rbuf = self._rbuf[extra:]
        self._check(status, remaining)
        if extra != rows * _BULK_ROW_BYTES:
            raise RuntimeError(
                f"bulk-renew response carries {extra} column bytes; "
                f"expected {rows * _BULK_ROW_BYTES}")
        granted = np.frombuffer(cols, np.uint64, rows)
        ttls = np.frombuffer(cols, np.uint32, rows, offset=8 * rows)
        epochs = np.frombuffer(cols, np.uint32, rows, offset=12 * rows)
        flags = np.frombuffer(cols, np.uint8, rows, offset=16 * rows)
        return [(int(granted[i]), int(ttls[i]), int(epochs[i]),
                 bool(flags[i] & 1)) for i in range(rows)]

    # -- telemetry (protocol v4, response-less) -------------------------------
    def telemetry_supported(self) -> bool:
        return self.server_version >= 4 and not self._telemetry_down

    def telemetry_report(self, blob: bytes) -> bool:
        """Ship one burn report; NO response is read (the op is
        response-less by contract).  Drop-don't-block: a send that
        cannot complete within ``telemetry_send_timeout`` (or errors)
        returns False and marks telemetry down for this connection — a
        partial write would desync the stream, so it is never retried.
        Callers count False as a dropped flush and keep accumulating."""
        if not self.telemetry_supported():
            return False
        frame = self._frame(OP_TELEMETRY, 0, 0, "", key_bytes=bytes(blob))
        if self.server_max_frame and len(frame) - 4 > self.server_max_frame:
            return False
        prev = None
        try:
            prev = self._sock.gettimeout()
            self._sock.settimeout(self._telemetry_send_timeout)
            self._sock.sendall(frame)
            return True
        except OSError:
            self._telemetry_down = True
            return False
        finally:
            if prev is not None:
                try:
                    self._sock.settimeout(prev)
                except OSError:
                    pass

    def available(self, lid: int, key: str) -> int:
        self._send(self._frame(OP_AVAILABLE, lid, 0, key))
        status, _, remaining = self._read_responses(1)[0]
        self._check(status, remaining)
        return remaining

    def reset(self, lid: int, key: str) -> None:
        self._send(self._frame(OP_RESET, lid, 0, key))
        self._read_responses(1)

    def ping(self) -> bool:
        self._sock.sendall(self._frame(OP_PING, 0, 0, ""))
        _, allowed, _ = self._read_responses(1)[0]
        return allowed
