"""Decision sidecar: a binary TCP protocol replacing the Redis round-trip.

The reference's distributed story is "every app instance speaks RESP to one
Redis".  This framework's equivalent is the sidecar: non-Python services
(e.g. a JVM API gateway) connect over TCP and stream decision requests; the
server funnels every connection into the shared micro-batcher, so requests
from *all* clients coalesce into the same device batches — the many-clients
/one-authority topology of Redis, with the TPU engine as the authority.

Wire format (little-endian), deliberately RESP-simple so any language can
speak it in ~30 lines:

  request  :=  u32 len | u8 op | u32 limiter_id | u32 permits | key bytes
  response :=  u32 len | u8 status | u8 allowed | i64 remaining

  op: 1 = TRY_ACQUIRE   (allowed + remaining hint)
      2 = AVAILABLE     (remaining permits; allowed unused)
      3 = RESET         (admin)
      4 = PING          (health; allowed=1 when storage is up)
  status: 0 = ok, 1 = error (remaining carries an errno)

Requests may be pipelined: a client can write N frames before reading N
responses (the provided ``SidecarClient.acquire_batch`` does exactly this),
which amortizes syscalls the way Redis pipelining does
(the reference leans on the same trick for INCR+PEXPIRE).  The server
honors the pipelining on the decision path: every TRY_ACQUIRE frame of
a read burst is SUBMITTED to the micro-batcher before any is resolved
(``TpuBatchedStorage.acquire_async``), so a 64-deep pipeline coalesces
into one device flush instead of paying 64 sequential batcher round
trips — responses still return in request order.

Limiters are registered server-side by name -> (algo, config); clients
address them by the integer id returned at registration (distributed via
config, exactly like the reference's named Spring beans).
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Dict, Optional, Sequence, Tuple

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

OP_TRY_ACQUIRE = 1
OP_AVAILABLE = 2
OP_RESET = 3
OP_PING = 4

_REQ_BODY = struct.Struct("<BII")    # op, lid, permits (after the u32 len)
_RESP = struct.Struct("<IBBq")       # len, status, allowed, remaining


def _mk_resp(status: int, allowed: int, remaining: int) -> bytes:
    return _RESP.pack(_RESP.size - 4, status, allowed, remaining)

ERR_UNKNOWN_OP = 1
ERR_UNKNOWN_LIMITER = 2
ERR_INTERNAL = 3


class SidecarServer:
    """Threaded TCP server over a TpuBatchedStorage."""

    def __init__(self, storage: TpuBatchedStorage, host: str = "0.0.0.0",
                 port: int = 0):
        self.storage = storage
        self._limiters: Dict[int, Tuple[str, RateLimitConfig]] = {}
        self._conns: set = set()
        self._conn_lock = threading.Lock()
        self._stopped = False
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def setup(self):
                with outer._conn_lock:
                    if outer._stopped:
                        # Accepted in the shutdown race window: close now
                        # rather than serving from a closed storage.
                        try:
                            self.request.shutdown(socket.SHUT_RDWR)
                        except OSError:
                            pass
                        self.request.close()
                        return
                    outer._conns.add(self.request)

            def finish(self):
                with outer._conn_lock:
                    outer._conns.discard(self.request)

            def handle(self):
                sock: socket.socket = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                buf = b""
                while True:
                    try:
                        chunk = sock.recv(65536)
                    except OSError:
                        return
                    if not chunk:
                        return
                    buf += chunk
                    # Two-phase: submit every decision frame of this
                    # read burst (futures), THEN resolve in order — the
                    # whole pipeline lands in one micro-batch flush.
                    pending = []
                    while len(buf) >= 4:
                        (length,) = struct.unpack_from("<I", buf)
                        if len(buf) < 4 + length:
                            break
                        frame = buf[4:4 + length]
                        buf = buf[4 + length:]
                        pending.append(outer._begin_frame(frame))
                    if pending:
                        try:
                            sock.sendall(b"".join(
                                outer._finish_frame(p) for p in pending))
                        except OSError:
                            return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="sidecar", daemon=True)

    # -- limiter registry -----------------------------------------------------
    def register(self, algo: str, config: RateLimitConfig) -> int:
        lid = self.storage.register_limiter(algo, config)
        self._limiters[lid] = (algo, config)
        return lid

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "SidecarServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        # Close ACCEPTED connections too: a stopped sidecar must not leave
        # zombie handler threads answering clients from a closed storage
        # (clients would see protocol errors instead of a dead connection
        # and never reconnect).
        with self._conn_lock:
            self._stopped = True
            conns = list(self._conns)
            self._conns.clear()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    # -- frame handling -------------------------------------------------------
    def _begin_frame(self, frame: bytes):
        """Phase 1 of a pipelined burst: TRY_ACQUIRE frames are submitted
        to the micro-batcher and return their FUTURE; everything else
        (and every error) resolves immediately to response bytes."""
        try:
            op, lid, permits = _REQ_BODY.unpack_from(frame)
            if op == OP_TRY_ACQUIRE:
                entry = self._limiters.get(lid)
                if entry is None:
                    return _mk_resp(1, 0, ERR_UNKNOWN_LIMITER)
                acquire_async = getattr(self.storage, "acquire_async",
                                        None)
                if acquire_async is not None:
                    key = frame[_REQ_BODY.size:].decode()
                    return acquire_async(entry[0], lid, key,
                                         max(int(permits), 1))
        except Exception:  # noqa: BLE001 — protocol errors must not kill the conn
            return _mk_resp(1, 0, ERR_INTERNAL)
        return self._handle_frame(frame)

    @staticmethod
    def _finish_frame(item) -> bytes:
        """Phase 2: resolve a submitted future (or pass bytes through)."""
        if isinstance(item, bytes):
            return item
        try:
            out = item.result()
            remaining = int(out.get("remaining", out.get("cache_value", 0)))
            return _mk_resp(0, 1 if out["allowed"] else 0, remaining)
        except Exception:  # noqa: BLE001 — per-frame errors stay per-frame
            return _mk_resp(1, 0, ERR_INTERNAL)

    def _handle_frame(self, frame: bytes) -> bytes:
        resp = _mk_resp

        try:
            op, lid, permits = _REQ_BODY.unpack_from(frame)
            key = frame[_REQ_BODY.size:].decode()
            if op == OP_PING:
                return resp(0, 1 if self.storage.is_available() else 0, 0)
            entry = self._limiters.get(lid)
            if entry is None:
                return resp(1, 0, ERR_UNKNOWN_LIMITER)
            algo, _cfg = entry
            if op == OP_TRY_ACQUIRE:
                out = self.storage.acquire(algo, lid, key, max(int(permits), 1))
                remaining = int(out.get("remaining", out.get("cache_value", 0)))
                return resp(0, 1 if out["allowed"] else 0, remaining)
            if op == OP_AVAILABLE:
                avail = int(self.storage.available_many(algo, lid, [key])[0])
                return resp(0, 0, avail)
            if op == OP_RESET:
                self.storage.reset_key(algo, lid, key)
                return resp(0, 1, 0)
            return resp(1, 0, ERR_UNKNOWN_OP)
        except Exception:  # noqa: BLE001 — protocol errors must not kill the conn
            return resp(1, 0, ERR_INTERNAL)


class SidecarSendError(ConnectionError):
    """Connection died while SENDING a request — the server cannot have
    processed it, so a caller may safely replay on a fresh connection.
    Read-phase failures stay plain ConnectionError: the server may have
    executed the request before dying, so replay risks double-charging."""


class SidecarClient:
    """Minimal pipelining client (reference for other-language ports)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rbuf = b""

    def _send(self, payload: bytes) -> None:
        try:
            self._sock.sendall(payload)
        except OSError as exc:
            raise SidecarSendError(str(exc)) from exc

    def close(self) -> None:
        self._sock.close()

    # -- framing --------------------------------------------------------------
    @staticmethod
    def _frame(op: int, lid: int, permits: int, key: str) -> bytes:
        body = struct.pack("<BII", op, lid, permits) + key.encode()
        return struct.pack("<I", len(body)) + body

    def _read_responses(self, n: int):
        out = []
        while len(out) < n:
            while len(self._rbuf) < _RESP.size:
                chunk = self._sock.recv(65536)
                if not chunk:
                    raise ConnectionError("sidecar closed connection")
                self._rbuf += chunk
            _, status, allowed, remaining = _RESP.unpack_from(self._rbuf)
            self._rbuf = self._rbuf[_RESP.size:]
            out.append((status, bool(allowed), remaining))
        return out

    # -- API ------------------------------------------------------------------
    def try_acquire(self, lid: int, key: str, permits: int = 1) -> bool:
        self._send(self._frame(OP_TRY_ACQUIRE, lid, permits, key))
        status, allowed, _ = self._read_responses(1)[0]
        if status:
            raise RuntimeError("sidecar error")
        return allowed

    def acquire_batch(
        self, lid: int, keys: Sequence[str],
        permits: Optional[Sequence[int]] = None,
    ):
        """Pipelined batch: N frames out, N responses in, one syscall each way."""
        permits = permits or [1] * len(keys)
        payload = b"".join(
            self._frame(OP_TRY_ACQUIRE, lid, p, k) for k, p in zip(keys, permits))
        self._send(payload)
        return self._read_responses(len(keys))

    def available(self, lid: int, key: str) -> int:
        self._send(self._frame(OP_AVAILABLE, lid, 0, key))
        status, _, remaining = self._read_responses(1)[0]
        if status:
            raise RuntimeError("sidecar error")
        return remaining

    def reset(self, lid: int, key: str) -> None:
        self._send(self._frame(OP_RESET, lid, 0, key))
        self._read_responses(1)

    def ping(self) -> bool:
        self._sock.sendall(self._frame(OP_PING, 0, 0, ""))
        _, allowed, _ = self._read_responses(1)[0]
        return allowed
