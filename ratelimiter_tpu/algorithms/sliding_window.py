"""Sliding-window-counter limiter over the storage plugin boundary.

Behavioral parity with ``algorithms/SlidingWindowRateLimiter.java:34-189``:
two fixed window buckets with a weighted estimate, a local negative cache
that short-circuits repeat rejections (lines 93-100), pre-check then
increment-by-one (quirks Q1/Q2), and the same metric names (lines 67-77).
The estimate uses this framework's exact integer arithmetic — see
``semantics/oracle.py`` for the spec and its equivalence to the reference's
double math.

This is the "compat" per-call path: every decision performs storage
operations one at a time, exactly like the reference does against Redis.  The
TPU-batched fast path lives behind ``TpuBatchedStorage`` (storage/tpu.py) and
the batch entry points of ``RateLimiter``.
"""

from __future__ import annotations

import time
from typing import Callable

from ratelimiter_tpu.cache import TTLCache
from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.core.limiter import RateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.utils.logging import get_logger

log = get_logger("algorithms.sliding_window")

# Batches at or above this size route through the pipelined
# string-stream path (storage.acquire_stream_strs) instead of one
# synchronous device batch.
_STREAM_MIN = 1 << 15


def _wall_clock_ms() -> int:
    return time.time_ns() // 1_000_000


class SlidingWindowRateLimiter(RateLimiter):
    def __init__(
        self,
        storage: RateLimitStorage,
        config: RateLimitConfig,
        meter_registry: MeterRegistry,
        clock_ms: Callable[[], int] = _wall_clock_ms,
    ):
        config.validate()
        self._storage = storage
        self._config = config
        self._clock_ms = clock_ms

        # Local cache to reduce storage round trips; short TTL balances
        # performance vs accuracy (SlidingWindowRateLimiter.java:55-64).
        if config.enable_local_cache:
            self._local_cache = TTLCache(
                ttl_ms=config.local_cache_ttl_ms, max_size=10_000, clock_ms=clock_ms
            )
        else:
            self._local_cache = None

        self._allowed = meter_registry.counter(
            "ratelimiter.requests.allowed", "Number of allowed requests")
        self._rejected = meter_registry.counter(
            "ratelimiter.requests.rejected", "Number of rejected requests")
        self._cache_hits = meter_registry.counter(
            "ratelimiter.cache.hits", "Number of local cache hits")

        # TPU-batched backend: whole decisions execute as device kernels
        # behind the same storage boundary; per-op storage calls otherwise.
        self._lid = (
            storage.register_limiter("sw", config)
            if getattr(storage, "supports_device_batching", False)
            else None
        )

    # -- RateLimiter ----------------------------------------------------------
    def try_acquire(self, key: str, permits: int = 1) -> bool:
        if permits <= 0:
            raise ValueError("permits must be positive")

        # Fast path: recently-seen count at/over the limit -> reject without
        # touching storage (SlidingWindowRateLimiter.java:93-100).
        if self._local_cache is not None:
            cached = self._local_cache.get_if_present(key)
            if cached is not None and cached >= self._config.max_permits:
                self._cache_hits.increment()
                self._rejected.increment()
                return False

        if self._lid is not None:
            out = self._storage.acquire("sw", self._lid, key, permits)
            if self._local_cache is not None:
                self._local_cache.put(key, int(out["cache_value"]))
            allowed = bool(out["allowed"])
            # Decision trace (SlidingWindowRateLimiter.java:176-177 analog).
            log.debug("sw decision key=%s permits=%d observed=%d allowed=%s",
                      key, permits, int(out["observed"]), allowed)
            (self._allowed if allowed else self._rejected).increment()
            return allowed

        now = self._clock_ms()
        current = self._current_count(key, now)

        if current + permits > self._config.max_permits:
            # Cache the rejection to avoid hammering storage
            # (SlidingWindowRateLimiter.java:104-111).
            if self._local_cache is not None:
                self._local_cache.put(key, current)
            self._rejected.increment()
            return False

        # Increment the current bucket atomically (quirk Q1: by 1, not by
        # `permits`) and re-check on the raw counter (quirk Q2).
        win = self._config.window_ms
        new_count = self._storage.increment_and_expire(
            self._window_key(key, now, win), win)

        if self._local_cache is not None:
            self._local_cache.put(key, new_count)

        allowed = new_count <= self._config.max_permits
        log.debug("sw decision key=%s permits=%d count=%d allowed=%s",
                  key, permits, new_count, allowed)
        (self._allowed if allowed else self._rejected).increment()
        return allowed

    def try_acquire_many(self, keys, permits=None):
        """Vectorized tryAcquire — one device batch for the whole call on the
        TPU backend (falls back to the scalar loop otherwise)."""
        if self._lid is None:
            return super().try_acquire_many(keys, permits)
        import numpy as np

        n = len(keys)
        unit = permits is None
        if not unit:
            permits = [int(p) for p in permits]
            if any(p <= 0 for p in permits):
                raise ValueError("permits must be positive")
        if (n >= _STREAM_MIN and self._local_cache is None
                and hasattr(self._storage, "acquire_stream_strs")):
            # Large cache-less call: pipelined string streaming — decisions
            # identical to acquire_many (cache-enabled limiters keep the
            # batch path, which returns the cache_value lane).  permits=None
            # is forwarded as-is: the unit-permit stream takes the relay
            # path (no permits lane, no device sort/scan).
            allowed = np.asarray(self._storage.acquire_stream_strs(
                "sw", self._lid, list(keys),
                None if unit else np.asarray(permits, dtype=np.int64)),
                dtype=bool)
            n_allowed = int(allowed.sum())
            self._allowed.add(n_allowed)
            self._rejected.add(n - n_allowed)
            return allowed
        out = self._storage.acquire_many(
            "sw", [self._lid] * n, list(keys),
            [1] * n if unit else permits)
        allowed = np.asarray(out["allowed"], dtype=bool)
        if self._local_cache is not None:
            for k, v in zip(keys, out["cache_value"]):
                self._local_cache.put(k, int(v))
        n_allowed = int(allowed.sum())
        self._allowed.add(n_allowed)
        self._rejected.add(n - n_allowed)
        return allowed

    def try_acquire_ids(self, key_ids, permits=None):
        """Integer-key vectorized tryAcquire (hyperscale path, TPU backend
        only): no string hashing; one native index call + one device batch."""
        if self._lid is None:
            raise NotImplementedError("try_acquire_ids requires the TPU backend")
        import numpy as np

        key_ids = np.ascontiguousarray(key_ids, dtype=np.int64)
        n = len(key_ids)
        permits = (np.ones(n, dtype=np.int64) if permits is None
                   else np.ascontiguousarray(permits, dtype=np.int64))
        out = self._storage.acquire_many_ids("sw", self._lid, key_ids, permits)
        allowed = np.asarray(out["allowed"], dtype=bool)
        n_allowed = int(allowed.sum())
        self._allowed.add(n_allowed)
        self._rejected.add(n - n_allowed)
        return allowed

    def try_acquire_stream_ids(self, key_ids, permits=None, *,
                               batch: int = 1 << 14, subbatches: int = 4):
        """Whole-stream integer-key tryAcquire via the pipelined scan path
        (storage.acquire_stream_ids); decisions match try_acquire_ids.
        The local cache is bypassed — int-id streams are uncacheable at
        this scale, matching try_acquire_ids."""
        if self._lid is None:
            raise NotImplementedError(
                "try_acquire_stream_ids requires the TPU backend")
        allowed = self._storage.acquire_stream_ids(
            "sw", self._lid, key_ids, permits,
            batch=batch, subbatches=subbatches)
        n_allowed = int(allowed.sum())
        self._allowed.add(n_allowed)
        self._rejected.add(len(allowed) - n_allowed)
        return allowed

    def get_available_permits(self, key: str) -> int:
        if self._lid is not None:
            return int(self._storage.available_many("sw", self._lid, [key])[0])
        current = self._current_count(key, self._clock_ms())
        return max(0, self._config.max_permits - current)

    def reset(self, key: str) -> None:
        if self._lid is not None:
            self._storage.reset_key("sw", self._lid, key)
            if self._local_cache is not None:
                self._local_cache.invalidate(key)
            return
        now = self._clock_ms()
        win = self._config.window_ms
        # Clear current and previous windows
        # (SlidingWindowRateLimiter.java:140-153).
        self._storage.delete(self._window_key(key, now, win))
        self._storage.delete(self._window_key(key, now - win, win))
        if self._local_cache is not None:
            self._local_cache.invalidate(key)

    # -- internals ------------------------------------------------------------
    def _current_count(self, key: str, now: int) -> int:
        """Weighted two-window estimate, exact integer form
        (SlidingWindowRateLimiter.java:158-180)."""
        win = self._config.window_ms
        curr = self._storage.get(self._window_key(key, now, win))
        prev = self._storage.get(self._window_key(key, now - win, win))
        rem = now % win
        return curr + (prev * (win - rem)) // win

    @staticmethod
    def _window_key(key: str, timestamp_ms: int, window_ms: int) -> str:
        window_start = (timestamp_ms // window_ms) * window_ms
        return f"rl:{key}:{window_start}"
