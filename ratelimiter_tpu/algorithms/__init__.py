from ratelimiter_tpu.algorithms.sliding_window import SlidingWindowRateLimiter
from ratelimiter_tpu.algorithms.sliding_window_log import SlidingWindowLogRateLimiter
from ratelimiter_tpu.algorithms.token_bucket import TokenBucketRateLimiter

__all__ = [
    "SlidingWindowRateLimiter",
    "SlidingWindowLogRateLimiter",
    "TokenBucketRateLimiter",
]
