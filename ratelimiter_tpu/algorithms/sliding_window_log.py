"""Sliding-window-log limiter — exact (non-approximated) sliding window.

The reference declares sorted-set storage methods for this algorithm but
never implements it (quirk Q5 in SURVEY.md: ``zAdd``/``zRemoveRangeByScore``/
``zCount`` are dead surface).  This framework implements it, making the
zset portion of the storage contract load-bearing:

- every allowed request appends a timestamped member to the key's zset,
- expired members (older than ``now - window``) are pruned on access,
- the decision counts live members: exact sliding window, O(window·rate)
  memory per key (vs O(1) for the counter approximation).

This algorithm runs over the generic storage contract (host-side on both
backends — per-key event lists are deliberately not a device structure; the
device engines implement the O(1)-per-key algorithms).  Use it when exact
boundary behavior matters more than hyperscale throughput.

Semantics notes:
- ``try_acquire(key, permits)`` admits iff live_count + permits <= max and
  then records ``permits`` members (unlike the counter algorithm's quirky
  increment-by-one, this algorithm is exact — documented difference).
- Members are unique per (timestamp, sequence) so equal-ms requests don't
  collapse.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.core.limiter import RateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage.base import RateLimitStorage


def _wall_clock_ms() -> int:
    return time.time_ns() // 1_000_000


class SlidingWindowLogRateLimiter(RateLimiter):
    def __init__(
        self,
        storage: RateLimitStorage,
        config: RateLimitConfig,
        meter_registry: MeterRegistry,
        clock_ms: Callable[[], int] = _wall_clock_ms,
    ):
        config.validate()
        self._storage = storage
        self._config = config
        self._clock_ms = clock_ms
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self._allowed = meter_registry.counter(
            "ratelimiter.log.allowed", "Allowed requests (sliding window log)")
        self._rejected = meter_registry.counter(
            "ratelimiter.log.rejected", "Rejected requests (sliding window log)")

    def _zkey(self, key: str) -> str:
        return f"rll:{key}"

    def try_acquire(self, key: str, permits: int = 1) -> bool:
        if permits <= 0:
            raise ValueError("permits must be positive")
        cfg = self._config
        now = self._clock_ms()
        zkey = self._zkey(key)
        with self._lock:
            # Prune members outside the window, count the rest, then admit.
            self._storage.z_remove_range_by_score(
                zkey, float("-inf"), float(now - cfg.window_ms))
            live = self._storage.z_count(zkey, float("-inf"), float("inf"))
            if live + permits > cfg.max_permits:
                self._rejected.increment()
                return False
            for _ in range(permits):
                self._storage.z_add(zkey, float(now), f"{now}-{next(self._seq)}")
        self._allowed.increment()
        return True

    def get_available_permits(self, key: str) -> int:
        cfg = self._config
        now = self._clock_ms()
        zkey = self._zkey(key)
        with self._lock:
            self._storage.z_remove_range_by_score(
                zkey, float("-inf"), float(now - cfg.window_ms))
            live = self._storage.z_count(zkey, float("-inf"), float("inf"))
        return max(0, cfg.max_permits - live)

    def reset(self, key: str) -> None:
        self._storage.delete(self._zkey(key))
