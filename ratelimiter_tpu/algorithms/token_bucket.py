"""Token-bucket limiter over the storage plugin boundary.

Behavioral parity with ``algorithms/TokenBucketRateLimiter.java:28-159``:
burst-friendly, atomic refill-then-consume executed *inside the storage
backend* (the reference ships a Lua script to Redis, lines 38-68; we invoke
the backend's named ``token_bucket`` script — a device kernel on the TPU
backend), TTL = 2x window refreshed only on allow, permits > capacity
rejected client-side (lines 110-116), and the same metric names (lines
87-93).

Deliberate fix over the reference: ``get_available_permits`` performs a
read-only refill via the ``token_bucket_peek`` script instead of string-
GETting the bucket hash, which in the reference always throws (quirk Q3,
TokenBucketRateLimiter.java:146-151).
"""

from __future__ import annotations

import time
from typing import Callable

from ratelimiter_tpu.core.config import RateLimitConfig, TOKEN_FP_ONE
from ratelimiter_tpu.core.limiter import RateLimiter
from ratelimiter_tpu.metrics import MeterRegistry
from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.utils.logging import get_logger

log = get_logger("algorithms.token_bucket")

# Batches at or above this size route through the pipelined
# string-stream path (storage.acquire_stream_strs) instead of one
# synchronous device batch.
_STREAM_MIN = 1 << 15


def _wall_clock_ms() -> int:
    return time.time_ns() // 1_000_000


class TokenBucketRateLimiter(RateLimiter):
    def __init__(
        self,
        storage: RateLimitStorage,
        config: RateLimitConfig,
        meter_registry: MeterRegistry,
        clock_ms: Callable[[], int] = _wall_clock_ms,
    ):
        config.validate()
        if config.refill_rate <= 0:
            raise ValueError(
                "Token bucket requires positive refillRate. "
                "Use RateLimitConfig(refill_rate=...)")
        self._storage = storage
        self._config = config
        self._clock_ms = clock_ms

        self._allowed = meter_registry.counter(
            "ratelimiter.tokenbucket.allowed", "Allowed requests (token bucket)")
        self._rejected = meter_registry.counter(
            "ratelimiter.tokenbucket.rejected", "Rejected requests (token bucket)")

        self._lid = (
            storage.register_limiter("tb", config)
            if getattr(storage, "supports_device_batching", False)
            else None
        )

    # -- RateLimiter ----------------------------------------------------------
    def try_acquire(self, key: str, permits: int = 1) -> bool:
        if permits <= 0:
            raise ValueError("permits must be positive")
        cfg = self._config
        if permits > cfg.max_permits:
            # Can never fulfill this request
            # (TokenBucketRateLimiter.java:110-116).
            self._rejected.increment()
            return False

        if self._lid is not None:
            out = self._storage.acquire("tb", self._lid, key, permits)
            allowed = bool(out["allowed"])
            log.debug("tb decision key=%s permits=%d remaining=%d allowed=%s",
                      key, permits, int(out["remaining"]), allowed)
            (self._allowed if allowed else self._rejected).increment()
            return allowed

        now = self._clock_ms()
        allowed_flag, _tokens_fp = self._storage.eval_script(
            "token_bucket",
            keys=[f"tb:{key}"],
            args=[
                cfg.max_permits_fp,
                cfg.refill_rate_fp,
                permits * TOKEN_FP_ONE,
                now,
                cfg.window_ms * 2,  # TTL: 2x window for safety
            ],
        )
        allowed = allowed_flag == 1
        log.debug("tb decision key=%s permits=%d tokens_fp=%d allowed=%s",
                  key, permits, _tokens_fp, allowed)
        (self._allowed if allowed else self._rejected).increment()
        return allowed

    def try_acquire_many(self, keys, permits=None):
        """Vectorized tryAcquire — one device batch on the TPU backend."""
        if self._lid is None:
            return super().try_acquire_many(keys, permits)
        import numpy as np

        n = len(keys)
        unit = permits is None
        if not unit:
            permits = [int(p) for p in permits]
            if any(p <= 0 for p in permits):
                raise ValueError("permits must be positive")
        # The device kernel itself rejects permits > capacity pre-consume.
        if n >= _STREAM_MIN and hasattr(self._storage, "acquire_stream_strs"):
            # Large call: pipelined string streaming (host hashing rides in
            # the fetch shadow) — decisions identical to acquire_many.
            # permits=None forwards as-is so the unit-permit stream takes
            # the relay path (no permits lane, no device sort/scan).
            allowed = self._storage.acquire_stream_strs(
                "tb", self._lid, list(keys),
                None if unit else np.asarray(permits, dtype=np.int64))
        else:
            out = self._storage.acquire_many(
                "tb", [self._lid] * n, list(keys),
                [1] * n if unit else permits)
            allowed = np.asarray(out["allowed"], dtype=bool)
        n_allowed = int(allowed.sum())
        self._allowed.add(n_allowed)
        self._rejected.add(n - n_allowed)
        return allowed

    def try_acquire_ids(self, key_ids, permits=None):
        """Integer-key vectorized tryAcquire (hyperscale path, TPU backend
        only)."""
        if self._lid is None:
            raise NotImplementedError("try_acquire_ids requires the TPU backend")
        import numpy as np

        key_ids = np.ascontiguousarray(key_ids, dtype=np.int64)
        n = len(key_ids)
        permits = (np.ones(n, dtype=np.int64) if permits is None
                   else np.ascontiguousarray(permits, dtype=np.int64))
        out = self._storage.acquire_many_ids("tb", self._lid, key_ids, permits)
        allowed = np.asarray(out["allowed"], dtype=bool)
        n_allowed = int(allowed.sum())
        self._allowed.add(n_allowed)
        self._rejected.add(n - n_allowed)
        return allowed

    def try_acquire_stream_ids(self, key_ids, permits=None, *,
                               batch: int = 1 << 14, subbatches: int = 4):
        """Whole-stream integer-key tryAcquire via the pipelined scan path
        (storage.acquire_stream_ids); decisions match try_acquire_ids."""
        if self._lid is None:
            raise NotImplementedError(
                "try_acquire_stream_ids requires the TPU backend")
        allowed = self._storage.acquire_stream_ids(
            "tb", self._lid, key_ids, permits,
            batch=batch, subbatches=subbatches)
        n_allowed = int(allowed.sum())
        self._allowed.add(n_allowed)
        self._rejected.add(len(allowed) - n_allowed)
        return allowed

    def get_available_permits(self, key: str) -> int:
        if self._lid is not None:
            return int(self._storage.available_many("tb", self._lid, [key])[0])
        cfg = self._config
        (tokens_fp,) = self._storage.eval_script(
            "token_bucket_peek",
            keys=[f"tb:{key}"],
            args=[cfg.max_permits_fp, cfg.refill_rate_fp, self._clock_ms()],
        )
        return tokens_fp // TOKEN_FP_ONE

    def reset(self, key: str) -> None:
        if self._lid is not None:
            self._storage.reset_key("tb", self._lid, key)
            return
        self._storage.delete(f"tb:{key}")
