from ratelimiter_tpu.metrics.registry import Counter, MeterRegistry

__all__ = ["Counter", "MeterRegistry"]
