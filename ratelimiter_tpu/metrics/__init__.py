from ratelimiter_tpu.metrics.registry import Counter, Gauge, MeterRegistry, Timer

__all__ = ["Counter", "Gauge", "MeterRegistry", "Timer"]
