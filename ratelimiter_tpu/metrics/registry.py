"""Metrics counters.

Capability parity with the reference's Micrometer usage (C12 in SURVEY.md):
named monotonic counters registered against a registry, e.g.
``ratelimiter.requests.allowed`` / ``ratelimiter.requests.rejected`` /
``ratelimiter.cache.hits`` (SlidingWindowRateLimiter.java:67-77) and
``ratelimiter.tokenbucket.allowed`` / ``ratelimiter.tokenbucket.rejected``
(TokenBucketRateLimiter.java:87-93), exposed by the service's actuator-style
endpoints (application.properties:14-15).

The reference also *documents* a ``ratelimiter.storage.latency`` histogram
that it never implements (ARCHITECTURE.md:172-185); here we implement it —
``Timer`` records microsecond latencies with percentile snapshots.

Counters use per-instance locks and support batch increments (``add(n)``)
because one device step resolves thousands of decisions at once.
"""

from __future__ import annotations

import threading
from typing import Dict, List


class Counter:
    """A named monotonic counter (Micrometer Counter analog)."""

    __slots__ = ("name", "description", "_value", "_lock")

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._value = 0.0
        self._lock = threading.Lock()

    def increment(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    # Batch-friendly alias: one device step yields many decisions.
    def add(self, amount: float) -> None:
        self.increment(amount)

    def count(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """A named point-in-time value (Micrometer Gauge analog).

    Unlike ``Counter`` it is set, not accumulated — used for values that
    can move both ways, e.g. ``ratelimiter.replication.lag_ms``.
    """

    __slots__ = ("name", "description", "_value", "_lock")

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def value(self) -> float:
        with self._lock:
            return self._value


class Timer:
    """Latency recorder: fixed log2-bucket histogram with interpolated
    percentile snapshots.

    Implements the ``ratelimiter.storage.latency`` histogram the reference
    documents but never ships (ARCHITECTURE.md:172-185).  Bucket ``i``
    counts samples in ``(2^(i-1), 2^i]`` microseconds (bucket 0 holds
    ``<= 1 us``; the last bucket is unbounded), so

    - ``record_us`` is O(1) and lock-free — one bit_length plus three
      in-place adds.  CPython's GIL makes each add a read-modify-write
      that can lose a count under extreme contention, which is an
      accepted trade for a hot path that previously took a lock per
      sample;
    - ``snapshot`` walks 64 fixed counters instead of sorting an up-to-
      64Ki reservoir under the recorder's lock.

    Percentiles interpolate linearly inside the target bucket at rank
    ``p * n`` (the Prometheus ``histogram_quantile`` convention), which
    also removes the old reservoir's index bias: ``int(p * len)``
    returned the element *after* the p-quantile on small sample sets.

    ``max_samples`` is accepted for back-compat and ignored (there is no
    reservoir to bound).
    """

    __slots__ = ("name", "description", "_counts", "_count", "_total_us")

    #: Number of log2 buckets; bucket N_BUCKETS-1 is unbounded (+Inf).
    N_BUCKETS = 64

    def __init__(self, name: str, description: str = "",
                 max_samples: int = 0):
        self.name = name
        self.description = description
        self._counts = [0] * self.N_BUCKETS
        self._count = 0
        self._total_us = 0.0

    def record_us(self, micros: float) -> None:
        if micros > 1.0:
            # ceil(micros) - 1, then bit_length: value v lands in the
            # bucket whose range (2^(i-1), 2^i] contains it.
            idx = (-int(-micros) - 1).bit_length()
            if idx >= self.N_BUCKETS:
                idx = self.N_BUCKETS - 1
        else:
            idx = 0
        self._counts[idx] += 1
        self._count += 1
        self._total_us += micros

    # -- raw surfaces (Prometheus exposition; observability/prometheus.py) --
    def bucket_bounds_us(self) -> List[float]:
        """Inclusive upper bound of each bucket in us; last is +Inf."""
        return [float(1 << i) for i in range(self.N_BUCKETS - 1)] + [
            float("inf")]

    def bucket_counts(self) -> List[int]:
        return list(self._counts)

    def merge(self, sparse_buckets, total_us: float) -> None:
        """Fold pre-bucketed samples recorded elsewhere with the SAME
        log2 scheme (a lease client's local-latency histogram arriving
        in a telemetry report): ``sparse_buckets`` is an iterable of
        ``(bucket_idx, count)``."""
        added = 0
        for idx, count in sparse_buckets:
            idx = min(max(int(idx), 0), self.N_BUCKETS - 1)
            self._counts[idx] += int(count)
            added += int(count)
        self._count += added
        self._total_us += float(total_us)

    def count(self) -> int:
        return self._count

    def total_us(self) -> float:
        return self._total_us

    def _quantile(self, counts: List[int], n: int, p: float) -> float:
        rank = p * n
        cum = 0
        value = 0.0
        for i, c in enumerate(counts):
            if not c:
                continue
            lo = float(1 << (i - 1)) if i else 0.0
            # The unbounded last bucket interpolates over one octave.
            hi = float(1 << i) if i < self.N_BUCKETS - 1 else 2.0 * lo
            value = lo + (hi - lo) * min((rank - cum) / c, 1.0)
            if cum + c >= rank:
                return value
            cum += c
        return value

    def snapshot(self) -> Dict[str, float]:
        counts = list(self._counts)
        n = sum(counts)
        total = self._total_us
        if n == 0:
            return {"count": 0, "mean_us": 0.0, "p50_us": 0.0,
                    "p95_us": 0.0, "p99_us": 0.0}
        return {
            "count": n,
            "mean_us": total / n,
            "p50_us": self._quantile(counts, n, 0.50),
            "p95_us": self._quantile(counts, n, 0.95),
            "p99_us": self._quantile(counts, n, 0.99),
        }


class MeterRegistry:
    """Registry of named meters (SimpleMeterRegistry analog,
    config/RateLimiterConfig.java:37-40)."""

    def __init__(self):
        self._meters: Dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, description: str = "") -> Counter:
        with self._lock:
            meter = self._meters.get(name)
            if meter is None:
                meter = Counter(name, description)
                self._meters[name] = meter
            if not isinstance(meter, Counter):
                raise TypeError(f"meter {name!r} already registered as {type(meter).__name__}")
            return meter

    def gauge(self, name: str, description: str = "") -> Gauge:
        with self._lock:
            meter = self._meters.get(name)
            if meter is None:
                meter = Gauge(name, description)
                self._meters[name] = meter
            if not isinstance(meter, Gauge):
                raise TypeError(f"meter {name!r} already registered as {type(meter).__name__}")
            return meter

    def timer(self, name: str, description: str = "") -> Timer:
        with self._lock:
            meter = self._meters.get(name)
            if meter is None:
                meter = Timer(name, description)
                self._meters[name] = meter
            if not isinstance(meter, Timer):
                raise TypeError(f"meter {name!r} already registered as {type(meter).__name__}")
            return meter

    def meters(self) -> Dict[str, object]:
        """The live meter objects by name (a copy of the map, not the
        meters) — the Prometheus renderer needs bucket-level access that
        ``scrape()``'s value view flattens away."""
        with self._lock:
            return dict(self._meters)

    def scrape(self) -> Dict[str, object]:
        """All meter values, for the /actuator/metrics endpoint."""
        with self._lock:
            meters = dict(self._meters)
        out: Dict[str, object] = {}
        for name, meter in meters.items():
            if isinstance(meter, Counter):
                out[name] = meter.count()
            elif isinstance(meter, Gauge):
                out[name] = meter.value()
            elif isinstance(meter, Timer):
                out[name] = meter.snapshot()
        return out
