"""Metrics counters.

Capability parity with the reference's Micrometer usage (C12 in SURVEY.md):
named monotonic counters registered against a registry, e.g.
``ratelimiter.requests.allowed`` / ``ratelimiter.requests.rejected`` /
``ratelimiter.cache.hits`` (SlidingWindowRateLimiter.java:67-77) and
``ratelimiter.tokenbucket.allowed`` / ``ratelimiter.tokenbucket.rejected``
(TokenBucketRateLimiter.java:87-93), exposed by the service's actuator-style
endpoints (application.properties:14-15).

The reference also *documents* a ``ratelimiter.storage.latency`` histogram
that it never implements (ARCHITECTURE.md:172-185); here we implement it —
``Timer`` records microsecond latencies with percentile snapshots.

Counters use per-instance locks and support batch increments (``add(n)``)
because one device step resolves thousands of decisions at once.
"""

from __future__ import annotations

import threading
from typing import Dict, List


class Counter:
    """A named monotonic counter (Micrometer Counter analog)."""

    __slots__ = ("name", "description", "_value", "_lock")

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._value = 0.0
        self._lock = threading.Lock()

    def increment(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    # Batch-friendly alias: one device step yields many decisions.
    def add(self, amount: float) -> None:
        self.increment(amount)

    def count(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """A named point-in-time value (Micrometer Gauge analog).

    Unlike ``Counter`` it is set, not accumulated — used for values that
    can move both ways, e.g. ``ratelimiter.replication.lag_ms``.
    """

    __slots__ = ("name", "description", "_value", "_lock")

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def value(self) -> float:
        with self._lock:
            return self._value


class Timer:
    """Latency recorder with percentile snapshots.

    Implements the ``ratelimiter.storage.latency`` histogram the reference
    documents but never ships (ARCHITECTURE.md:172-185). Keeps a bounded
    reservoir of recent samples (microseconds).
    """

    __slots__ = ("name", "description", "_samples", "_count", "_total_us", "_lock", "_max_samples")

    def __init__(self, name: str, description: str = "", max_samples: int = 65536):
        self.name = name
        self.description = description
        self._samples: List[float] = []
        self._count = 0
        self._total_us = 0.0
        self._max_samples = max_samples
        self._lock = threading.Lock()

    def record_us(self, micros: float) -> None:
        with self._lock:
            self._count += 1
            self._total_us += micros
            if len(self._samples) < self._max_samples:
                self._samples.append(micros)
            else:
                # Simple reservoir: overwrite pseudo-randomly by count.
                self._samples[self._count % self._max_samples] = micros

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            n = self._count
            total = self._total_us
            samples = sorted(self._samples)
        if not samples:
            return {"count": 0, "mean_us": 0.0, "p50_us": 0.0, "p95_us": 0.0, "p99_us": 0.0}

        def pct(p: float) -> float:
            return samples[min(len(samples) - 1, int(p * len(samples)))]

        return {
            "count": n,
            "mean_us": total / max(1, n),
            "p50_us": pct(0.50),
            "p95_us": pct(0.95),
            "p99_us": pct(0.99),
        }


class MeterRegistry:
    """Registry of named meters (SimpleMeterRegistry analog,
    config/RateLimiterConfig.java:37-40)."""

    def __init__(self):
        self._meters: Dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, description: str = "") -> Counter:
        with self._lock:
            meter = self._meters.get(name)
            if meter is None:
                meter = Counter(name, description)
                self._meters[name] = meter
            if not isinstance(meter, Counter):
                raise TypeError(f"meter {name!r} already registered as {type(meter).__name__}")
            return meter

    def gauge(self, name: str, description: str = "") -> Gauge:
        with self._lock:
            meter = self._meters.get(name)
            if meter is None:
                meter = Gauge(name, description)
                self._meters[name] = meter
            if not isinstance(meter, Gauge):
                raise TypeError(f"meter {name!r} already registered as {type(meter).__name__}")
            return meter

    def timer(self, name: str, description: str = "") -> Timer:
        with self._lock:
            meter = self._meters.get(name)
            if meter is None:
                meter = Timer(name, description)
                self._meters[name] = meter
            if not isinstance(meter, Timer):
                raise TypeError(f"meter {name!r} already registered as {type(meter).__name__}")
            return meter

    def scrape(self) -> Dict[str, object]:
        """All meter values, for the /actuator/metrics endpoint."""
        with self._lock:
            meters = dict(self._meters)
        out: Dict[str, object] = {}
        for name, meter in meters.items():
            if isinstance(meter, Counter):
                out[name] = meter.count()
            elif isinstance(meter, Gauge):
                out[name] = meter.value()
            elif isinstance(meter, Timer):
                out[name] = meter.snapshot()
        return out
