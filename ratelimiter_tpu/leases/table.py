"""Host-side lease accounting: who holds which per-key permit budget.

One :class:`Lease` per ``(algo, lid, key)`` at a time — a leased key has
exactly one client burning it locally, which is what makes the
over-admission bound compose per key.  The table is pure bookkeeping
(budgets, TTL deadlines, fence epochs, usage counters); the device
charges/credits live in ``leases/manager.py`` via the storage's
``lease_reserve``/``lease_credit`` surface.

Bounded: ``max_leases`` caps the table; when full, expired leases are
swept first, then grants are refused (a refused grant just means the
client stays on the per-decision path — fail-closed, never unbounded
state).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Iterator, Optional, Tuple


@dataclasses.dataclass
class Lease:
    """One outstanding per-key permit budget."""

    algo: str
    lid: int
    key: str
    budget: int          # permits granted by the LAST reserve
    ws: int              # window the charge landed in (sw; 0 for tb)
    epoch: int           # fence epoch observed at grant time
    deadline_ms: int     # TTL deadline (manager clock)
    granted_total: int = 0   # permits charged over the lease's lifetime
    used_total: int = 0      # burns the client has reported back
    renewals: int = 0
    # Policy generation (control/, ARCHITECTURE §15) the budget was
    # charged under: a renewal at an older generation re-reserves under
    # the NEW rate (credit + fresh clamp against the updated config).
    policy_gen: int = 0
    # Bulk lease (edge/, ARCHITECTURE §14b): the holder is an edge
    # aggregator subleasing slices to its own clients, so the budget is
    # an AGGREGATE and clamps against ``max_bulk_budget`` instead of the
    # per-client ``max_budget``.  Over-admission nests: aggregator
    # outstanding <= this budget <= the core's outstanding bound.
    bulk: bool = False

    def expired(self, now_ms: int) -> bool:
        return now_ms >= self.deadline_ms


class LeaseTable:
    """Thread-safe bounded registry of outstanding leases."""

    def __init__(self, max_leases: int = 65536,
                 max_forward_jump_ms: int = 0,
                 forward_step_ms: int = 0):
        self._lock = threading.Lock()
        self._leases: Dict[Tuple[str, int, str], Lease] = {}
        self.max_leases = int(max_leases)
        # Forward clock-jump clamp (the TTL-side mirror of the storage
        # stamp's ``backward_clamps``): a wall-clock step LARGER than
        # ``max_forward_jump_ms`` is implausible (an injected jump, a
        # bad NTP slew), so :meth:`clamp_forward` refuses to replay it
        # into TTL accounting — the jump is ABSORBED into a standing
        # offset (counted once in ``forward_clamps``) and the expiry
        # clock resumes ``forward_step_ms`` past the last observation,
        # then keeps tracking subsequent wall progress at 1x.  Live
        # clients renewing at their normal cadence sail through
        # (nothing mass-expires in the poisoned tick, no matter how
        # many keys one sweep visits), while abandoned leases still
        # expire after their ordinary remaining TTL of rebased time.
        # Jumps at or under the threshold pass through untouched
        # (normal TTL expiry is exactly a legit forward step).
        # ``max_forward_jump_ms=0`` disables the clamp.
        self.max_forward_jump_ms = int(max_forward_jump_ms)
        self.forward_step_ms = int(forward_step_ms) or max(
            1, self.max_forward_jump_ms // 8)
        self.forward_clamps = 0
        self._expiry_clock: Optional[int] = None
        self._forward_offset = 0

    def clamp_forward(self, now_ms: int) -> int:
        """The table's view of ``now`` for TTL accounting: wall time
        minus the absorbed-jump offset.  A step beyond
        ``max_forward_jump_ms`` since the last observation grows the
        offset so TTL time lands ``forward_step_ms`` past that
        observation and continues at wall rate from there — every
        caller in the same sweep sees the SAME rebased now, so a
        poisoned jump can never expire more than a normal tick's worth
        of leases.  Backward steps pass through untouched (an earlier
        ``now`` only ever keeps a lease alive longer, which is the
        safe direction; the storage stamp clamp owns backward
        monotonicity)."""
        now = int(now_ms)
        if self.max_forward_jump_ms <= 0:
            return now
        with self._lock:
            eff = now - self._forward_offset
            if self._expiry_clock is None:
                self._expiry_clock = eff
                return eff
            if eff - self._expiry_clock > self.max_forward_jump_ms:
                target = self._expiry_clock + self.forward_step_ms
                self._forward_offset += eff - target
                eff = target
                self.forward_clamps += 1
            if eff > self._expiry_clock:
                self._expiry_clock = eff
            return eff

    @staticmethod
    def _k(algo: str, lid: int, key: str) -> Tuple[str, int, str]:
        return (algo, int(lid), key)

    def get(self, algo: str, lid: int, key: str) -> Optional[Lease]:
        with self._lock:
            return self._leases.get(self._k(algo, lid, key))

    def put(self, lease: Lease) -> bool:
        """Install a lease; False when the table is full (after sweeping
        nothing expired) — the caller refuses the grant."""
        with self._lock:
            k = self._k(lease.algo, lease.lid, lease.key)
            if k not in self._leases and len(self._leases) >= self.max_leases:
                return False
            self._leases[k] = lease
            return True

    def pop(self, algo: str, lid: int, key: str) -> Optional[Lease]:
        with self._lock:
            return self._leases.pop(self._k(algo, lid, key), None)

    def sweep_expired(self, now_ms: int) -> list:
        """Remove and return every TTL-expired lease."""
        with self._lock:
            dead = [k for k, v in self._leases.items()
                    if v.expired(now_ms)]
            return [self._leases.pop(k) for k in dead]

    def outstanding(self) -> int:
        with self._lock:
            return len(self._leases)

    def outstanding_budget(self) -> int:
        """Sum of unburned budget across live leases — the system-wide
        worst-case over-admission exposure if every leased client died
        right now AND every charge were lost (each per-key term is
        itself bounded by that key's remaining-window budget)."""
        with self._lock:
            return sum(v.budget for v in self._leases.values())

    def outstanding_budget_for(self, algo: str, lid: int,
                               exclude_key: Optional[str] = None) -> int:
        """One tenant's outstanding lease budget — the accounting behind
        concurrency slots (control/, ARCHITECTURE §15): with lease
        grants as slots, ``max_concurrent`` per tenant is enforced by
        bounding this sum.  ``exclude_key`` leaves one lease out (a
        renewal replaces its own budget, which must not count against
        itself).  O(outstanding leases) under the lock — grants are the
        cold path (decisions burn client-side)."""
        with self._lock:
            return sum(v.budget for (a, l, k), v in self._leases.items()
                       if a == algo and l == int(lid) and k != exclude_key)

    def __iter__(self) -> Iterator[Lease]:
        with self._lock:
            return iter(list(self._leases.values()))
