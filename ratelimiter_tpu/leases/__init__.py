"""Token leases: client-side enforcement with server reconciliation.

The server grants a client a bounded per-key permit budget (a *lease*)
charged atomically against the live device counters; the client burns
it locally at memory speed and renews one wire frame per budget instead
of one per decision — the 10-100x ingress collapse of "Rethinking HTTP
API Rate Limiting: A Client-Side Approach" (PAPERS.md).

Layers: ``ops/lease.py`` (the device RESERVE/CREDIT kernels, specified
bit-for-bit by ``semantics/oracle.py:reserve/credit``), ``table.py``
(host lease accounting), ``manager.py`` (grant/renew/release/revoke,
fence-epoch integration with PR 9 failover), ``client.py`` (the local
burner), wire protocol v3 (``service/sidecar.py``), and the chaos drill
``storage/chaos.py:lease_failover_drill``.
"""

from ratelimiter_tpu.leases.client import DirectTransport, LeaseClient
from ratelimiter_tpu.leases.manager import LeaseGrant, LeaseManager
from ratelimiter_tpu.leases.sublease import BulkPool, Sublease
from ratelimiter_tpu.leases.table import Lease, LeaseTable

__all__ = [
    "BulkPool",
    "DirectTransport",
    "Lease",
    "LeaseClient",
    "LeaseGrant",
    "LeaseManager",
    "LeaseTable",
    "Sublease",
]
