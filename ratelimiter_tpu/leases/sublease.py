"""Sublease accounting: how an edge aggregator nests client slices
inside one bulk lease (ARCHITECTURE §14b).

A :class:`BulkPool` is the aggregator-side mirror of ONE bulk lease on
``(lid, key)``: the core granted it an aggregate ``budget`` (leases/
manager.py, ``bulk=True``), and the pool hands out :class:`Sublease`
slices to clients at memory speed.  Permits are conserved — every
permit in the pool is in exactly one of three places::

    remaining + sliced_out + used_pending == budget + deficit

- ``remaining``     unsliced permits the pool can still hand out
- ``sliced_out``    permits in clients' hands, burns not yet reported
- ``used_pending``  burns reported by clients, not yet flushed upstream
- ``deficit``       transient over-hang after a SHRINKING renewal
                    (the core re-granted less than what is already
                    sliced out); returns from clients pay it down
                    before anything re-enters ``remaining``

The nesting invariant the property tests assert (tests/test_edge.py):
``sliced_out + remaining <= budget + deficit`` with ``deficit == 0``
whenever renewals are not shrinking — so the aggregator can never admit
more than its bulk budget between flushes, and fleet over-admission
when an aggregator dies is bounded by the sum of its bulk budgets,
exactly the per-key bound the core already documents.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


@dataclasses.dataclass
class Sublease:
    """One client's slice of a bulk pool."""

    session_id: int
    amount: int          # unreported permits this client may still burn
    granted_total: int = 0
    used_total: int = 0


@dataclasses.dataclass
class BulkPool:
    """Aggregator-side state of one bulk lease on ``(lid, key)``."""

    lid: int
    key: str
    budget: int          # aggregate granted by the core's LAST renewal
    remaining: int       # unsliced permits
    epoch: int           # scoped fence epoch stamped by the core
    deadline_ms: int     # bulk-lease TTL deadline (aggregator clock)
    sliced_out: int = 0
    used_pending: int = 0
    deficit: int = 0
    revoked: bool = False
    granted_total: int = 0
    renewals: int = 0
    subs: Dict[int, Sublease] = dataclasses.field(default_factory=dict)

    def expired(self, now_ms: int) -> bool:
        return now_ms >= self.deadline_ms

    def outstanding(self) -> int:
        """Permits the aggregator can admit without another upstream
        frame — the quantity the nesting invariant bounds by the bulk
        budget (plus any transient shrink deficit)."""
        return self.remaining + self.sliced_out

    def check_conservation(self) -> None:
        assert (self.remaining + self.sliced_out + self.used_pending
                == self.budget + self.deficit), (
            f"pool ({self.lid},{self.key!r}) conservation broken: "
            f"rem={self.remaining} out={self.sliced_out} "
            f"pending={self.used_pending} budget={self.budget} "
            f"deficit={self.deficit}")

    # -- slice lifecycle -------------------------------------------------------
    def slice(self, session_id: int, requested: int) -> Sublease:
        """Hand ``requested`` permits (clamped to ``remaining``) to a
        session.  A session that already holds a slice gets it FOLDED
        conservatively first (see :meth:`fold_lost`) — a re-granting
        client lost track of its old slice, and unreported permits must
        count as burned, never silently returned."""
        old = self.subs.get(session_id)
        if old is not None:
            self.fold_lost(old)
        amt = max(0, min(int(requested), self.remaining))
        self.remaining -= amt
        self.sliced_out += amt
        sub = Sublease(session_id=session_id, amount=amt,
                       granted_total=amt)
        self.subs[session_id] = sub
        return sub

    def fold_used(self, sub: Sublease, used: int) -> int:
        """Fold a client's reported burns into ``used_pending``;
        returns the portion actually backed by the slice (over-reports
        beyond the slice are counted conservatively: they grow
        ``used_pending`` AND ``deficit`` together, so conservation
        holds and the burn is still reported upstream)."""
        u = max(int(used), 0)
        take = min(u, sub.amount)
        sub.amount -= take
        sub.used_total += u
        self.sliced_out -= take
        self.used_pending += take
        extra = u - take
        if extra > 0:
            self.used_pending += extra
            self.deficit += extra
        return take

    def return_unused(self, sub: Sublease) -> int:
        """Give a slice's unburned remainder back to the pool — paying
        down any shrink deficit before permits re-enter circulation."""
        rem = sub.amount
        sub.amount = 0
        self.sliced_out -= rem
        pay = min(rem, self.deficit)
        self.deficit -= pay
        self.remaining += rem - pay
        return rem

    def fold_lost(self, sub: Sublease) -> None:
        """A slice whose holder vanished (crash, re-grant after drop):
        its unreported permits may or may not have been burned, so the
        conservative fold counts them as USED — they flush upstream as
        burns, keeping the core's view an upper bound."""
        rem = sub.amount
        sub.amount = 0
        self.sliced_out -= rem
        self.used_pending += rem

    def top_up(self, sub: Sublease, requested: int) -> int:
        """Refill a (folded, emptied) slice to ``requested`` from
        ``remaining`` — the renewal path's re-slice.  Returns the new
        slice amount (0 when the pool is dry)."""
        amt = max(0, min(int(requested), self.remaining))
        self.remaining -= amt
        self.sliced_out += amt
        sub.amount = amt
        sub.granted_total += amt
        return amt

    def fold_over_report(self, used: int) -> None:
        """Burns reported with no slice backing them (a client whose
        sublease this pool never saw): conserve by growing
        ``used_pending`` and ``deficit`` together — the burn still
        flushes upstream, it just never consumes pool capacity."""
        u = max(int(used), 0)
        self.used_pending += u
        self.deficit += u

    def drop_sub(self, session_id: int) -> Optional[Sublease]:
        return self.subs.pop(session_id, None)

    # -- renewal bookkeeping ---------------------------------------------------
    def apply_renewal(self, granted: int, ttl_ms: int, epoch: int,
                      now_ms: int, reported_used: int) -> None:
        """Fold one upstream renewal answer in: ``reported_used`` burns
        left ``used_pending``, the pool's aggregate capacity becomes
        ``granted``, and a shrink below what is already sliced out
        becomes ``deficit`` (paid down by future returns)."""
        self.used_pending = max(self.used_pending - int(reported_used), 0)
        self.budget = int(granted)
        self.deficit = max(0, self.sliced_out + self.used_pending
                           - self.budget)
        self.remaining = max(0, self.budget - self.sliced_out
                             - self.used_pending)
        self.epoch = int(epoch)
        self.deadline_ms = int(now_ms) + max(int(ttl_ms), 1)
        self.granted_total += int(granted)
        self.renewals += 1
        self.check_conservation()


PoolKey = Tuple[int, str]
