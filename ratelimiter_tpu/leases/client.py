"""Client side of token leases: the local burner.

A :class:`LeaseClient` turns "one wire frame per decision" into "one
wire frame per budget": it holds a per-key lease (a permit budget the
server pre-charged on the device) and answers ``try_acquire`` from host
memory — a dict lookup and a decrement — renewing over the wire only
when the budget runs out, the TTL expires, or the server revokes.

Admission safety is the server's by construction: every locally-allowed
permit was already charged against the device counters at grant time,
so a crashing client can only UNDER-admit (charged-but-unburned budget,
reclaimed by TTL/window expiry).  The over-admission window exists only
across a failover (burns between a fence-epoch bump and the next
renewal), bounded by the outstanding budget — which the reserve kernel
bounded by the key's remaining-window budget.

Decision semantics seen by the caller:

- lease live and budget covers ``permits`` -> local ALLOW (zero wire);
- budget exhausted / TTL passed -> one RENEW (or LEASE) round trip,
  then the fresh budget answers;
- server granted 0 (key contended, already leased elsewhere, fenced,
  or over its remaining-window budget) -> the key stays on the
  per-decision path: with ``direct_fallback=True`` (default) each
  decision forwards to the server's ordinary TRY_ACQUIRE (the device
  arbitrates contended keys, exactly as without leases); with
  ``direct_fallback=False`` the client denies locally until the
  server's retry hint elapses (strict lease-only mode — the chaos
  drill uses it so every state mutation flows through the replayable
  reserve/credit log).

Transports are duck-typed: ``service/sidecar.py:SidecarClient`` (wire
protocol v3) and :class:`DirectTransport` (in-process, over a
``LeaseManager``) both provide ``lease_grant`` / ``lease_renew`` /
``lease_release`` / ``try_acquire``.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, Optional


def _wall_ms() -> int:
    return time.time_ns() // 1_000_000


class _Local:
    """One locally-held lease."""

    __slots__ = ("remaining", "used", "deadline", "epoch", "deny_until")

    def __init__(self, remaining: int, deadline: int, epoch: int,
                 deny_until: int = 0):
        self.remaining = int(remaining)
        self.used = 0
        self.deadline = int(deadline)
        self.epoch = int(epoch)
        self.deny_until = int(deny_until)


class DirectTransport:
    """In-process transport: LeaseClient -> LeaseManager (drills,
    embedded deployments — no TCP in the loop)."""

    def __init__(self, manager):
        self.manager = manager

    def lease_grant(self, lid: int, key: str, requested: int):
        return self.manager.grant(lid, key, requested)

    def lease_renew(self, lid: int, key: str, used: int,
                    requested: int = 0):
        return self.manager.renew(lid, key, used, requested)

    def lease_release(self, lid: int, key: str, used: int) -> None:
        self.manager.release(lid, key, used)

    def try_acquire(self, lid: int, key: str, permits: int = 1) -> bool:
        algo, _cfg = self.manager._algo_cfg(lid)
        out = self.manager.storage.acquire(algo, lid, key, permits)
        return bool(out["allowed"])


class LeaseClient:
    """Local lease burner over a lease-capable transport."""

    def __init__(self, transport, lid: int, *, budget: int = 64,
                 clock_ms=None, direct_fallback: bool = True):
        self._t = transport
        self.lid = int(lid)
        self.budget = max(int(budget), 1)
        self._clock_ms = clock_ms or _wall_ms
        self.direct_fallback = bool(direct_fallback)
        self._leases: Dict[str, _Local] = {}
        # Accounting (the loopback bench computes its wire-frame ratio
        # from these; the chaos drill asserts per-key admission).
        self.local_decisions = 0   # allows answered with ZERO wire frames
        self.local_denies = 0
        self.wire_ops = 0          # lease + fallback frames sent
        self.revoked_seen = 0
        self.allowed_by_key: collections.Counter = collections.Counter()

    # -- the decision surface --------------------------------------------------
    def try_acquire(self, key: str, permits: int = 1) -> bool:
        permits = max(int(permits), 1)
        now = int(self._clock_ms())
        lease = self._leases.get(key)
        if lease is not None and now < lease.deadline \
                and lease.remaining >= permits:
            lease.remaining -= permits
            lease.used += permits
            self.local_decisions += 1
            self.allowed_by_key[key] += permits
            return True
        lease = self._refresh(key, lease, now)
        if lease is not None and now < lease.deadline \
                and lease.remaining >= permits:
            lease.remaining -= permits
            lease.used += permits
            self.allowed_by_key[key] += permits
            return True
        if self.direct_fallback:
            self.wire_ops += 1
            allowed = bool(self._t.try_acquire(self.lid, key, permits))
            if allowed:
                self.allowed_by_key[key] += permits
            return allowed
        self.local_denies += 1
        return False

    def _refresh(self, key: str, lease: Optional[_Local],
                 now: int) -> Optional[_Local]:
        """Renew/re-grant over the wire; None when no budget is usable
        (cooldown after a zero grant, or the server refused)."""
        if lease is not None and lease.remaining <= 0 \
                and now < lease.deny_until:
            return None  # zero-grant cooldown: no wire spam
        if lease is not None and (lease.used or lease.remaining):
            self.wire_ops += 1
            resp = self._t.lease_renew(self.lid, key, lease.used,
                                       self.budget)
            lease.used = 0
            if resp is None:  # revoked: re-grant against whatever serves
                self.revoked_seen += 1
                self.wire_ops += 1
                resp = self._t.lease_grant(self.lid, key, self.budget)
        else:
            self.wire_ops += 1
            resp = self._t.lease_grant(self.lid, key, self.budget)
        if resp is None:
            self._leases.pop(key, None)
            return None
        granted, ttl_ms, epoch = resp[0], resp[1], resp[2]
        if granted <= 0:
            cool = _Local(0, now, epoch, deny_until=now + max(ttl_ms, 1))
            self._leases[key] = cool
            return None
        fresh = _Local(granted, now + ttl_ms, epoch)
        self._leases[key] = fresh
        return fresh

    # -- lifecycle -------------------------------------------------------------
    def release_all(self) -> None:
        """Report final burns and hand every unused budget back."""
        for key, lease in list(self._leases.items()):
            if lease.used or lease.remaining:
                self.wire_ops += 1
                try:
                    self._t.lease_release(self.lid, key, lease.used)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
        self._leases.clear()

    def drop(self) -> dict:
        """Simulate a client crash (the chaos drill's kill): abandon
        every lease WITHOUT releasing — returns what was outstanding so
        the drill can assert the over-admission bound."""
        out = {k: {"remaining": v.remaining, "used": v.used}
               for k, v in self._leases.items()}
        self._leases.clear()
        return out

    close = release_all
