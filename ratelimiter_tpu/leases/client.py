"""Client side of token leases: the local burner.

A :class:`LeaseClient` turns "one wire frame per decision" into "one
wire frame per budget": it holds a per-key lease (a permit budget the
server pre-charged on the device) and answers ``try_acquire`` from host
memory — a dict lookup and a decrement — renewing over the wire only
when the budget runs out, the TTL expires, or the server revokes.

Admission safety is the server's by construction: every locally-allowed
permit was already charged against the device counters at grant time,
so a crashing client can only UNDER-admit (charged-but-unburned budget,
reclaimed by TTL/window expiry).  The over-admission window exists only
across a failover (burns between a fence-epoch bump and the next
renewal), bounded by the outstanding budget — which the reserve kernel
bounded by the key's remaining-window budget.

Decision semantics seen by the caller:

- lease live and budget covers ``permits`` -> local ALLOW (zero wire);
- budget exhausted / TTL passed -> one RENEW (or LEASE) round trip,
  then the fresh budget answers;
- server granted 0 (key contended, already leased elsewhere, fenced,
  or over its remaining-window budget) -> the key stays on the
  per-decision path: with ``direct_fallback=True`` (default) each
  decision forwards to the server's ordinary TRY_ACQUIRE (the device
  arbitrates contended keys, exactly as without leases); with
  ``direct_fallback=False`` the client denies locally until the
  server's retry hint elapses (strict lease-only mode — the chaos
  drill uses it so every state mutation flows through the replayable
  reserve/credit log).

Transports are duck-typed: ``service/sidecar.py:SidecarClient`` (wire
protocol v3/v4) and :class:`DirectTransport` (in-process, over a
``LeaseManager``) both provide ``lease_grant`` / ``lease_renew`` /
``lease_release`` / ``try_acquire`` / ``telemetry_report``.

**Burn telemetry (observability/telemetry.py).**  With leases on, the
server no longer observes most decisions — it sees one coarse ``used``
count per renewal.  The client therefore accumulates per-(lid,
key-class) burn/deny counts plus a local-decision latency histogram
(the Timer log2-bucket scheme) and flushes them as one TELEMETRY
report: piggybacked in front of every renew/grant wire op (the op is
response-less, so this adds zero round trips) and on a bounded cadence
(``telemetry_flush_ms``) otherwise.  **Drop-don't-block**: a flush
that cannot be shipped is dropped and counted
(``telemetry_dropped``) — its counts are lost by design; telemetry is
an observability signal, never backpressure on the decision path.

**Trace lineage.**  With ``trace_lineage=True`` each lease mints one
64-bit trace id at grant and carries it on every wire op, so the
server's lineage ring shows grant -> local burns (the ``client`` hop
renew stamps) -> renew under one id (``trace_of(key)`` returns it).
"""

from __future__ import annotations

import collections
import time
from typing import Dict, Optional


def _wall_ms() -> int:
    return time.time_ns() // 1_000_000


class _Local:
    """One locally-held lease."""

    __slots__ = ("remaining", "used", "deadline", "epoch", "deny_until",
                 "trace")

    def __init__(self, remaining: int, deadline: int, epoch: int,
                 deny_until: int = 0, trace: int = 0):
        self.remaining = int(remaining)
        self.used = 0
        self.deadline = int(deadline)
        self.epoch = int(epoch)
        self.deny_until = int(deny_until)
        self.trace = int(trace)


class DirectTransport:
    """In-process transport: LeaseClient -> LeaseManager (drills,
    embedded deployments — no TCP in the loop)."""

    def __init__(self, manager):
        self.manager = manager

    def lease_grant(self, lid: int, key: str, requested: int,
                    trace_id: int = 0, bulk: bool = False):
        return self.manager.grant(lid, key, requested, trace_id=trace_id,
                                  bulk=bulk)

    def lease_renew(self, lid: int, key: str, used: int,
                    requested: int = 0, trace_id: int = 0):
        return self.manager.renew(lid, key, used, requested,
                                  trace_id=trace_id)

    def lease_bulk_renew(self, lid: int, keys, used, requested,
                         epochs=None, trace_id: int = 0):
        """Portfolio renewal (edge aggregators): one row per key, each
        the exact equivalent of :meth:`lease_renew`.  ``epochs`` (one
        per row, optional) names the lease instance each report belongs
        to, so burns flushed for a revoked bulk lease can never fold
        into a successor grant's accounting.  Returns one ``(granted,
        ttl_ms, epoch, revoked)`` tuple per row — the in-process mirror
        of wire v6 ``OP_BULK_RENEW``."""
        out = []
        eps = epochs if epochs is not None else [None] * len(keys)
        for key, u, req, ep in zip(keys, used, requested, eps):
            resp = self.manager.renew(lid, key, int(u), int(req),
                                      trace_id=trace_id,
                                      epoch=None if ep is None else int(ep))
            if resp is None:
                out.append((0, 0, 0, True))
            else:
                out.append((int(resp.granted), int(resp.ttl_ms),
                            int(resp.epoch), False))
        return out

    def lease_release(self, lid: int, key: str, used: int,
                      trace_id: int = 0) -> None:
        self.manager.release(lid, key, used, trace_id=trace_id)

    def try_acquire(self, lid: int, key: str, permits: int = 1,
                    trace_id: int = 0) -> bool:
        algo, _cfg = self.manager._algo_cfg(lid)
        out = self.manager.storage.acquire(algo, lid, key, permits)
        return bool(out["allowed"])

    def telemetry_report(self, blob: bytes) -> bool:
        return self.manager.telemetry_report(blob) >= 0


class LeaseClient:
    """Local lease burner over a lease-capable transport."""

    def __init__(self, transport, lid: int, *, budget: int = 64,
                 clock_ms=None, direct_fallback: bool = True,
                 telemetry: bool = True,
                 telemetry_flush_ms: float = 250.0,
                 telemetry_rearm_ms: float = 5000.0,
                 key_class=None,
                 trace_lineage: bool = False):
        self._t = transport
        self.lid = int(lid)
        self.budget = max(int(budget), 1)
        self._clock_ms = clock_ms or _wall_ms
        self.direct_fallback = bool(direct_fallback)
        self._leases: Dict[str, _Local] = {}
        # Accounting (the loopback bench computes its wire-frame ratio
        # from these; the chaos drill asserts per-key admission).
        self.local_decisions = 0   # allows answered with ZERO wire frames
        self.local_denies = 0
        self.wire_ops = 0          # lease + fallback frames sent
        self.revoked_seen = 0
        self.allowed_by_key: collections.Counter = collections.Counter()
        # Burn telemetry (module docstring): only armed when the
        # transport can ship a report.
        self._telem = None
        self.telemetry_flush_ms = float(telemetry_flush_ms)
        self.telemetry_flushes = 0    # reports shipped
        self.telemetry_dropped = 0    # reports dropped (never blocked on)
        # lease.telemetry_rearmed: latch recoveries — a transport whose
        # telemetry went down (one failed write latches it for that
        # CONNECTION) is reconnected + re-HELLO'd at a bounded cadence;
        # each success re-arms burn reporting instead of leaving it
        # silently dead for the life of the client.
        self.telemetry_rearmed = 0
        self.telemetry_rearm_ms = float(telemetry_rearm_ms)
        self._last_rearm = 0
        self._last_flush = int(self._clock_ms())
        if telemetry and hasattr(transport, "telemetry_report"):
            from ratelimiter_tpu.observability.telemetry import (
                ClientTelemetry,
            )

            self._telem = ClientTelemetry(key_class=key_class)
        self._trace_lineage = bool(trace_lineage)

    def trace_of(self, key: str) -> int:
        """The lease's lineage trace id (0 when untraced/unknown)."""
        lease = self._leases.get(key)
        return lease.trace if lease is not None else 0

    # -- the decision surface --------------------------------------------------
    def try_acquire(self, key: str, permits: int = 1) -> bool:
        permits = max(int(permits), 1)
        telem = self._telem
        # Sampled stamping: the perf_counter pair costs ~1 µs per local
        # burn — the dominant telemetry overhead on a path whose whole
        # budget is a few µs.  Only the first record of each flush
        # interval pays it (ClientTelemetry.stamp_pending re-arms on
        # flush); every other burn records counts latency-free.
        stamp = telem is not None and telem.stamp_pending
        t0 = time.perf_counter() if stamp else 0.0
        now = int(self._clock_ms())
        lease = self._leases.get(key)
        if lease is not None and now < lease.deadline \
                and lease.remaining >= permits:
            lease.remaining -= permits
            lease.used += permits
            self.local_decisions += 1
            self.allowed_by_key[key] += permits
            if telem is not None:
                telem.record_burn(
                    self.lid, key, permits,
                    (time.perf_counter() - t0) * 1e6 if stamp else None)
                self._maybe_flush(now)
            return True
        lease = self._refresh(key, lease, now)
        if lease is not None and now < lease.deadline \
                and lease.remaining >= permits:
            lease.remaining -= permits
            lease.used += permits
            self.allowed_by_key[key] += permits
            if telem is not None:
                # The first burn of a fresh budget: local too (the wire
                # op charged the BUDGET, not this decision).
                telem.record_burn(
                    self.lid, key, permits,
                    (time.perf_counter() - t0) * 1e6 if stamp else None)
            return True
        if self.direct_fallback:
            self.wire_ops += 1
            allowed = bool(self._t.try_acquire(self.lid, key, permits))
            if allowed:
                self.allowed_by_key[key] += permits
            return allowed
        self.local_denies += 1
        if telem is not None:
            telem.record_deny(
                self.lid, key,
                (time.perf_counter() - t0) * 1e6 if stamp else None)
            self._maybe_flush(now)
        return False

    def try_acquire_many(self, keys, permits=None) -> list:
        """Batched decision surface: burn locally where live leases
        cover, then coalesce EVERY fallback decision of the flush into
        columnar batch frames (transport ``acquire_block``, wire v5 —
        one frame per chunk instead of one frame per request).
        Decisions are positionally identical to calling
        :meth:`try_acquire` per key; only the wire framing changes.
        Transports without ``acquire_block`` fall back per-request."""
        n = len(keys)
        perms = ([1] * n if permits is None
                 else [max(int(p), 1) for p in permits])
        out = [False] * n
        fb_i: list = []
        fb_k: list = []
        fb_p: list = []
        telem = self._telem
        now = int(self._clock_ms())
        for i, key in enumerate(keys):
            p = perms[i]
            lease = self._leases.get(key)
            hit = lease is not None and now < lease.deadline \
                and lease.remaining >= p
            if not hit:
                lease = self._refresh(key, lease, now)
            if lease is not None and now < lease.deadline \
                    and lease.remaining >= p:
                lease.remaining -= p
                lease.used += p
                if hit:
                    self.local_decisions += 1
                self.allowed_by_key[key] += p
                if telem is not None:
                    telem.record_burn(self.lid, key, p, None)
                out[i] = True
                continue
            if self.direct_fallback:
                fb_i.append(i)
                fb_k.append(key)
                fb_p.append(p)
            else:
                self.local_denies += 1
                if telem is not None:
                    telem.record_deny(self.lid, key, None)
        if telem is not None:
            self._maybe_flush(now)
        if fb_i:
            block = getattr(self._t, "acquire_block", None)
            if block is not None:
                # One columnar frame per 16-row chunk (the server's
                # default pipeline cap bounds declared rows per frame).
                self.wire_ops += -(-len(fb_k) // 16)
                allowed = block(self.lid, fb_k, permits=fb_p)
            else:
                allowed = []
                for k, p in zip(fb_k, fb_p):
                    self.wire_ops += 1
                    allowed.append(bool(self._t.try_acquire(self.lid, k, p)))
            for i, k, p, a in zip(fb_i, fb_k, fb_p, allowed):
                if a:
                    out[i] = True
                    self.allowed_by_key[k] += p
        return out

    # -- telemetry flushing ----------------------------------------------------
    def _maybe_flush(self, now: int) -> None:
        if self._telem is not None and self._telem.pending() \
                and now - self._last_flush >= self.telemetry_flush_ms:
            self._flush_telemetry(now)

    def _flush_telemetry(self, now: int) -> None:
        """Ship the accumulated report.  Drop-don't-block: a failed
        send loses that report's counts (counted in
        ``telemetry_dropped``) and never retries inline.  A transport
        whose telemetry latched down is re-armed here (reconnect +
        re-HELLO) at a bounded cadence — never more often than
        ``telemetry_rearm_ms`` — so one bad write costs at most one
        re-arm window of reports, not the client's lifetime."""
        telem = self._telem
        if telem is None or not telem.pending():
            return
        if getattr(self._t, "_telemetry_down", False) \
                and hasattr(self._t, "reconnect") \
                and now - self._last_rearm >= self.telemetry_rearm_ms:
            self._last_rearm = now
            try:
                rearmed = bool(self._t.reconnect())
            except Exception:  # noqa: BLE001 — telemetry never propagates
                rearmed = False
            if rearmed:
                self.telemetry_rearmed += 1
        self._last_flush = now
        blob = telem.encode_and_reset()
        try:
            ok = self._t.telemetry_report(blob)
        except Exception:  # noqa: BLE001 — telemetry must never propagate
            ok = False
        if ok:
            self.telemetry_flushes += 1
        else:
            self.telemetry_dropped += 1

    def _refresh(self, key: str, lease: Optional[_Local],
                 now: int) -> Optional[_Local]:
        """Renew/re-grant over the wire; None when no budget is usable
        (cooldown after a zero grant, or the server refused)."""
        if lease is not None and lease.remaining <= 0 \
                and now < lease.deny_until:
            return None  # zero-grant cooldown: no wire spam
        # Piggyback: the renew/grant below already pays a round trip;
        # a response-less TELEMETRY frame in front of it rides free.
        self._flush_telemetry(now)
        tid = lease.trace if lease is not None else 0
        if not tid and self._trace_lineage:
            from ratelimiter_tpu.observability.telemetry import (
                mint_trace_id,
            )

            tid = mint_trace_id()
        if lease is not None and (lease.used or lease.remaining):
            self.wire_ops += 1
            resp = self._t.lease_renew(self.lid, key, lease.used,
                                       self.budget, trace_id=tid)
            lease.used = 0
            if resp is None:  # revoked: re-grant against whatever serves
                self.revoked_seen += 1
                self.wire_ops += 1
                resp = self._t.lease_grant(self.lid, key, self.budget,
                                           trace_id=tid)
        else:
            self.wire_ops += 1
            resp = self._t.lease_grant(self.lid, key, self.budget,
                                       trace_id=tid)
        if resp is None:
            self._leases.pop(key, None)
            return None
        granted, ttl_ms, epoch = resp[0], resp[1], resp[2]
        if granted <= 0:
            cool = _Local(0, now, epoch, deny_until=now + max(ttl_ms, 1),
                          trace=tid)
            self._leases[key] = cool
            return None
        fresh = _Local(granted, now + ttl_ms, epoch, trace=tid)
        self._leases[key] = fresh
        return fresh

    # -- lifecycle -------------------------------------------------------------
    def release_all(self) -> None:
        """Report final burns and hand every unused budget back (after
        a final telemetry flush, so the server's fleet counters
        reconcile exactly at release time)."""
        self._flush_telemetry(int(self._clock_ms()))
        for key, lease in list(self._leases.items()):
            if lease.used or lease.remaining:
                self.wire_ops += 1
                try:
                    self._t.lease_release(self.lid, key, lease.used,
                                          trace_id=lease.trace)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
        self._leases.clear()

    def drop(self) -> dict:
        """Simulate a client crash (the chaos drill's kill): abandon
        every lease WITHOUT releasing — returns what was outstanding so
        the drill can assert the over-admission bound."""
        out = {k: {"remaining": v.remaining, "used": v.used}
               for k, v in self._leases.items()}
        self._leases.clear()
        return out

    close = release_all
