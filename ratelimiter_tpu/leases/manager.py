"""Server side of token leases: grant / renew / release / revoke.

The manager bridges the host lease table (leases/table.py) and the
storage's atomic ``lease_reserve``/``lease_credit`` surface
(storage/tpu.py -> ops/lease.py), and owns every policy decision:

- **Grant**: charge up to ``budget`` permits for a key in one device
  reserve.  The kernel bounds the grant by the remaining-window budget
  (sliding window) / current tokens (token bucket), so over-admission
  when a leased client dies is bounded by construction — the same
  per-key "one extra max_permits per window, worst case" bound
  ``storage/degraded.py`` documents.  A key that is ALREADY leased is
  refused (granted 0): one burner per key keeps the bound per-key; the
  second client stays on the per-decision path (the device keeps
  arbitrating contended keys — the lease design goal).
- **TTL**: ``min(ttl_ms, remaining window)`` for the sliding window —
  the charge ages out when the window rolls, so the budget must not
  outlive it; plain ``ttl_ms`` for the token bucket (its charge never
  expires, only refills around it).
- **Renew**: the client reports ``used`` burns; the manager credits the
  unused remainder back to the device and reserves a fresh budget in
  the same call — renewals ride the normal decision path, one wire
  frame per budget instead of one per decision.
- **Fence epochs**: every lease is stamped with the storage's fence
  epoch at grant time.  A renewal whose lease predates the current
  epoch is REVOKED, not honored — a failover promoted a replacement in
  between, and crediting/charging across that boundary would corrupt
  whichever side survived.  The client re-grants against the (possibly
  new) serving backend.  ``FencedError`` from the storage forces the
  same revocation.  Burns reported on a revoked or expired lease are
  counted into ``ratelimiter.lease.over_admission`` — a conservative
  upper bound on permits admitted locally that the serving backend may
  never have seen charged.

Metrics (``ratelimiter.lease.*``): granted / renewed / revoked /
expired counters, ``local_decisions`` (client-reported burns —
decisions that cost ZERO wire frames at decision time), ``over_
admission`` (permits, see above), and an ``outstanding`` gauge.

``record_ops=True`` keeps a replayable log of every reserve/credit with
its device stamp; the chaos drill (storage/chaos.py:
lease_failover_drill) replays it into ``semantics/oracle.py`` and
asserts the device state is bit-identical once renewals drain.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, NamedTuple, Optional, Tuple

from ratelimiter_tpu.leases.table import Lease, LeaseTable
from ratelimiter_tpu.storage.errors import FencedError, StorageException
from ratelimiter_tpu.utils.logging import get_logger

log = get_logger("leases.manager")


def _wall_ms() -> int:
    return time.time_ns() // 1_000_000


class LeaseGrant(NamedTuple):
    """What a grant/renew answers: ``granted == 0`` means the key stays
    on the per-decision path for ``ttl_ms`` (retry hint)."""

    granted: int
    ttl_ms: int
    epoch: int


class LeaseManager:
    """Grants, renews, and revokes per-key permit budgets."""

    def __init__(self, storage, *,
                 default_budget: int = 64,
                 max_budget: int = 1024,
                 ttl_ms: float = 2000.0,
                 deny_ttl_ms: float = 25.0,
                 max_leases: int = 65536,
                 clock_ms=None,
                 registry=None,
                 recorder=None,
                 record_ops: bool = False,
                 storm_threshold: int = 8,
                 storm_window_ms: float = 2000.0,
                 max_concurrent: int = 0,
                 max_bulk_budget: int = 0):
        self.storage = storage
        self.default_budget = max(int(default_budget), 1)
        self.max_budget = max(int(max_budget), 1)
        # Aggregate cap for BULK leases (edge aggregators, ARCHITECTURE
        # §14b) — bulk budgets cover many subleased clients, so they may
        # legitimately exceed the per-client max_budget (and the old
        # 65535 wire cap; wire v6 carries them full-width).  0 means
        # "no separate cap": bulk grants clamp like ordinary ones.
        self.max_bulk_budget = max(int(max_bulk_budget), 0)
        self.ttl_ms = float(ttl_ms)
        self.deny_ttl_ms = max(float(deny_ttl_ms), 1.0)
        # TTL accounting rides the table's forward-clamped expiry clock:
        # one observed wall step advances expiry time by at most a few
        # TTLs, so an injected forward clock jump (chaos ``clock_jump``,
        # a bad NTP slew) degrades into a handful of clamped ticks
        # instead of mass-expiring every live lease at once.
        self.table = LeaseTable(
            max_leases=max_leases,
            max_forward_jump_ms=max(10_000, 4 * int(self.ttl_ms)))
        self._clock_ms = (clock_ms
                          or getattr(storage, "_clock_ms", None)
                          or _wall_ms)
        self._lock = threading.RLock()
        self._sweep_tick = 0
        self.ops: List[Tuple] = []   # replay log (record_ops)
        self._record = bool(record_ops)
        # Revocation-storm coalescing: N fence-driven revocations inside
        # the window read as ONE flight event with a tally — after a
        # failover, every outstanding lease revokes at its next renewal,
        # and a post-mortem needs "storm of 412" not 412 ring entries.
        self.storm_threshold = max(int(storm_threshold), 1)
        self.storm_window_ms = float(storm_window_ms)
        self._revoke_times: collections.deque = collections.deque(
            maxlen=max(self.storm_threshold, 64))
        self.revocation_storms = 0
        # Trace lineage ring (observability/telemetry.py), discovered on
        # the serving storage (the router passes through to the primary).
        self._lineage = getattr(storage, "lineage", None)
        if recorder is not None:
            self._recorder = recorder
        else:
            from ratelimiter_tpu.observability import flight_recorder

            self._recorder = flight_recorder()
        if registry is not None:
            mk = registry.counter
            self._m_granted = mk(
                "ratelimiter.lease.granted",
                "Leases granted (fresh per-key budgets charged on device)")
            self._m_renewed = mk(
                "ratelimiter.lease.renewed",
                "Lease renewals served (unused credited, budget re-charged)")
            self._m_revoked = mk(
                "ratelimiter.lease.revoked",
                "Leases revoked (fence-epoch advance, FencedError, or "
                "unknown lease at renewal)")
            self._m_expired = mk(
                "ratelimiter.lease.expired",
                "Leases dropped by TTL expiry")
            self._m_local = mk(
                "ratelimiter.lease.local_decisions",
                "Client-reported decisions burned locally against a lease "
                "(zero wire frames at decision time)")
            self._m_over = mk(
                "ratelimiter.lease.over_admission",
                "Permits burned against revoked/expired leases — "
                "conservative upper bound on admission the serving "
                "backend may not have seen charged")
            self._m_outstanding = registry.gauge(
                "ratelimiter.lease.outstanding",
                "Leases currently outstanding")
        else:
            self._m_granted = self._m_renewed = self._m_revoked = None
            self._m_expired = self._m_local = self._m_over = None
            self._m_outstanding = None
        # Plain counters (drills read them without a registry).
        self.granted_total = 0
        self.renewed_total = 0
        self.revoked_total = 0
        self.expired_total = 0
        self.local_decisions_total = 0
        self.over_admission_total = 0
        # Concurrency slots (control/, ARCHITECTURE §15): per-lid caps
        # on the tenant's aggregate outstanding lease budget — lease
        # grants ARE the slots, so max_concurrent is enforced by the
        # accounting this manager already keeps, no new device surface.
        self._concurrency: dict = {}
        # Fleet-wide default cap (ratelimiter.control.max_concurrent;
        # 0/None = unbounded); per-lid set_concurrency_cap overrides.
        self.default_concurrency = (int(max_concurrent)
                                    if max_concurrent else None)
        self.concurrency_refused_total = 0
        # Policy-generation rebases: renewals whose budget predated a
        # live policy update and was re-reserved under the new rate.
        self.policy_rebased_total = 0

    # -- small helpers ---------------------------------------------------------
    def _algo_cfg(self, lid: int):
        entry = self.storage._configs.get(int(lid))
        if entry is None:
            raise KeyError(f"no limiter registered under lid={lid}")
        return entry  # (algo, config)

    def _epoch(self) -> int:
        fn = getattr(self.storage, "fence_info", None)
        if fn is None:
            return 0
        try:
            return int(fn()["epoch"])
        except Exception:  # noqa: BLE001 — epoch is best-effort metadata
            return 0

    def _scope_epoch(self, lid: int, key: str) -> int:
        """The revocation epoch for THIS key (ARCHITECTURE §14b): a
        storage exposing ``lease_scope_epoch`` scopes fence bumps to the
        shard the key routes to, so a single-shard promotion revokes
        only that shard's leases.  Storages without the surface keep the
        old global-epoch semantics."""
        fn = getattr(self.storage, "lease_scope_epoch", None)
        if fn is None:
            return self._epoch()
        try:
            return int(fn(int(lid), key))
        except Exception:  # noqa: BLE001 — epoch is best-effort metadata
            return self._epoch()

    def _budget_cap(self, bulk: bool) -> int:
        if bulk and self.max_bulk_budget:
            return max(self.max_bulk_budget, self.max_budget)
        return self.max_budget

    def _policy_gen(self, lid: int) -> int:
        """The lid's current policy-row generation (0 when the storage
        has no policy table — e.g. a bare memory backend)."""
        table = getattr(self.storage, "table", None)
        if table is None or not hasattr(table, "row_generation"):
            return 0
        try:
            return int(table.row_generation(int(lid)))
        except Exception:  # noqa: BLE001 — generation is metadata
            return 0

    # -- concurrency slots (control/) ------------------------------------------
    def set_concurrency_cap(self, lid: int, max_concurrent) -> None:
        """Bound one tenant's aggregate outstanding lease budget (lease
        grants as concurrency slots).  ``None`` lifts the cap.  A cap
        cut below the current outstanding budget does not revoke
        anything immediately — each lease shrinks (or is refused) at
        its next renewal, the same lazy convergence policy updates
        use."""
        with self._lock:
            if max_concurrent is None:
                self._concurrency.pop(int(lid), None)
            else:
                self._concurrency[int(lid)] = max(int(max_concurrent), 0)

    def concurrency_caps(self) -> dict:
        with self._lock:
            return dict(self._concurrency)

    def _slot_clamp(self, algo: str, lid: int, req: int,
                    exclude_key=None) -> int:
        """Clamp a grant/renewal request to the tenant's free slots;
        <= 0 means refuse (the key stays on the per-decision path)."""
        cap = self._concurrency.get(int(lid), self.default_concurrency)
        if cap is None:
            return req
        free = cap - self.table.outstanding_budget_for(
            algo, lid, exclude_key=exclude_key)
        return min(req, free)

    def _bump(self, meter, attr: str, n: int = 1) -> None:
        if n <= 0:
            return
        setattr(self, attr, getattr(self, attr) + n)
        if meter is not None:
            meter.add(n)

    def _gauge(self) -> None:
        if self._m_outstanding is not None:
            self._m_outstanding.set(float(self.table.outstanding()))

    def _trace(self, trace_id: int, hop: str, **fields) -> None:
        """One lineage hop under a (forced-sampled) wire trace id."""
        lin = self._lineage
        if lin is not None and trace_id:
            lin.force(trace_id)
            lin.record(trace_id, hop, **fields)

    def _note_fence_revocation(self, now: int, key: str,
                               reason: str) -> None:
        """Record a fence-driven revocation and coalesce bursts: the
        Nth revocation inside the window lands ONE ``lease.
        revocation_storm`` flight event (itself coalesced), so the ring
        shows the fence-epoch bump's blast radius as a tally."""
        self._revoke_times.append(now)
        recent = sum(1 for t in self._revoke_times
                     if now - t <= self.storm_window_ms)
        if recent >= self.storm_threshold:
            self.revocation_storms += 1
            self._recorder.record(
                "lease.revocation_storm",
                coalesce_ms=self.storm_window_ms,
                n_revocations=recent, epoch=self._epoch(), key=key,
                reason=reason)

    def _maybe_sweep(self, now: int) -> None:
        self._sweep_tick += 1
        if self._sweep_tick % 256:
            return
        for lease in self.table.sweep_expired(now):
            self._bump(self._m_expired, "expired_total")
            self._recorder.record("lease.expired", coalesce_ms=1000.0,
                                  key=lease.key)

    def _credit(self, lease: Lease, unused: int) -> None:
        """Best-effort device credit of unused budget (kernel drops a
        rolled-window credit safely)."""
        if unused <= 0:
            return
        out = self.storage.lease_credit(
            lease.algo, lease.lid, lease.key, int(unused), lease.ws)
        # stamp == 0 marks a fail-closed router answer (no device op ran)
        # — recording it would corrupt an oracle replay.
        if self._record and out.get("stamp", 0) > 0:
            self.ops.append(("credit", lease.algo, lease.lid, lease.key,
                             int(unused), lease.ws, out["stamp"]))

    # -- the lease protocol ----------------------------------------------------
    def grant(self, lid: int, key: str, requested: int = 0,
              trace_id: int = 0, bulk: bool = False) -> LeaseGrant:
        """Grant a fresh per-key budget.  ``granted == 0`` (with a retry
        hint in ``ttl_ms``) when the key is already leased, the budget
        is exhausted, the table is full, or the storage is fenced.
        ``trace_id`` threads the grant into the lineage ring.  ``bulk``
        marks an edge-aggregator portfolio lease: the budget is an
        aggregate and clamps against ``max_bulk_budget``."""
        with self._lock:
            algo, cfg = self._algo_cfg(lid)
            now = self.table.clamp_forward(int(self._clock_ms()))
            self._maybe_sweep(now)
            self._trace(trace_id, "lease.grant", key=key,
                        requested=int(requested))
            scope_epoch = self._scope_epoch(lid, key)
            existing = self.table.get(algo, lid, key)
            if existing is not None:
                if existing.expired(now):
                    self.table.pop(algo, lid, key)
                    self._bump(self._m_expired, "expired_total")
                    self._recorder.record("lease.expired",
                                          coalesce_ms=1000.0, key=key)
                elif scope_epoch > existing.epoch:
                    # The holder's lease predates a fence bump on this
                    # key's shard: its charge lives (at best) on the
                    # replaced backend.  Revoke it NOW so a re-granted
                    # aggregator takes the key over immediately instead
                    # of waiting out the dead holder's TTL; the dead
                    # holder's eventual renewal lands "unknown_lease"
                    # and its burns count into over_admission as usual.
                    self.table.pop(algo, lid, key)
                    self._bump(self._m_revoked, "revoked_total")
                    self._recorder.record("lease.revoked", key=key,
                                          reason="fence_epoch_grant",
                                          coalesce_ms=200.0)
                    self._note_fence_revocation(now, key,
                                                "fence_epoch_grant")
                else:
                    # One burner per key: the second client stays on the
                    # per-decision path (the device arbitrates contended
                    # keys).
                    return LeaseGrant(0, int(self.deny_ttl_ms),
                                      existing.epoch)
            req = int(requested) or self.default_budget
            req = max(1, min(req, self._budget_cap(bulk),
                             cfg.max_permits))
            req = self._slot_clamp(algo, lid, req)
            if req <= 0:
                # Concurrency slots exhausted: the tenant's outstanding
                # lease budget is at max_concurrent — refuse, the key
                # stays on the per-decision path until slots free up.
                self.concurrency_refused_total += 1
                return LeaseGrant(0, int(self.deny_ttl_ms), self._epoch())
            self._trace(trace_id, "batcher", op="flush+reserve")
            try:
                out = self.storage.lease_reserve(algo, lid, key, req)
            except FencedError:
                self._bump(self._m_revoked, "revoked_total")
                return LeaseGrant(0, int(self.deny_ttl_ms), self._epoch())
            except StorageException:
                return LeaseGrant(0, int(self.deny_ttl_ms), self._epoch())
            if self._record and out.get("stamp", 0) > 0:
                self.ops.append(("reserve", algo, lid, key, req,
                                 out["granted"], out["ws"], out["stamp"]))
            granted = int(out["granted"])
            self._trace(trace_id, "shard", path="lease_reserve",
                        granted=granted, stamp=int(out.get("stamp", 0)))
            epoch = self._scope_epoch(lid, key)
            if granted <= 0:
                return LeaseGrant(0, int(self.deny_ttl_ms), epoch)
            ttl = self._ttl_for(algo, cfg, out["stamp"])
            lease = Lease(algo=algo, lid=int(lid), key=key, budget=granted,
                          ws=int(out["ws"]), epoch=epoch,
                          deadline_ms=now + ttl, granted_total=granted,
                          policy_gen=self._policy_gen(lid), bulk=bulk)
            if not self.table.put(lease):
                # Table full: undo the charge and refuse — bounded state.
                self._credit(lease, granted)
                return LeaseGrant(0, int(self.deny_ttl_ms), epoch)
            self._bump(self._m_granted, "granted_total")
            self._recorder.record("lease.granted", coalesce_ms=1000.0,
                                  key=key, granted=granted)
            self._trace(trace_id, "resolve", granted=granted, ttl_ms=ttl,
                        epoch=epoch)
            self._gauge()
            return LeaseGrant(granted, ttl, epoch)

    def renew(self, lid: int, key: str, used: int,
              requested: int = 0,
              trace_id: int = 0,
              epoch: Optional[int] = None) -> Optional[LeaseGrant]:
        """Renew: report ``used`` burns, credit the unused remainder,
        charge a fresh budget.  Returns ``None`` when the lease was
        REVOKED (fence epoch advanced, storage fenced, or unknown
        lease) — the client must re-grant before burning again.

        ``epoch`` (when given) names the lease INSTANCE the report
        belongs to: an edge aggregator flushing burns for a revoked
        bulk lease may race a successor grant on the same key, and
        without the check those burns would fold into the successor's
        accounting.  A report whose epoch predates the live lease's is
        counted straight into ``over_admission`` — the dead instance's
        burns — and the live lease is left untouched.  The check is
        exact for fence-driven revocations (the epoch always advanced);
        a TTL-expired instance whose successor carries the SAME epoch
        folds into the successor — conservative (the successor's next
        renewal credits less, never more)."""
        with self._lock:
            algo, cfg = self._algo_cfg(lid)
            now = self.table.clamp_forward(int(self._clock_ms()))
            used = max(int(used), 0)
            self._bump(self._m_local, "local_decisions_total", used)
            # The client leg of the lineage: burns since the last wire
            # op ran client-side with ZERO frames — this hop is where
            # they become visible server-side.
            self._trace(trace_id, "client", local_burns=used, key=key)
            self._trace(trace_id, "lease.renew", key=key)
            lease = self.table.get(algo, lid, key)
            if lease is None:
                # Swept/never granted: those burns ran against a lease
                # this table no longer vouches for.
                self._bump(self._m_over, "over_admission_total", used)
                self._bump(self._m_revoked, "revoked_total")
                self._recorder.record("lease.revoked", key=key,
                                      reason="unknown_lease",
                                      coalesce_ms=200.0)
                return None
            if epoch is not None and int(epoch) != lease.epoch:
                # Stale lease-instance report (ARCHITECTURE §14b): the
                # reporter's lease died and the key was already
                # re-granted.  The burns ran against the DEAD
                # instance's (unreclaimed) reservation, so they are
                # over-admission — never the successor's usage.
                self._bump(self._m_over, "over_admission_total", used)
                self._recorder.record("lease.revoked", key=key,
                                      reason="stale_epoch_report",
                                      coalesce_ms=200.0)
                return None
            lease.used_total += used
            cur_epoch = self._scope_epoch(lid, key)
            if cur_epoch > lease.epoch:
                # Failover promoted a replacement since the grant: the
                # charge lives (at best) on the old backend, so neither
                # credit nor honor — revoke, client re-grants against
                # whatever serves now.  Burns since the last report are
                # the (bounded) over-admission window.
                self.table.pop(algo, lid, key)
                self._bump(self._m_revoked, "revoked_total")
                self._bump(self._m_over, "over_admission_total", used)
                self._recorder.record("lease.revoked", key=key,
                                      reason="fence_epoch",
                                      coalesce_ms=200.0)
                self._note_fence_revocation(now, key, "fence_epoch")
                self._gauge()
                return None
            unused = max(lease.budget - used, 0)
            if lease.expired(now):
                self.table.pop(algo, lid, key)
                self._bump(self._m_expired, "expired_total")
                self._bump(self._m_over, "over_admission_total", used)
                self._recorder.record("lease.expired", coalesce_ms=1000.0,
                                      key=key)
                try:
                    self._credit(lease, unused)
                except (FencedError, StorageException):
                    pass
                self._gauge()
                return None
            req = int(requested) or lease.budget
            req = max(1, min(req, self._budget_cap(lease.bulk),
                             cfg.max_permits))
            cur_gen = self._policy_gen(lid)
            if cur_gen > lease.policy_gen:
                # A live policy update landed since the last charge: the
                # re-reserve below runs against the NEW device rate and
                # the clamp above already used the new config — count
                # the rebase so drills can assert the budget turnover.
                self.policy_rebased_total += 1
            req = self._slot_clamp(algo, lid, req, exclude_key=key)
            if req <= 0:
                # The tenant's concurrency cap shrank below this lease:
                # credit the unused budget back and revoke to the
                # per-decision path (the lazy convergence contract).
                self.concurrency_refused_total += 1
                self.table.pop(algo, lid, key)
                try:
                    self._credit(lease, unused)
                except (FencedError, StorageException):
                    pass
                self._gauge()
                return LeaseGrant(0, int(self.deny_ttl_ms), cur_epoch)
            self._trace(trace_id, "batcher", op="credit+reserve")
            try:
                self._credit(lease, unused)
                out = self.storage.lease_reserve(algo, lid, key, req)
            except FencedError:
                self.table.pop(algo, lid, key)
                self._bump(self._m_revoked, "revoked_total")
                self._recorder.record("lease.revoked", key=key,
                                      reason="fenced", coalesce_ms=200.0)
                self._note_fence_revocation(now, key, "fenced")
                self._gauge()
                return None
            except StorageException:
                self.table.pop(algo, lid, key)
                self._gauge()
                return LeaseGrant(0, int(self.deny_ttl_ms), cur_epoch)
            if self._record and out.get("stamp", 0) > 0:
                self.ops.append(("reserve", algo, lid, key, req,
                                 out["granted"], out["ws"], out["stamp"]))
            granted = int(out["granted"])
            self._trace(trace_id, "shard", path="lease_reserve",
                        granted=granted, stamp=int(out.get("stamp", 0)))
            if granted <= 0:
                self.table.pop(algo, lid, key)
                self._gauge()
                return LeaseGrant(0, int(self.deny_ttl_ms), cur_epoch)
            ttl = self._ttl_for(algo, cfg, out["stamp"])
            lease.budget = granted
            lease.ws = int(out["ws"])
            lease.policy_gen = cur_gen
            lease.epoch = self._scope_epoch(lid, key)
            lease.deadline_ms = now + ttl
            lease.granted_total += granted
            lease.renewals += 1
            self._bump(self._m_renewed, "renewed_total")
            self._trace(trace_id, "resolve", granted=granted, ttl_ms=ttl,
                        epoch=lease.epoch)
            return LeaseGrant(granted, ttl, lease.epoch)

    def release(self, lid: int, key: str, used: int,
                trace_id: int = 0) -> None:
        """Close a lease: report final burns and credit the remainder."""
        with self._lock:
            algo, _cfg = self._algo_cfg(lid)
            used = max(int(used), 0)
            self._bump(self._m_local, "local_decisions_total", used)
            self._trace(trace_id, "client", local_burns=used, key=key)
            self._trace(trace_id, "lease.release", key=key)
            lease = self.table.pop(algo, lid, key)
            if lease is None:
                return
            lease.used_total += used
            self._recorder.record("lease.released", coalesce_ms=1000.0,
                                  key=key)
            if self._scope_epoch(lid, key) > lease.epoch:
                self._bump(self._m_over, "over_admission_total", used)
                self._gauge()
                return
            try:
                self._credit(lease, max(lease.budget - used, 0))
            except (FencedError, StorageException):
                pass
            self._gauge()

    def telemetry_report(self, blob: bytes) -> int:
        """Fold one client burn report into the storage's fleet
        telemetry plane (the in-process leg of the TELEMETRY op —
        ``DirectTransport`` calls this).  Returns the record count, -1
        on a malformed blob, or -1 when the storage carries no plane."""
        plane = getattr(self.storage, "telemetry", None)
        if plane is None:
            return -1
        return plane.fold(blob)

    def _ttl_for(self, algo: str, cfg, stamp: int) -> int:
        """Sliding window: the charge ages out when the window rolls, so
        the lease must not outlive it.  Token bucket: plain ttl_ms."""
        if algo == "sw":
            remaining = cfg.window_ms - (int(stamp) % cfg.window_ms)
            return max(1, min(int(self.ttl_ms), int(remaining)))
        return max(1, int(self.ttl_ms))

    # -- introspection ---------------------------------------------------------
    def status(self) -> dict:
        return {
            "outstanding": self.table.outstanding(),
            "outstanding_budget": self.table.outstanding_budget(),
            "granted": self.granted_total,
            "renewed": self.renewed_total,
            "revoked": self.revoked_total,
            "expired": self.expired_total,
            "local_decisions": self.local_decisions_total,
            "over_admission": self.over_admission_total,
            "revocation_storms": self.revocation_storms,
            "concurrency_refused": self.concurrency_refused_total,
            "policy_rebased": self.policy_rebased_total,
            "concurrency_caps": self.concurrency_caps(),
        }
