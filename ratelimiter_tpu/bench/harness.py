"""Benchmark harness (C15 parity).

The reference's harness drives N threads of per-request tryAcquire against
live Redis and reports throughput + latency percentiles
(RateLimiterBenchmark scenarios; README publishes 80,192 req/s, p99 578 us
on an M1).  This harness reproduces those scenarios against this framework's
backends and adds the BASELINE.json driver scenarios (1M-key Zipf token
bucket, 10M-key uniform sliding window, 100K-tenant mix, burst
batch-acquire).

Three measurement modes, reported separately and honestly:

- ``engine``     — device-step rate with pre-assigned slots: the kernel's
                   decision throughput (sort + solve + gather/scatter).
- ``end_to_end`` — string keys in, decisions out, through the slot index and
                   storage layer (the number comparable to the reference's
                   throughput figures).
- ``threaded``   — T threads of single tryAcquire through the micro-batcher;
                   per-request wall latencies incl. queue wait -> p50/p95/p99
                   (the number comparable to the reference's latency figures).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List

import numpy as np

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.engine.engine import DeviceEngine
from ratelimiter_tpu.engine.state import LimiterTable


def _pcts(lat_us: np.ndarray) -> Dict[str, float]:
    lat = np.sort(lat_us)
    def pct(p):
        return float(lat[min(len(lat) - 1, int(p * len(lat)))])
    return {
        "mean_us": float(lat.mean()),
        "p50_us": pct(0.50),
        "p95_us": pct(0.95),
        "p99_us": pct(0.99),
    }


# ---------------------------------------------------------------------------
# Key-stream generators (BASELINE.json configs)
# ---------------------------------------------------------------------------

def uniform_stream(rng, num_keys: int, n: int) -> np.ndarray:
    return rng.integers(0, num_keys, size=n)


def zipf_stream(rng, num_keys: int, n: int, a: float = 1.1) -> np.ndarray:
    # Bounded Zipf via inverse-CDF over ranks (np.random.zipf is unbounded).
    ranks = np.arange(1, num_keys + 1, dtype=np.float64)
    probs = ranks ** (-a)
    probs /= probs.sum()
    return rng.choice(num_keys, size=n, p=probs)


# ---------------------------------------------------------------------------
# Engine-level throughput (pre-assigned slots)
# ---------------------------------------------------------------------------

def bench_engine(
    engine,
    algo: str,
    lid: int,
    slot_stream: np.ndarray,   # precomputed slots per request
    permits: np.ndarray,
    batch: int,
    warmup_batches: int = 3,
    now0: int = 1_753_000_000_000,
) -> Dict:
    """Feed `slot_stream` through the engine in fixed batches; decisions/sec."""
    fn = engine.sw_acquire if algo == "sw" else engine.tb_acquire
    n = (len(slot_stream) // batch) * batch
    slots = slot_stream[:n].reshape(-1, batch)
    perms = permits[:n].reshape(-1, batch)
    lids = np.full(batch, lid, dtype=np.int32)

    for i in range(min(warmup_batches, len(slots))):
        fn(slots[i], lids, perms[i], now0 + i)
    engine.block_until_ready()

    lat = []
    t_all = time.perf_counter()
    for i in range(len(slots)):
        t0 = time.perf_counter()
        fn(slots[i], lids, perms[i], now0 + 10 + i)
        lat.append((time.perf_counter() - t0) * 1e6)
    wall = time.perf_counter() - t_all
    decisions = len(slots) * batch
    return {
        "mode": "engine",
        "decisions": decisions,
        "batch": batch,
        "wall_s": wall,
        "decisions_per_sec": decisions / wall,
        "batch_latency": _pcts(np.asarray(lat)),
    }


# ---------------------------------------------------------------------------
# End-to-end (string keys through storage + slot index)
# ---------------------------------------------------------------------------

def bench_end_to_end(
    limiter,
    key_stream: List[str],
    permits: np.ndarray,
    batch: int,
) -> Dict:
    n = (len(key_stream) // batch) * batch
    lat = []
    t_all = time.perf_counter()
    for i in range(0, n, batch):
        t0 = time.perf_counter()
        limiter.try_acquire_many(key_stream[i:i + batch], permits[i:i + batch])
        lat.append((time.perf_counter() - t0) * 1e6)
    wall = time.perf_counter() - t_all
    return {
        "mode": "end_to_end",
        "decisions": n,
        "batch": batch,
        "wall_s": wall,
        "decisions_per_sec": n / wall,
        "batch_latency": _pcts(np.asarray(lat)),
    }


# ---------------------------------------------------------------------------
# Threaded single-request latency (through the micro-batcher)
# ---------------------------------------------------------------------------

def bench_threaded(
    limiter,
    keys_per_thread: Callable[[int], List[str]],
    n_threads: int,
    requests_per_thread: int,
) -> Dict:
    lat = np.zeros((n_threads, requests_per_thread))
    barrier = threading.Barrier(n_threads)

    def worker(t):
        my_keys = keys_per_thread(t)
        barrier.wait()
        for i in range(requests_per_thread):
            t0 = time.perf_counter()
            limiter.try_acquire(my_keys[i % len(my_keys)])
            lat[t, i] = (time.perf_counter() - t0) * 1e6

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    t_all = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_all
    total = n_threads * requests_per_thread
    return {
        "mode": "threaded",
        "threads": n_threads,
        "decisions": total,
        "wall_s": wall,
        "decisions_per_sec": total / wall,
        "request_latency": _pcts(lat.reshape(-1)),
    }


# ---------------------------------------------------------------------------
# Scenario helpers
# ---------------------------------------------------------------------------

def make_engine(num_slots: int, configs: List[RateLimitConfig]):
    table = LimiterTable()
    lids = [table.register(c) for c in configs]
    return DeviceEngine(num_slots=num_slots, table=table), lids
