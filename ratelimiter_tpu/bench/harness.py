"""Benchmark harness (C15 parity).

The reference's harness drives N threads of per-request tryAcquire against
live Redis and reports throughput + latency percentiles
(RateLimiterBenchmark scenarios; README publishes 80,192 req/s, p99 578 us
on an M1).  This harness reproduces those scenarios against this framework's
backends and adds the BASELINE.json driver scenarios (1M-key Zipf token
bucket, 10M-key uniform sliding window, 100K-tenant mix, burst
batch-acquire).

Measurement modes, reported separately and honestly:

- ``end_to_end`` — string keys in, decisions out, through the slot index and
                   storage layer (the number comparable to the reference's
                   throughput figures).
- ``threaded``   — T threads of single tryAcquire through the micro-batcher;
                   per-request wall latencies incl. queue wait -> p50/p95/p99
                   (the number comparable to the reference's latency figures).
- ``stream_ids`` — (driven from bench.py) whole-stream integer-key decisions
                   through the pipelined scan-bits path — the hyperscale
                   throughput number.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List

import numpy as np


def _pcts(lat_us: np.ndarray) -> Dict[str, float]:
    lat = np.sort(lat_us)
    def pct(p):
        return float(lat[min(len(lat) - 1, int(p * len(lat)))])
    return {
        # n_samples makes degenerate upper percentiles visible (p95 == p99
        # means the tail is one sample, not a plateau).
        "n_samples": int(len(lat)),
        "mean_us": float(lat.mean()),
        "p50_us": pct(0.50),
        "p95_us": pct(0.95),
        "p99_us": pct(0.99),
    }


# ---------------------------------------------------------------------------
# Key-stream generators (BASELINE.json configs)
# ---------------------------------------------------------------------------

def uniform_stream(rng, num_keys: int, n: int) -> np.ndarray:
    return rng.integers(0, num_keys, size=n)


def zipf_stream(rng, num_keys: int, n: int, a: float = 1.1) -> np.ndarray:
    # Bounded Zipf via inverse-CDF over ranks (np.random.zipf is unbounded).
    ranks = np.arange(1, num_keys + 1, dtype=np.float64)
    probs = ranks ** (-a)
    probs /= probs.sum()
    return rng.choice(num_keys, size=n, p=probs)


# ---------------------------------------------------------------------------
# End-to-end (string keys through storage + slot index)
# ---------------------------------------------------------------------------

def bench_end_to_end(
    limiter,
    key_stream: List[str],
    permits: np.ndarray,
    batch: int,
) -> Dict:
    n = (len(key_stream) // batch) * batch
    # Warm the jit cache at the exact batch shape (compile excluded).
    limiter.try_acquire_many(key_stream[:batch], permits[:batch])
    lat = []
    t_all = time.perf_counter()
    for i in range(0, n, batch):
        t0 = time.perf_counter()
        limiter.try_acquire_many(key_stream[i:i + batch], permits[i:i + batch])
        lat.append((time.perf_counter() - t0) * 1e6)
    wall = time.perf_counter() - t_all
    return {
        "mode": "end_to_end",
        "decisions": n,
        "batch": batch,
        "wall_s": wall,
        "decisions_per_sec": n / wall,
        "batch_latency": _pcts(np.asarray(lat)),
    }


def bench_end_to_end_stream(
    limiter,
    key_stream: List[str],
    permits: np.ndarray | None,
    latency_batch: int = 1 << 14,
    latency_batches: int = 8,
    storage=None,
    reps: int = 3,
) -> Dict:
    """End-to-end string keys via the pipelined stream path.

    Throughput: ``reps`` timed ``try_acquire_many`` passes over the whole
    stream (above the limiter's stream threshold it routes through
    ``storage.acquire_stream_strs``, overlapping host packing/hashing
    with device fetches); the median pass is the robust figure.  With
    ``storage`` given, each pass records the per-chunk phase lanes
    (pack_s / walk_s / fetch_s — VERDICT r4 #7) via stream_stats.
    Latency: a handful of synchronous ``latency_batch``-sized calls,
    reported separately — they measure the non-pipelined round trip.
    """
    n = len(key_stream)
    # Warm compile shapes (stream super-batch, tail, latency batch) with
    # full untimed passes — buckets drain but throughput is unaffected.
    # Warmup repeats until the storage's chunk-plan map stops changing
    # shape (election -> new chunk shapes -> fresh XLA compiles), so
    # timed passes never meet a fresh shape (same discipline as
    # bench.py run_stream).
    def plan_sig():
        if storage is None:
            return None
        return {k: (v["kind"], v.get("schedule", v.get("chunk")))
                for k, v in storage._chunk_plans.items()}

    for i in range(4):
        sig = plan_sig()
        limiter.try_acquire_many(key_stream, permits)
        if i > 0 and plan_sig() == sig:
            break
    limiter.try_acquire_many(key_stream[:latency_batch],
                             None if permits is None
                             else permits[:latency_batch])
    passes = []
    for _ in range(max(reps, 1)):
        stats = None
        if storage is not None:
            storage.stream_stats = stats = []
        t0 = time.perf_counter()
        limiter.try_acquire_many(key_stream, permits)
        wall = time.perf_counter() - t0
        if storage is not None:
            storage.stream_stats = None
        passes.append({"wall_s": round(wall, 4),
                       "decisions_per_sec": round(n / wall, 1),
                       "stats": stats})
    lat = []
    for i in range(latency_batches):
        j = (i * latency_batch) % max(n - latency_batch, 1)
        t1 = time.perf_counter()
        limiter.try_acquire_many(
            key_stream[j:j + latency_batch],
            None if permits is None else permits[j:j + latency_batch])
        lat.append((time.perf_counter() - t1) * 1e6)
    total_wall = sum(p["wall_s"] for p in passes)
    rates = sorted(p["decisions_per_sec"] for p in passes)
    return {
        "mode": "end_to_end_stream",
        "decisions": n * len(passes),
        "wall_s": round(total_wall, 4),
        "decisions_per_sec": n * len(passes) / total_wall,
        "median_pass_decisions_per_sec": rates[len(rates) // 2],
        "best_pass_decisions_per_sec": rates[-1],
        "passes": passes,
        "batch": latency_batch,
        "batch_latency": _pcts(np.asarray(lat)),
    }


# ---------------------------------------------------------------------------
# Threaded single-request latency (through the micro-batcher)
# ---------------------------------------------------------------------------

def bench_threaded(
    limiter,
    keys_per_thread: Callable[[int], List[str]],
    n_threads: int,
    requests_per_thread: int,
) -> Dict:
    lat = np.zeros((n_threads, requests_per_thread))
    barrier = threading.Barrier(n_threads)

    def worker(t):
        my_keys = keys_per_thread(t)
        barrier.wait()
        for i in range(requests_per_thread):
            t0 = time.perf_counter()
            limiter.try_acquire(my_keys[i % len(my_keys)])
            lat[t, i] = (time.perf_counter() - t0) * 1e6

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    t_all = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_all
    total = n_threads * requests_per_thread
    return {
        "mode": "threaded",
        "threads": n_threads,
        "decisions": total,
        "wall_s": wall,
        "decisions_per_sec": total / wall,
        "request_latency": _pcts(lat.reshape(-1)),
    }


