"""Cross-host node process: shard primaries or standbys, runnable as
``python -m ratelimiter_tpu.replication.hostproc``.

This is the process the multi-process topology (ARCHITECTURE §10c) is
made of.  A node hosts ``--shards k`` independent shard storages (k=1
by default — the PR 14 topology unchanged).  A PRIMARY node serves
decisions over one sidecar per shard (wire protocol v4, optional token
leases), ships each shard's replication stream to its standby
(``--repl-target``, comma-separated for k>1), exposes ONE control port
multiplexing every shard (PROBE / PROBE_ALL / FENCE / LEASE / RESTORE /
SHIP / RETARGET), and runs the LEASE KEEPER per shard: when the
orchestrator's direct renewals stop arriving, the keeper fetches the
newest deposited grant from the standby's mailbox over the replication-
side link — so a primary partitioned only from the ORCHESTRATOR keeps
serving, while one partitioned from everything runs its lease down and
self-fences within one TTL.  A STANDBY node applies the replication
streams, answers the witness probe (``repl_rx_age_ms``), holds the
lease mailboxes, and serves the remote-promotion RPC — a successful
PROMOTE starts a sidecar over the now-serving storage and reports its
port for clients to re-point.

RETARGET is the fleet autopilot's re-seed primitive (ARCHITECTURE §16):
point this shard's replication stream at a NEW standby's listener —
swap the sink under the existing replicator (primary), or build one on
a promoted storage that never had one (post-promotion standby) — then
force a full re-baseline frame and ship it synchronously.  An
unpromoted standby refuses (re-seeding from a shadow would fork the
authority chain).

The process prints ONE JSON line on stdout when ready (ports, explicit
``lid_base``, ``version``, and shard count included) and exits cleanly
on stdin EOF **or SIGTERM** — the launcher (fleet/NodeManager, a drill,
an init system wrapper) owns its lifetime through the pipe, and an init
system's TERM gets the same graceful teardown (drain sidecars, release
the serving lease, exit 0).  Exit code therefore distinguishes a
graceful stop (0) from a crash-kill (signal death) — the chaos
conductor's ``kill`` vs ``stop`` actions assert on exactly that.

``storage/chaos.py`` spawns these as real OS subprocesses with
``FaultInjectingProxy`` links between them.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Callable, Dict, List, Optional


def _build_limiters(spec_json: str, shards: int) -> List[List[dict]]:
    """Parse ``--limiters``: a JSON list of limiter specs applied to
    EVERY shard, or a list of k lists for per-shard policies."""
    spec = json.loads(spec_json) if spec_json else []
    if not isinstance(spec, list):
        raise ValueError("--limiters must be a JSON list")
    if spec and all(isinstance(s, list) for s in spec):
        if len(spec) != shards:
            raise ValueError(
                f"--limiters gave {len(spec)} per-shard lists for "
                f"--shards {shards}")
        return spec
    return [list(spec) for _ in range(shards)]


def _split_targets(arg: str, shards: int) -> List[str]:
    """Split a comma-separated ``host:port`` list, one per shard
    (empty string = that shard ships nowhere)."""
    if not arg:
        return [""] * shards
    targets = [t.strip() for t in arg.split(",")]
    if len(targets) == 1 and shards > 1:
        raise ValueError(
            f"--repl-target gave 1 target for --shards {shards}; pass "
            f"a comma-separated list, one per shard")
    if len(targets) != shards:
        raise ValueError(
            f"--repl-target gave {len(targets)} targets for "
            f"--shards {shards}")
    return targets


def _make_lease_manager(storage, props: Optional[dict] = None):
    from ratelimiter_tpu.leases import LeaseManager

    props = props or {}
    return LeaseManager(
        storage,
        default_budget=int(props.get("default_budget", 64)),
        max_budget=int(props.get("max_budget", 1024)),
        ttl_ms=float(props.get("ttl_ms", 2000.0)),
        deny_ttl_ms=float(props.get("deny_ttl_ms", 25.0)),
    )


class LeaseKeeper:
    """Primary-side relay fetcher: while a serving lease is installed,
    poll the standby's mailbox and apply any deposit that would EXTEND
    the local deadline (a stale deposit can only shorten it and is
    skipped — the lease still expires on the original schedule).

    Age accounting makes the relay skew-free: the deposit's ``age_ms``
    is measured on the STANDBY's clock between orchestrator deposit and
    our fetch, so the applied TTL is ``ttl - age - slack`` — always at
    or under what the orchestrator believes it granted, never past it.

    ``shard`` addresses the mailbox on a multiplexed standby control
    port (None keeps the bare op for raw single-shard handler tables).
    """

    def __init__(self, storage, standby_ctl, poll_ms: float = 100.0,
                 slack_ms: float = 25.0, shard: Optional[int] = None):
        self.storage = storage
        self.ctl = standby_ctl
        self.poll_ms = float(poll_ms)
        self.slack_ms = float(slack_ms)
        self.shard = shard
        self.fetches = 0
        self.applied = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="lease-keeper", daemon=True)

    def start(self) -> "LeaseKeeper":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_ms / 1000.0):
            try:
                self._poll_once()
            except Exception:  # noqa: BLE001 — the keeper never dies;
                # a broken relay just lets the lease run down (by design)
                pass

    def _poll_once(self) -> None:
        info = self.storage.serving_lease_info()
        if not info["installed"]:
            return  # no lease granted yet, or already expired/fenced
        kw = {} if self.shard is None else {"shard": int(self.shard)}
        resp = self.ctl.try_call("lease_fetch", **kw)
        self.fetches += 1
        if resp is None or not resp.get("ok") or not resp.get("deposited"):
            return
        effective = (float(resp["ttl_ms"]) - float(resp["age_ms"])
                     - self.slack_ms)
        if effective <= info["ttl_remaining_ms"]:
            return  # stale deposit: applying it would SHORTEN the lease
        try:
            self.storage.grant_serving_lease(int(resp["epoch"]), effective)
            self.applied += 1
        except ValueError:
            # Stale epoch or fenced storage: the deposit is from an old
            # generation (or we already self-fenced) — never resurrect.
            pass


def _shard_extras(storage, box: dict, args,
                  allowed: Optional[Callable[[], bool]] = None) -> Dict:
    """The per-shard ``ship`` + ``retarget`` ops, reading the shard's
    replicator through a mutable ``box`` so a replicator created or
    re-pointed AFTER the handler table was built is still the one the
    ops drive (a closure over the boot-time object would go stale the
    moment retarget runs)."""
    from ratelimiter_tpu.replication.log import ReplicationLog
    from ratelimiter_tpu.replication.replicator import Replicator
    from ratelimiter_tpu.replication.transport import SocketSink

    def ship() -> dict:
        storage.flush()
        repl = box.get("replicator")
        shipped = repl.ship_now() if repl is not None else 0
        return {"frames": int(shipped)}

    def retarget(host: str, port: int,
                 interval_ms: Optional[float] = None) -> dict:
        if allowed is not None and not allowed():
            raise RuntimeError(
                "retarget refused: shard is an unpromoted standby "
                "(re-seeding from a shadow would fork authority)")
        interval = float(interval_ms if interval_ms is not None
                         else args.repl_interval_ms)
        sink = SocketSink(host, int(port), timeout=2.0, max_retries=1,
                          backoff_ms=20.0,
                          ack_timeout=args.ack_timeout_ms / 1000.0,
                          dead_after=2)
        repl = box.get("replicator")
        if repl is not None:
            # Sink swap under a stopped pipeline: stop() leaves the
            # replicator restartable (threads joined, stop flag
            # cleared), so the SAME object carries its counters across
            # the re-point and every handler that captured it stays
            # valid.
            repl.stop()
            try:
                repl.sink.close()
            except Exception:  # noqa: BLE001 — old link teardown
                pass
            repl.sink = sink
            repl.interval_ms = interval
        else:
            repl = Replicator(ReplicationLog(storage), sink,
                              interval_ms=interval)
            box["replicator"] = repl
        # The new peer has empty state: re-baseline with a full frame
        # and ship it synchronously so the caller's success means "the
        # new standby holds a consistent snapshot", not "queued".
        repl.log.request_full()
        repl.start()
        storage.flush()
        frames = repl.ship_now()
        return {"target": f"{host}:{int(port)}", "frames": int(frames)}

    return {"ship": ship, "retarget": retarget}


def _node_extras() -> Dict[str, Callable]:
    """Process-global control ops (both roles): ``skew`` sets the
    injected clock offset every default now-source in this process
    reads (storage/tpu.py), so the chaos conductor can step one NODE's
    clock mid-drill without touching the others."""
    from ratelimiter_tpu.storage.tpu import clock_skew_ms, set_clock_skew_ms

    def skew(skew_ms: Optional[int] = None) -> dict:
        if skew_ms is None:
            return {"skew_ms": clock_skew_ms()}
        prev = set_clock_skew_ms(int(skew_ms))
        return {"skew_ms": int(skew_ms), "prev_ms": prev}

    return {"skew": skew}


def run_primary(args) -> int:
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.replication.control import (
        ControlClient,
        ControlServer,
        mux_handlers,
        primary_handlers,
    )
    from ratelimiter_tpu.replication.log import ReplicationLog
    from ratelimiter_tpu.replication.replicator import Replicator
    from ratelimiter_tpu.replication.transport import SocketSink
    from ratelimiter_tpu.service.sidecar import SidecarServer
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    specs = _build_limiters(args.limiters, args.shards)
    targets = _split_targets(args.repl_target, args.shards)
    standby_ctl = None
    if args.standby_control:
        host, _, port = args.standby_control.rpartition(":")
        standby_ctl = ControlClient(host or "127.0.0.1", int(port),
                                    timeout=0.5)

    per_shard: Dict[int, Dict] = {}
    storages, sidecars, boxes, keepers = [], [], [], []
    lids_per_shard: List[List[int]] = []
    for q in range(args.shards):
        storage = TpuBatchedStorage(num_slots=args.num_slots,
                                    max_delay_ms=0.2)
        sidecar = SidecarServer(storage, host=args.host, port=0,
                                drain_timeout_ms=200.0)
        if args.lease:
            sidecar.attach_leases(_make_lease_manager(storage))
        lids = []
        for spec in specs[q]:
            spec = dict(spec)
            algo = spec.pop("algo")
            lids.append(sidecar.register(algo, RateLimitConfig(**spec)))
        sidecar.start()
        box: dict = {"replicator": None}
        if targets[q]:
            host, _, port = targets[q].rpartition(":")
            sink = SocketSink(host or "127.0.0.1", int(port), timeout=2.0,
                              max_retries=1, backoff_ms=20.0,
                              ack_timeout=args.ack_timeout_ms / 1000.0,
                              dead_after=2)
            box["replicator"] = Replicator(
                ReplicationLog(storage), sink,
                interval_ms=args.repl_interval_ms).start()
        if standby_ctl is not None:
            keepers.append(LeaseKeeper(
                storage, standby_ctl, poll_ms=args.keeper_poll_ms,
                shard=q).start())
        per_shard[q] = primary_handlers(
            storage, replicator=box["replicator"],
            extra=_shard_extras(storage, box, args))
        storages.append(storage)
        sidecars.append(sidecar)
        boxes.append(box)
        lids_per_shard.append(lids)

    control = ControlServer(mux_handlers(per_shard, extra=_node_extras()),
                            host=args.host).start()
    print(json.dumps(_ready_line(
        "primary", control, args,
        sidecar_ports=[s.port for s in sidecars],
        lids=lids_per_shard)), flush=True)
    _wait_for_shutdown()
    for keeper in keepers:
        keeper.stop()
    for box in boxes:
        if box["replicator"] is not None:
            box["replicator"].close()
    control.stop()
    for sidecar in sidecars:
        sidecar.stop()  # drains in-flight frames (drain_timeout_ms)
    for storage in storages:
        # Graceful hand-back: drop the serving lease BEFORE close so
        # the orchestrator reads "stopped on purpose", not a TTL runout.
        try:
            storage.release_serving_lease()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        storage.close()
    if standby_ctl is not None:
        standby_ctl.close()
    return 0


def run_standby(args) -> int:
    from ratelimiter_tpu.replication.control import (
        ControlServer,
        LeaseMailbox,
        mux_handlers,
        standby_handlers,
    )
    from ratelimiter_tpu.replication.standby import StandbyReceiver
    from ratelimiter_tpu.replication.transport import ReplicationServer
    from ratelimiter_tpu.service.sidecar import SidecarServer
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    per_shard: Dict[int, Dict] = {}
    storages, repl_servers, boxes = [], [], []
    promoted_sidecars: List[dict] = []
    for q in range(args.shards):
        storage = TpuBatchedStorage(num_slots=args.num_slots,
                                    max_delay_ms=0.2)
        receiver = StandbyReceiver(storage)
        repl_server = ReplicationServer(receiver, host=args.host).start()
        promoted_sidecar: dict = {}

        def on_promote(storage=storage,
                       promoted_sidecar=promoted_sidecar) -> dict:
            # The shadow is now the serving primary for this shard's
            # keyspace: open the front door and expose every limiter the
            # replication stream registered (lids mean the same policies
            # as on the dead primary — StandbyReceiver verified that on
            # apply).
            sidecar = SidecarServer(storage, host=args.host, port=0,
                                    drain_timeout_ms=200.0)
            if args.lease:
                sidecar.attach_leases(_make_lease_manager(storage))
            for lid, (algo, cfg) in sorted(storage._configs.items()):
                sidecar.expose(lid, algo, cfg)
            sidecar.start()
            promoted_sidecar["server"] = sidecar
            return {"serve_port": sidecar.port}

        box: dict = {"replicator": None}
        per_shard[q] = standby_handlers(
            storage, receiver, repl_server=repl_server,
            mailbox=LeaseMailbox(), on_promote=on_promote,
            extra=_shard_extras(
                storage, box, args,
                allowed=lambda receiver=receiver: receiver.promoted))
        storages.append(storage)
        repl_servers.append(repl_server)
        boxes.append(box)
        promoted_sidecars.append(promoted_sidecar)

    control = ControlServer(mux_handlers(per_shard, extra=_node_extras()),
                            host=args.host).start()
    print(json.dumps(_ready_line(
        "standby", control, args,
        repl_ports=[s.port for s in repl_servers])), flush=True)
    _wait_for_shutdown()
    for box in boxes:
        if box["replicator"] is not None:
            box["replicator"].close()
    control.stop()
    for repl_server in repl_servers:
        repl_server.stop()
    for promoted_sidecar in promoted_sidecars:
        sidecar = promoted_sidecar.get("server")
        if sidecar is not None:
            sidecar.stop()
    for storage in storages:
        storage.close()
    return 0


def _ready_line(role: str, control, args,
                sidecar_ports: Optional[List[int]] = None,
                repl_ports: Optional[List[int]] = None,
                lids: Optional[List[List[int]]] = None) -> dict:
    """The one-line ready JSON.  ``lid_base`` is EXPLICIT (the smallest
    lid any shard registered) so launchers assert agreement instead of
    relying on the storage's lids-start-at-1 convention; k=1 keeps the
    PR 14 scalar field names so old drills parse unchanged."""
    info = {"ready": True, "role": role, "control_port": control.port,
            "version": args.version, "shards": args.shards}
    if lids and any(lids):
        bases = sorted({min(ls) for ls in lids if ls})
        if len(bases) != 1:
            raise RuntimeError(f"shards disagree on lid base: {bases}")
        info["lid_base"] = bases[0]
    if args.shards == 1:
        if sidecar_ports:
            info["sidecar_port"] = sidecar_ports[0]
        if repl_ports:
            info["repl_port"] = repl_ports[0]
        if lids:
            info["lids"] = lids[0]
    else:
        if sidecar_ports:
            info["sidecar_ports"] = sidecar_ports
        if repl_ports:
            info["repl_ports"] = repl_ports
        if lids:
            info["lids"] = lids
    return info


# Graceful-shutdown latch: set by stdin EOF (the launcher dropped its
# pipe) or SIGTERM (an init system / the chaos conductor's graceful
# stop).  Either way the caller runs the SAME ordered teardown and
# exits 0 — only an actual kill signal dies nonzero.
_SHUTDOWN = threading.Event()


def _install_sigterm() -> None:
    """Route SIGTERM into the shutdown latch.  Best-effort: signal
    handlers only install from the main thread (in-process tests that
    drive ``run_primary`` from a worker thread just skip this)."""
    try:
        signal.signal(signal.SIGTERM, lambda *_: _SHUTDOWN.set())
    except ValueError:
        pass


def _wait_for_eof() -> None:
    """Block until the launcher closes our stdin (its handle on our
    lifetime); also returns if stdin was never a pipe.  Reads the raw
    fd — a buffered ``sys.stdin`` read would hold the reader's lock
    across the block, and interpreter finalization aborts (fatal
    ``_enter_buffered_busy``) if a SIGTERM exit races a daemon thread
    parked inside it."""
    try:
        fd = sys.stdin.fileno()
        while os.read(fd, 4096):
            pass
    except (OSError, ValueError):
        time.sleep(3600.0)


def _wait_for_shutdown() -> None:
    """Block until stdin EOF or SIGTERM, whichever first.  The EOF
    watch runs on a daemon thread so a TERM can interrupt a blocked
    pipe read (PEP 475 would otherwise retry it forever)."""

    def eof_watch() -> None:
        _wait_for_eof()
        _SHUTDOWN.set()

    threading.Thread(target=eof_watch, name="eof-watch",
                     daemon=True).start()
    _SHUTDOWN.wait()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--role", choices=("primary", "standby"),
                        required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--num-slots", type=int, default=512)
    parser.add_argument("--shards", type=int, default=1,
                        help="independent shard storages hosted by this "
                             "node behind ONE multiplexed control port")
    parser.add_argument("--version", default="v0",
                        help="deploy version tag echoed in the ready "
                             "line and the fleet actuator (rolling "
                             "upgrades assert on it)")
    parser.add_argument("--limiters", default="",
                        help="JSON list of limiter specs to register "
                             "(primary; algo + RateLimitConfig kwargs), "
                             "or a list of per-shard lists")
    parser.add_argument("--lease", action="store_true",
                        help="attach a token-lease manager to the "
                             "sidecar (v3 LEASE/RENEW/RELEASE)")
    parser.add_argument("--repl-target", default="",
                        help="host:port of the standby's replication "
                             "listener (primary; comma-separated, one "
                             "per shard, for --shards > 1)")
    parser.add_argument("--standby-control", default="",
                        help="host:port of the standby's CONTROL port "
                             "(primary; enables the lease-relay keeper)")
    parser.add_argument("--repl-interval-ms", type=float, default=100.0)
    # Generous by default: the standby's FIRST frame apply jit-compiles
    # write_rows, and an ack deadline under that compile time reads as a
    # dead link on a cold cache (the props default is 5000 too).
    parser.add_argument("--ack-timeout-ms", type=float, default=5000.0)
    parser.add_argument("--keeper-poll-ms", type=float, default=100.0)
    args = parser.parse_args(argv)
    if args.shards < 1:
        parser.error("--shards must be >= 1")
    # Persistent XLA compile cache: the node's dispatch shapes are the
    # standard micro-batch buckets, so a warm cache turns per-process
    # jit compiles into disk loads (utils/compile_cache.py).
    try:
        from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache(None)
    except Exception:  # noqa: BLE001 — cold compiles still work
        pass
    _install_sigterm()
    if args.role == "primary":
        return run_primary(args)
    return run_standby(args)


if __name__ == "__main__":
    raise SystemExit(main())
