"""Cross-host node process: one shard primary or one standby, runnable
as ``python -m ratelimiter_tpu.replication.hostproc``.

This is the process the multi-process topology (ARCHITECTURE §10c) is
made of.  A PRIMARY node serves decisions over a sidecar (wire protocol
v4, optional token leases), ships its replication stream to its standby
(``--repl-target``), exposes the control port (PROBE / FENCE / LEASE /
RESTORE / SHIP), and runs the LEASE KEEPER: when the orchestrator's
direct renewals stop arriving, the keeper fetches the newest deposited
grant from the standby's mailbox over the replication-side link — so a
primary partitioned only from the ORCHESTRATOR keeps serving, while one
partitioned from everything runs its lease down and self-fences within
one TTL.  A STANDBY node applies the replication stream, answers the
witness probe (``repl_rx_age_ms``), holds the lease mailbox, and serves
the remote-promotion RPC — a successful PROMOTE starts a sidecar over
the now-serving storage and reports its port for clients to re-point.

The process prints ONE JSON line on stdout when ready (ports included)
and exits when stdin closes — the launcher (a drill, an init system
wrapper) owns its lifetime through the pipe.

``storage/chaos.py:cross_host_failover_drill`` spawns these as real OS
subprocesses with ``FaultInjectingProxy`` links between them.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import List, Optional


def _build_limiters(spec_json: str) -> List[dict]:
    spec = json.loads(spec_json) if spec_json else []
    if not isinstance(spec, list):
        raise ValueError("--limiters must be a JSON list")
    return spec


def _make_lease_manager(storage, props: Optional[dict] = None):
    from ratelimiter_tpu.leases import LeaseManager

    props = props or {}
    return LeaseManager(
        storage,
        default_budget=int(props.get("default_budget", 64)),
        max_budget=int(props.get("max_budget", 1024)),
        ttl_ms=float(props.get("ttl_ms", 2000.0)),
        deny_ttl_ms=float(props.get("deny_ttl_ms", 25.0)),
    )


class LeaseKeeper:
    """Primary-side relay fetcher: while a serving lease is installed,
    poll the standby's mailbox and apply any deposit that would EXTEND
    the local deadline (a stale deposit can only shorten it and is
    skipped — the lease still expires on the original schedule).

    Age accounting makes the relay skew-free: the deposit's ``age_ms``
    is measured on the STANDBY's clock between orchestrator deposit and
    our fetch, so the applied TTL is ``ttl - age - slack`` — always at
    or under what the orchestrator believes it granted, never past it.
    """

    def __init__(self, storage, standby_ctl, poll_ms: float = 100.0,
                 slack_ms: float = 25.0):
        self.storage = storage
        self.ctl = standby_ctl
        self.poll_ms = float(poll_ms)
        self.slack_ms = float(slack_ms)
        self.fetches = 0
        self.applied = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="lease-keeper", daemon=True)

    def start(self) -> "LeaseKeeper":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_ms / 1000.0):
            try:
                self._poll_once()
            except Exception:  # noqa: BLE001 — the keeper never dies;
                # a broken relay just lets the lease run down (by design)
                pass

    def _poll_once(self) -> None:
        info = self.storage.serving_lease_info()
        if not info["installed"]:
            return  # no lease granted yet, or already expired/fenced
        resp = self.ctl.try_call("lease_fetch")
        self.fetches += 1
        if resp is None or not resp.get("ok") or not resp.get("deposited"):
            return
        effective = (float(resp["ttl_ms"]) - float(resp["age_ms"])
                     - self.slack_ms)
        if effective <= info["ttl_remaining_ms"]:
            return  # stale deposit: applying it would SHORTEN the lease
        try:
            self.storage.grant_serving_lease(int(resp["epoch"]), effective)
            self.applied += 1
        except ValueError:
            # Stale epoch or fenced storage: the deposit is from an old
            # generation (or we already self-fenced) — never resurrect.
            pass


def run_primary(args) -> int:
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.replication.control import (
        ControlClient,
        ControlServer,
        primary_handlers,
    )
    from ratelimiter_tpu.replication.log import ReplicationLog
    from ratelimiter_tpu.replication.replicator import Replicator
    from ratelimiter_tpu.replication.transport import SocketSink
    from ratelimiter_tpu.service.sidecar import SidecarServer
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    storage = TpuBatchedStorage(num_slots=args.num_slots,
                                max_delay_ms=0.2)
    sidecar = SidecarServer(storage, host=args.host, port=0,
                            drain_timeout_ms=200.0)
    if args.lease:
        sidecar.attach_leases(_make_lease_manager(storage))
    lids = []
    for spec in _build_limiters(args.limiters):
        algo = spec.pop("algo")
        lids.append(sidecar.register(algo, RateLimitConfig(**spec)))
    sidecar.start()

    replicator = None
    if args.repl_target:
        host, _, port = args.repl_target.rpartition(":")
        sink = SocketSink(host or "127.0.0.1", int(port), timeout=2.0,
                          max_retries=1, backoff_ms=20.0,
                          ack_timeout=args.ack_timeout_ms / 1000.0,
                          dead_after=2)
        replicator = Replicator(ReplicationLog(storage), sink,
                                interval_ms=args.repl_interval_ms).start()

    keeper = None
    if args.standby_control:
        host, _, port = args.standby_control.rpartition(":")
        keeper = LeaseKeeper(
            storage, ControlClient(host or "127.0.0.1", int(port),
                                   timeout=0.5),
            poll_ms=args.keeper_poll_ms).start()

    control = ControlServer(
        primary_handlers(storage, replicator=replicator),
        host=args.host).start()

    print(json.dumps({"ready": True, "role": "primary",
                      "control_port": control.port,
                      "sidecar_port": sidecar.port,
                      "lids": lids}), flush=True)
    _wait_for_eof()
    if keeper is not None:
        keeper.stop()
    if replicator is not None:
        replicator.close()
    control.stop()
    sidecar.stop()
    storage.close()
    return 0


def run_standby(args) -> int:
    from ratelimiter_tpu.replication.control import (
        ControlServer,
        LeaseMailbox,
        standby_handlers,
    )
    from ratelimiter_tpu.replication.standby import StandbyReceiver
    from ratelimiter_tpu.replication.transport import ReplicationServer
    from ratelimiter_tpu.service.sidecar import SidecarServer
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    storage = TpuBatchedStorage(num_slots=args.num_slots,
                                max_delay_ms=0.2)
    receiver = StandbyReceiver(storage)
    repl_server = ReplicationServer(receiver, host=args.host).start()
    promoted_sidecar: dict = {}

    def on_promote() -> dict:
        # The shadow is now the serving primary for this shard's
        # keyspace: open the front door and expose every limiter the
        # replication stream registered (lids mean the same policies as
        # on the dead primary — StandbyReceiver verified that on apply).
        sidecar = SidecarServer(storage, host=args.host, port=0,
                                drain_timeout_ms=200.0)
        if args.lease:
            sidecar.attach_leases(_make_lease_manager(storage))
        for lid, (algo, cfg) in sorted(storage._configs.items()):
            sidecar.expose(lid, algo, cfg)
        sidecar.start()
        promoted_sidecar["server"] = sidecar
        return {"serve_port": sidecar.port}

    control = ControlServer(
        standby_handlers(storage, receiver, repl_server=repl_server,
                         mailbox=LeaseMailbox(), on_promote=on_promote),
        host=args.host).start()

    print(json.dumps({"ready": True, "role": "standby",
                      "control_port": control.port,
                      "repl_port": repl_server.port}), flush=True)
    _wait_for_eof()
    control.stop()
    repl_server.stop()
    sidecar = promoted_sidecar.get("server")
    if sidecar is not None:
        sidecar.stop()
    storage.close()
    return 0


def _wait_for_eof() -> None:
    """Block until the launcher closes our stdin (its handle on our
    lifetime); also returns if stdin was never a pipe."""
    try:
        while sys.stdin.buffer.read(4096):
            pass
    except (OSError, ValueError):
        time.sleep(3600.0)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--role", choices=("primary", "standby"),
                        required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--num-slots", type=int, default=512)
    parser.add_argument("--limiters", default="",
                        help="JSON list of limiter specs to register "
                             "(primary; algo + RateLimitConfig kwargs)")
    parser.add_argument("--lease", action="store_true",
                        help="attach a token-lease manager to the "
                             "sidecar (v3 LEASE/RENEW/RELEASE)")
    parser.add_argument("--repl-target", default="",
                        help="host:port of the standby's replication "
                             "listener (primary)")
    parser.add_argument("--standby-control", default="",
                        help="host:port of the standby's CONTROL port "
                             "(primary; enables the lease-relay keeper)")
    parser.add_argument("--repl-interval-ms", type=float, default=100.0)
    # Generous by default: the standby's FIRST frame apply jit-compiles
    # write_rows, and an ack deadline under that compile time reads as a
    # dead link on a cold cache (the props default is 5000 too).
    parser.add_argument("--ack-timeout-ms", type=float, default=5000.0)
    parser.add_argument("--keeper-poll-ms", type=float, default=100.0)
    args = parser.parse_args(argv)
    # Persistent XLA compile cache: the node's dispatch shapes are the
    # standard micro-batch buckets, so a warm cache turns per-process
    # jit compiles into disk loads (utils/compile_cache.py).
    try:
        from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache(None)
    except Exception:  # noqa: BLE001 — cold compiles still work
        pass
    if args.role == "primary":
        return run_primary(args)
    return run_standby(args)


if __name__ == "__main__":
    raise SystemExit(main())
