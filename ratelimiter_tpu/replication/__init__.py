"""Live state replication & hot-standby failover for the TPU engine.

The availability layer Redis AOF/replication gave the reference and the
device-resident engine lacked: the primary's engine journals dirty slots
per dispatched batch (engine/state.py:SlotJournal), a ``ReplicationLog``
coalesces them into epoch-stamped frames (replication/wire.py), an async
``Replicator`` ships the frames off the decision path, and a
``StandbyReceiver`` applies them to a shadow engine that can be promoted
on failover with decisions bit-identical to ``semantics/oracle.py`` for
every key at or before the last replicated epoch.

Wiring (service/wiring.py) is config-gated and OFF by default:

    replication.enabled     = true
    replication.role        = primary | standby
    replication.target      = standby-host:7401        (primary)
    replication.listen_port = 7401                     (standby)
    replication.interval_ms = 200                      (primary)
"""

from ratelimiter_tpu.replication.log import (
    ReplicationLog,
    engine_state_fingerprint,
)
from ratelimiter_tpu.replication.replicator import Replicator
from ratelimiter_tpu.replication.standby import (
    ReplicationStateError,
    StandbyReceiver,
)
from ratelimiter_tpu.replication.transport import (
    FrameArchive,
    InProcessSink,
    ReplicationServer,
    SocketSink,
    TeeSink,
)
from ratelimiter_tpu.replication.wire import (
    DEFAULT_FRAME_BUDGET,
    chunk_frames,
    decode_frame,
    encode_frame,
)

__all__ = [
    "DEFAULT_FRAME_BUDGET",
    "FrameArchive",
    "InProcessSink",
    "ReplicationLog",
    "ReplicationServer",
    "ReplicationStateError",
    "Replicator",
    "SocketSink",
    "StandbyReceiver",
    "TeeSink",
    "chunk_frames",
    "decode_frame",
    "encode_frame",
    "engine_state_fingerprint",
]
