"""Live state replication & failover for the TPU engine — shard-aware.

The availability layer Redis AOF/replication gave the reference and the
device-resident engine lacked: the primary's engine journals dirty slots
per dispatched batch — a device-resident touched-slot bitmap
(engine/state.py:DeviceSlotJournal) riding the dispatch's own uploaded
lanes, elected per device against the host-scatter fallback
(SlotJournal) — a ``ReplicationLog`` coalesces them into epoch-stamped
frames (replication/wire.py), an async ``Replicator`` ships the frames
off the decision path behind a byte-bounded in-flight queue (slow links
coalesce cuts instead of growing host memory), and a ``StandbyReceiver``
applies them to a shadow engine that can be promoted on failover with
decisions bit-identical to ``semantics/oracle.py`` for every key at or
before the last replicated epoch.

A SHARDED engine replicates per shard (replication/sharded.py): each
shard ships its own epoch stream into a standby mesh of ordinary flat
standbys, a dead shard is promoted alone while the surviving shards
keep serving behind a ``ShardFailoverRouter``, and health reports a
DEGRADED-shard state instead of DOWN.

Failover itself is autonomous (replication/orchestrator.py): the
``FailoverOrchestrator`` watches per-shard liveness through an explicit
state machine with flap damping (consecutive-failure + hysteresis),
fences the replaced backend at a monotonic epoch (zombie dispatches
refuse with ``FencedError``), drives the proven promotion path with
bounded retry, and re-seeds a fresh standby so the system returns to
N+1 — zero manual actuator calls (``ratelimiter.orchestrator.*``).

The topology spans PROCESSES AND HOSTS (replication/control.py +
remote.py + hostproc.py, ARCHITECTURE §10c): a small control-plane RPC
(PROBE / FENCE / LEASE / PROMOTE / RESTORE over length-prefixed JSON)
lets the same orchestrator drive shard primaries and standbys running
as separate OS processes, with a DISTRIBUTED fence: the orchestrator
grants each serving backend an epoch lease and renews it while probes
answer (relayed through the standby's mailbox when only the
orchestrator's own link is partitioned), a primary whose lease expires
SELF-FENCES within one TTL, and a promoted replacement always carries
a strictly higher epoch — bounded over-admission with no quorum
library.  ``storage/chaos.py:cross_host_failover_drill`` proves it with
real subprocesses under injected partitions.

Wiring (service/wiring.py) is config-gated and OFF by default:

    replication.enabled     = true
    replication.role        = primary | standby
    replication.target      = standby-host:7401        (flat primary)
    replication.targets     = h0:7401,h1:7401,...      (sharded primary,
                                                        one per shard)
    replication.listen_port = 7401                     (standby)
    replication.interval_ms = 200                      (primary)
"""

from ratelimiter_tpu.replication.control import (
    ControlClient,
    ControlError,
    ControlServer,
    LeaseMailbox,
    mux_handlers,
    primary_handlers,
    standby_handlers,
)
from ratelimiter_tpu.replication.log import (
    ReplicationLog,
    device_journal_elected,
    engine_state_fingerprint,
    make_journal,
)
from ratelimiter_tpu.replication.orchestrator import (
    BackendLeaseChannel,
    FailoverOrchestrator,
    OrchestratorConfig,
)
from ratelimiter_tpu.replication.remote import (
    FanoutLeaseChannel,
    RemoteBackend,
    RemoteReceiver,
    RemoteShardDirectory,
    RemoteStandbySet,
    parse_ready,
    standby_witness,
)
from ratelimiter_tpu.replication.replicator import Replicator
from ratelimiter_tpu.replication.sharded import (
    ShardedReplicationLog,
    ShardedReplicator,
    ShardFailoverRouter,
    ShardStandbySet,
)
from ratelimiter_tpu.replication.standby import (
    ReplicationStateError,
    StandbyReceiver,
)
from ratelimiter_tpu.replication.transport import (
    FrameArchive,
    InProcessSink,
    ReplicationServer,
    SocketSink,
    TeeSink,
)
from ratelimiter_tpu.replication.wire import (
    DEFAULT_FRAME_BUDGET,
    chunk_frames,
    decode_frame,
    encode_frame,
)

__all__ = [
    "BackendLeaseChannel",
    "ControlClient",
    "ControlError",
    "ControlServer",
    "DEFAULT_FRAME_BUDGET",
    "FailoverOrchestrator",
    "FanoutLeaseChannel",
    "FrameArchive",
    "LeaseMailbox",
    "OrchestratorConfig",
    "InProcessSink",
    "RemoteBackend",
    "RemoteReceiver",
    "RemoteShardDirectory",
    "RemoteStandbySet",
    "ReplicationLog",
    "ReplicationServer",
    "ReplicationStateError",
    "Replicator",
    "ShardFailoverRouter",
    "ShardStandbySet",
    "ShardedReplicationLog",
    "ShardedReplicator",
    "SocketSink",
    "StandbyReceiver",
    "TeeSink",
    "chunk_frames",
    "decode_frame",
    "device_journal_elected",
    "encode_frame",
    "engine_state_fingerprint",
    "make_journal",
    "mux_handlers",
    "parse_ready",
    "primary_handlers",
    "standby_handlers",
    "standby_witness",
]
