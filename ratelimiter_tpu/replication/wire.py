"""Replication frame wire format.

A frame is the unit the primary ships to a standby: an epoch-stamped
delta of packed state rows (the coalesced dirty-slot set of one
``SlotJournal.drain``), plus — on the epoch's last sub-frame — the
key->slot index journal and the limiter registrations that make the
rows addressable after a promotion.

Encoding reuses the checkpoint machinery's array detach/attach
(engine/checkpoint.py) so native fingerprint index dumps ship as raw
numpy arrays, not JSON:

    b"RLRP" | u16 version | u32 json_len | json meta | npz payload

Large epochs are CHUNKED (:func:`chunk_frames`) to the same per-dispatch
wire budget the streaming loops use (storage/tpu.py wire budgets,
measured on the dev tunnel): each sub-frame's row payload stays under
``max_bytes`` so one slow frame never parks the link, and the standby
applies sub-frames as they land (rows are idempotent writes; only the
``last`` sub-frame advances the epoch).
"""

from __future__ import annotations

import io
import json
import struct
from typing import Dict, List, Optional

import numpy as np

from ratelimiter_tpu.engine.checkpoint import (
    _attach_index_arrays,
    _detach_index_arrays,
)

MAGIC = b"RLRP"
WIRE_VERSION = 1

# Per-sub-frame row-payload budget: the 16 MB per-dispatch wire budget
# the streaming loops settled on (storage/tpu.py:_RELAY_WIRE_BUDGET_*,
# ROUND_NOTES r3 — large transfers amortize best in ~16 MB units).
DEFAULT_FRAME_BUDGET = 16 << 20

_HEADER = struct.Struct("<4sHI")  # magic, version, json length


def chunk_frames(
    epoch: int,
    cut_ms: int,
    num_slots: int,
    deltas: Dict[str, Dict[str, np.ndarray]],
    index_dump: Dict,
    limiters: Dict,
    full: bool = False,
    max_bytes: int = DEFAULT_FRAME_BUDGET,
) -> List[Dict]:
    """Split one epoch's deltas into sub-frames within the wire budget.

    ``deltas`` maps algo -> {"slots": i64[n], "rows": i32[n, L]}.  The
    index journal and limiter table ride only on the LAST sub-frame:
    they describe the state at the cut, so applying them before every
    row has landed would let a promotion see keys whose rows are still
    in flight.
    """
    pieces: List[Dict] = []  # (algo, slots, rows) chunks, budget-sized
    for algo, payload in deltas.items():
        slots = np.asarray(payload["slots"], dtype=np.int64)
        rows = np.asarray(payload["rows"], dtype=np.int32)
        if not len(slots):
            continue
        row_bytes = max(rows[0].nbytes + 8, 1)
        per = max(int(max_bytes // row_bytes), 1)
        for i in range(0, len(slots), per):
            pieces.append({"algo": algo,
                           "slots": slots[i:i + per],
                           "rows": rows[i:i + per]})
    frames: List[Dict] = []
    if not pieces:
        pieces = [None]  # index/limiters-only frame (still epoch-stamped)
    for seq, piece in enumerate(pieces):
        last = seq == len(pieces) - 1
        frame: Dict = {
            "epoch": int(epoch),
            "seq": seq,
            "last": last,
            "full": bool(full),
            "cut_ms": int(cut_ms),
            "num_slots": int(num_slots),
            "algos": {},
        }
        if piece is not None:
            frame["algos"][piece["algo"]] = {
                "slots": piece["slots"], "rows": piece["rows"]}
        if last:
            frame["index"] = index_dump
            frame["limiters"] = limiters
        frames.append(frame)
    return frames


def encode_frame(frame: Dict) -> bytes:
    """Serialize a frame dict (numpy arrays -> npz, the rest -> JSON)."""
    arrays: Dict[str, np.ndarray] = {}
    meta = {k: v for k, v in frame.items() if k not in ("algos", "index")}
    meta["algos"] = sorted(frame.get("algos", {}))
    for algo, payload in frame.get("algos", {}).items():
        arrays[f"delta_{algo}_slots"] = np.asarray(payload["slots"],
                                                   dtype=np.int64)
        arrays[f"delta_{algo}_rows"] = np.asarray(payload["rows"],
                                                  dtype=np.int32)
    if "index" in frame:
        meta["index"] = _detach_index_arrays(frame["index"], arrays)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    blob = json.dumps(meta).encode()
    return _HEADER.pack(MAGIC, WIRE_VERSION, len(blob)) + blob + buf.getvalue()


def decode_frame(data: bytes) -> Dict:
    magic, version, jlen = _HEADER.unpack_from(data)
    if magic != MAGIC:
        raise ValueError("not a replication frame (bad magic)")
    if version != WIRE_VERSION:
        raise ValueError(f"unsupported replication wire version {version}")
    meta = json.loads(data[_HEADER.size:_HEADER.size + jlen])
    arrays = dict(np.load(io.BytesIO(data[_HEADER.size + jlen:]),
                          allow_pickle=False))
    frame: Dict = {k: v for k, v in meta.items() if k not in ("algos",
                                                              "index")}
    frame["algos"] = {
        algo: {"slots": arrays[f"delta_{algo}_slots"],
               "rows": arrays[f"delta_{algo}_rows"]}
        for algo in meta.get("algos", [])
    }
    if "index" in meta:
        frame["index"] = _attach_index_arrays(meta["index"], arrays)
    return frame


def frame_slots(frame: Dict) -> Dict[str, Optional[np.ndarray]]:
    """Per-algo slot ids a frame carries (re-mark set on ship failure)."""
    return {algo: payload["slots"]
            for algo, payload in frame.get("algos", {}).items()}
