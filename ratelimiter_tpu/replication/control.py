"""Control-plane RPC: the small dedicated port that lets a failover
orchestrator drive shard primaries and standbys running in OTHER
processes (ROADMAP item 4 — cross-host scale-out).

The replication data plane (transport.py) ships state; this port ships
*authority*: PROBE (liveness + replica status), FENCE (install a fence
epoch on a zombie), LEASE (grant/renew the serving lease that bounds a
partitioned zombie's over-admission), LEASE_DEPOSIT / LEASE_FETCH (the
standby-relayed renewal path for a primary the orchestrator cannot
reach directly), PROMOTE (the remote-promotion RPC), RESTORE (operator
unfence), and SHIP (flush + one synchronous replication cycle — drills
use it to pin the replica byte-exact before a kill).  The fleet
control plane (ARCHITECTURE §15) rides the same port: every role also
serves CONTROLLER_CLAIM / SET_POLICY / POLICY_INFO / SIGNALS — the
epoch-fenced controller-leadership ops (:class:`ControllerSeat`).

Wire format (ARCHITECTURE §10c)::

    u32 length (LE) | UTF-8 JSON payload

Request payloads are ``{"op": <name>, ...args}``; responses are
``{"ok": true, ...fields}`` or ``{"ok": false, "error": <detail>}``.
JSON over length-prefixed frames is deliberate: control traffic is a
few frames per second per shard (the decision path never touches this
port), so the spec optimizes for auditability — an operator can drive
every op with ``python -c`` and a socket — not for bytes.  An unknown
op answers ``ok=false`` in-protocol; a handler exception is caught and
answered the same way (the control port never wedges on a bad frame).

Roles install different handler sets (``primary_handlers`` /
``standby_handlers``); a node can expose extra ops by passing more
callables.  Every handler runs on the server's connection thread —
handlers must stay short (promote is the long pole and is bounded by
the client's per-call timeout).
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time
from typing import Callable, Dict, Optional

from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("replication.control")

_LEN = struct.Struct("<I")

# A control frame is a few hundred bytes of JSON; anything bigger is a
# framing error or an attack, answered in-protocol and the conn closed.
MAX_CONTROL_FRAME = 1 << 20


class ControlError(ConnectionError):
    """Transport-level control failure (peer unreachable / link cut /
    timed out) — distinct from an in-protocol ``ok=false`` refusal."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("control peer closed connection")
        buf += chunk
    return buf


def _read_frame(sock: socket.socket) -> Optional[dict]:
    try:
        header = _recv_exact(sock, _LEN.size)
    except (ConnectionError, OSError):
        return None
    (length,) = _LEN.unpack(header)
    if length == 0 or length > MAX_CONTROL_FRAME:
        raise ValueError(f"control frame length {length} out of bounds")
    payload = _recv_exact(sock, length)
    return json.loads(payload.decode("utf-8"))


def _write_frame(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    sock.sendall(_LEN.pack(len(data)) + data)


class ControlServer:
    """Framed-JSON control listener dispatching to a handler table.

    ``handlers`` maps op name -> callable; the callable receives the
    request's non-``op`` fields as keyword arguments and returns a dict
    merged into the ``{"ok": true}`` response (or raises — the error
    string is answered as ``ok=false``).
    """

    def __init__(self, handlers: Dict[str, Callable[..., dict]],
                 host: str = "127.0.0.1", port: int = 0):
        self.handlers = dict(handlers)
        self.requests_served = 0
        self.errors_answered = 0
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock: socket.socket = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                while True:
                    try:
                        req = _read_frame(sock)
                    except (ValueError, OSError):
                        return  # framing violation: drop the conn
                    if req is None:
                        return
                    op = req.pop("op", None)
                    fn = outer.handlers.get(op)
                    if fn is None:
                        resp = {"ok": False, "error": f"unknown op {op!r}"}
                        outer.errors_answered += 1
                    else:
                        try:
                            out = fn(**req) or {}
                            resp = {"ok": True, **out}
                        except Exception as exc:  # noqa: BLE001 — answered
                            resp = {"ok": False,
                                    "error": f"{type(exc).__name__}: {exc}"}
                            outer.errors_answered += 1
                    outer.requests_served += 1
                    try:
                        _write_frame(sock, resp)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="control-rpc",
            daemon=True)

    def start(self) -> "ControlServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class ControlClient:
    """One control connection with per-call deadlines.

    Connects lazily and reconnects per failed call; a call that cannot
    complete within ``timeout`` raises :class:`ControlError` (the
    orchestrator treats that as a probe failure — exactly the signal a
    partition produces).  Thread-safe: one in-flight call at a time.
    """

    def __init__(self, host: str, port: int, timeout: float = 2.0):
        self.host = host
        self.port = int(port)
        self.timeout = float(timeout)
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def call(self, op: str, timeout: Optional[float] = None, **kw) -> dict:
        """One request/response round trip; raises ControlError on any
        transport fault (the in-protocol ``ok`` field is the caller's to
        check).

        A call that fails on a PREVIOUSLY-USED connection retries once
        on a fresh one: a persistent control link can go stale between
        calls (peer restart, idle reaper, half-closed proxy) and every
        control op is safe to re-ask — reads are pure, and the write ops
        are guarded server-side by monotonic epochs / single-winner
        promotion, so a duplicate is answered in-protocol, not
        double-applied."""
        deadline = float(timeout if timeout is not None else self.timeout)
        with self._lock:
            for attempt in range(2):
                reused = self._sock is not None
                try:
                    if self._sock is None:
                        self._sock = self._connect()
                    self._sock.settimeout(deadline)
                    _write_frame(self._sock, {"op": op, **kw})
                    resp = _read_frame(self._sock)
                except (OSError, ValueError, ConnectionError) as exc:
                    self._drop()
                    if reused and attempt == 0:
                        continue
                    raise ControlError(
                        f"control call {op!r} to {self.host}:{self.port} "
                        f"failed: {exc}") from exc
                if resp is None:
                    self._drop()
                    if reused and attempt == 0:
                        continue
                    raise ControlError(
                        f"control peer {self.host}:{self.port} closed "
                        f"during {op!r}")
                return resp
            raise ControlError(  # unreachable; loop always raised/returned
                f"control call {op!r} to {self.host}:{self.port} failed")

    def call_ok(self, op: str, timeout: Optional[float] = None,
                **kw) -> dict:
        """Like :meth:`call` but an in-protocol refusal raises too."""
        resp = self.call(op, timeout=timeout, **kw)
        if not resp.get("ok"):
            raise RuntimeError(
                f"control op {op!r} refused by {self.host}:{self.port}: "
                f"{resp.get('error')}")
        return resp

    def try_call(self, op: str, **kw) -> Optional[dict]:
        """``call`` that returns None instead of raising on transport
        faults (witness/status polls that must never throw)."""
        try:
            return self.call(op, **kw)
        except ControlError:
            return None

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._drop()


# ---------------------------------------------------------------------------
# Role handler tables (hostproc.py and service/wiring.py install these)
# ---------------------------------------------------------------------------


class LeaseMailbox:
    """The standby-relayed renewal path's mailbox: the orchestrator
    deposits serving-lease grants here (it can reach the standby), and
    the primary — when it has not heard from the orchestrator directly —
    fetches the newest deposit over the replication-side link it still
    has.  Age is stamped at deposit on the MAILBOX's clock and returned
    relative, so neither peer needs synchronized wall clocks."""

    def __init__(self):
        self._lock = threading.Lock()
        self._grant: Optional[dict] = None
        self.deposits = 0
        self.fetches = 0

    def deposit(self, epoch: int, ttl_ms: float) -> dict:
        with self._lock:
            self._grant = {"epoch": int(epoch), "ttl_ms": float(ttl_ms),
                           "at_mono": time.monotonic()}
            self.deposits += 1
            return {"epoch": int(epoch)}

    def fetch(self) -> dict:
        with self._lock:
            self.fetches += 1
            if self._grant is None:
                return {"deposited": False}
            age_ms = (time.monotonic() - self._grant["at_mono"]) * 1000.0
            return {"deposited": True, "epoch": self._grant["epoch"],
                    "ttl_ms": self._grant["ttl_ms"],
                    "age_ms": round(age_ms, 3)}


class ControllerSeat:
    """Node-side acceptor for the fleet controller's authority claims
    (ARCHITECTURE §15).  Mirrors the serving-lease fence-epoch rule on
    the CONTROL plane: the seat remembers the highest controller epoch
    it ever granted, a claim at a lower epoch is refused in-protocol
    (with the current epoch, so a zombie learns it was superseded), and
    every policy write carries the writer's epoch — a write below the
    seat's epoch is rejected and counted, never applied.  Epochs are
    granted per NODE; the electing side only considers itself leader
    with a MAJORITY of seats, so two controllers can never both hold a
    quorum at the same epoch."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self.node: Optional[str] = None
        self.epoch = 0
        self.ttl_ms = 0.0
        self.granted_at = 0.0
        self.stale_rejected = 0

    def claim(self, node: str, epoch: int, ttl_ms: float = 3000.0) -> dict:
        """Grant (or refuse) controller authority at ``epoch``.  A
        strictly higher epoch always wins — even over an unexpired
        grant, exactly like ``storage.fence`` — and the CURRENT holder
        renews at its own epoch to refresh the TTL."""
        epoch = int(epoch)
        with self._lock:
            now = self._clock()
            if epoch > self.epoch or (epoch == self.epoch
                                      and node == self.node):
                self.node = str(node)
                self.epoch = epoch
                self.ttl_ms = float(ttl_ms)
                self.granted_at = now
                return {"granted": True, "epoch": self.epoch,
                        "node": self.node}
            return {"granted": False, "epoch": self.epoch,
                    "node": self.node,
                    "expired": self._expired_locked(now)}

    def check(self, epoch: int) -> bool:
        """True iff a write stamped ``epoch`` is current; a stale epoch
        is counted (``stale_rejected``) and must not be applied."""
        with self._lock:
            if int(epoch) < self.epoch:
                self.stale_rejected += 1
                return False
            return True

    def _expired_locked(self, now: float) -> bool:
        return (self.node is not None
                and (now - self.granted_at) * 1000.0 > self.ttl_ms)

    def info(self) -> dict:
        with self._lock:
            now = self._clock()
            remaining = 0.0
            if self.node is not None:
                remaining = self.ttl_ms - (now - self.granted_at) * 1000.0
            return {"node": self.node, "epoch": self.epoch,
                    "ttl_remaining_ms": round(remaining, 3),
                    "expired": self._expired_locked(now),
                    "stale_rejected": self.stale_rejected}


def controller_handlers(storage, seat: Optional[ControllerSeat] = None,
                        ) -> Dict[str, Callable]:
    """The fleet-controller ops EVERY node role serves (merged into
    both ``primary_handlers`` and ``standby_handlers``):

    - ``controller_claim`` — grant/renew/refuse controller authority at
      a fence epoch (see :class:`ControllerSeat`).
    - ``set_policy``      — apply a batch of policy rows at the
      leader's monotone generation stamp.  Idempotent: a duplicate is
      a no-op, an older generation is refused (``stale_generation``),
      and a write below the seat's controller epoch is refused without
      touching the table (``stale_epoch``) — the zombie-leader guard
      the partitioned-controller drill proves.
    - ``policy_info``     — the policy table (generation + per-lid
      rows) plus the controller seat, the leader's anti-entropy read.
    - ``signals``         — the node's local ``UsageSignals`` per lid
      (serialized as field lists) plus the plane's staleness, the
      leader's fleet-true observation read.
    """
    from ratelimiter_tpu.engine.checkpoint import apply_limiter_policies

    seat = seat if seat is not None else ControllerSeat()

    def _generation() -> int:
        table = getattr(storage, "table", None)
        return int(table.generation) if table is not None else 0

    def controller_claim(node: str, epoch: int,
                         ttl_ms: float = 3000.0) -> dict:
        out = seat.claim(node, epoch, ttl_ms)
        out["generation"] = _generation()
        return out

    def set_policy(rows: dict, epoch: int = 0, node: str = "") -> dict:
        if not seat.check(int(epoch)):
            return {"applied": False, "stale_epoch": True,
                    "epoch": seat.epoch, "generation": _generation()}
        try:
            apply_limiter_policies(storage, dict(rows))
        except ValueError as exc:
            # An older generation racing a newer one is EXPECTED under
            # retries and failover — answer in-protocol so the caller
            # converges instead of error-storming.
            return {"applied": False, "stale_generation": True,
                    "error": str(exc), "generation": _generation()}
        return {"applied": True, "generation": _generation()}

    def policy_info() -> dict:
        if hasattr(storage, "policy_info"):
            out = dict(storage.policy_info())
        else:
            out = {"generation": _generation(), "lids": {}}
        out["controller"] = seat.info()
        return out

    def signals(window_ms: int = 2000) -> dict:
        plane = getattr(storage, "telemetry", None)
        if plane is None:
            return {"signals": {}, "staleness_ms": 0.0}
        sigs = plane.all_signals(int(window_ms))
        return {"signals": {str(lid): list(s) for lid, s in sigs.items()},
                "staleness_ms": float(plane.staleness_ms())}

    return {"controller_claim": controller_claim, "set_policy": set_policy,
            "policy_info": policy_info, "signals": signals}


def mux_handlers(per_shard: Dict[int, Dict[str, Callable]],
                 extra: Optional[Dict[str, Callable]] = None) -> Dict:
    """Multiplex several shards' handler tables behind ONE control port.

    A multi-shard node (``hostproc --shards k``) runs k independent
    shard storages in one process but must not burn k listener ports and
    k orchestrator connections: every op gains an optional ``shard``
    field (default 0, so single-shard callers and old drills keep
    working verbatim) and dispatches to that shard's table.  An unknown
    shard or an op the shard does not serve is answered in-protocol.

    ``probe_all`` answers EVERY shard's probe in one round trip —
    ``{"shards": {"0": {probe..., "ok": true}, ...}}`` — so a manager
    watching a k-shard node pays one RPC per NODE per tick, not one per
    shard (the per-RPC GIL cost is the orchestrator probe loop's long
    pole; see bench/orchestrator_overhead.py).
    """
    shards = {int(q): dict(table) for q, table in per_shard.items()}

    def _dispatch(op: str) -> Callable[..., dict]:
        def call(shard: int = 0, **kw) -> dict:
            table = shards.get(int(shard))
            if table is None:
                raise ValueError(f"unknown shard {shard}")
            fn = table.get(op)
            if fn is None:
                raise ValueError(f"op {op!r} not served by shard {shard}")
            return fn(**kw) or {}
        return call

    def probe_all() -> dict:
        out: Dict[str, dict] = {}
        for q in sorted(shards):
            fn = shards[q].get("probe")
            if fn is None:
                continue
            try:
                out[str(q)] = {"ok": True, **(fn() or {})}
            except Exception as exc:  # noqa: BLE001 — per-shard verdict
                out[str(q)] = {"ok": False,
                               "error": f"{type(exc).__name__}: {exc}"}
        return {"shards": out}

    ops: set = set()
    for table in shards.values():
        ops.update(table)
    handlers: Dict[str, Callable] = {op: _dispatch(op) for op in ops}
    handlers["probe_all"] = probe_all
    handlers.update(extra or {})
    return handlers


def primary_handlers(storage, replicator=None,
                     extra: Optional[Dict[str, Callable]] = None) -> Dict:
    """Control ops a shard-primary process exposes.

    - ``probe``   — liveness + fence/lease state (the orchestrator's
      remote probe; also the operator's ``status`` peek).
    - ``fence``   — install a whole-storage fence epoch (the storage
      behind this port IS one shard of the cross-host topology).
    - ``lease``   — grant/renew the serving lease (distributed fence).
    - ``restore`` — operator unfence: lift the fence at ``epoch``.
    - ``ship``    — flush + one synchronous replication cycle (drills
      pin the replica byte-exact before a kill).
    """

    def probe() -> dict:
        out = {"role": "primary", "available": False}
        try:
            out["available"] = bool(storage.is_available())
        except Exception:  # noqa: BLE001 — an erroring probe reads dead
            pass
        out["fence"] = storage.fence_info()
        out["lease"] = storage.serving_lease_info()
        if replicator is not None:
            out["replication"] = {
                "frames_shipped": replicator.frames_shipped,
                "errors": replicator.errors,
                "link": replicator.link_state(),
            }
        return out

    def fence(epoch: int) -> dict:
        return {"epoch": storage.fence(int(epoch))}

    def lease(epoch: int, ttl_ms: float) -> dict:
        return storage.grant_serving_lease(int(epoch), float(ttl_ms))

    def restore(epoch: int) -> dict:
        storage.lift_fence(int(epoch))
        return {"epoch": int(epoch), "lease": storage.serving_lease_info()}

    def ship() -> dict:
        storage.flush()
        shipped = replicator.ship_now() if replicator is not None else 0
        return {"frames": int(shipped)}

    handlers = {"probe": probe, "fence": fence, "lease": lease,
                "restore": restore, "ship": ship}
    handlers.update(controller_handlers(storage))
    handlers.update(extra or {})
    return handlers


def standby_handlers(storage, receiver, repl_server=None,
                     mailbox: Optional[LeaseMailbox] = None,
                     on_promote: Optional[Callable[[], dict]] = None,
                     extra: Optional[Dict[str, Callable]] = None) -> Dict:
    """Control ops a standby process exposes.

    - ``probe``         — replica status (consistent/promoted/epoch) plus
      ``repl_rx_age_ms``: milliseconds since the standby last heard ANY
      replication frame or heartbeat from its primary.  This is the
      orchestrator's second witness — a primary the orchestrator cannot
      reach but whose heartbeats still land here is PARTITIONED-FROM-THE-
      ORCHESTRATOR, not dead, and must not be fenced or replaced.
    - ``lease_deposit`` / ``lease_fetch`` — the relay mailbox (above).
    - ``promote``       — the remote-promotion RPC; ``on_promote`` runs
      after a successful promote (hostproc starts a serving sidecar) and
      its fields join the response.
    - ``fence`` / ``lease`` / ``restore`` — the promoted storage's
      authority surface (after promotion this node IS the shard).
    """
    box = mailbox if mailbox is not None else LeaseMailbox()

    def probe() -> dict:
        out = {
            "role": "standby",
            "promoted": bool(receiver.promoted),
            "consistent": bool(receiver.consistent),
            "last_epoch": int(receiver.last_epoch),
            "frames_applied": int(receiver.frames_applied),
            "available": True,
        }
        if receiver.promoted:
            try:
                out["available"] = bool(storage.is_available())
            except Exception:  # noqa: BLE001
                out["available"] = False
        if repl_server is not None:
            age = repl_server.rx_age_ms()
            if age is not None:
                out["repl_rx_age_ms"] = round(age, 3)
        out["fence"] = storage.fence_info()
        out["lease"] = storage.serving_lease_info()
        return out

    def promote(force: bool = False) -> dict:
        receiver.promote(force=bool(force))
        out = {"last_epoch": int(receiver.last_epoch)}
        if on_promote is not None:
            out.update(on_promote() or {})
        return out

    def fence(epoch: int) -> dict:
        return {"epoch": storage.fence(int(epoch))}

    def lease(epoch: int, ttl_ms: float) -> dict:
        return storage.grant_serving_lease(int(epoch), float(ttl_ms))

    def restore(epoch: int) -> dict:
        storage.lift_fence(int(epoch))
        return {"epoch": int(epoch)}

    handlers = {"probe": probe, "promote": promote,
                "lease_deposit": box.deposit, "lease_fetch": box.fetch,
                "fence": fence, "lease": lease, "restore": restore}
    handlers.update(controller_handlers(storage))
    handlers.update(extra or {})
    return handlers
