"""Hot-standby receiver: applies replication frames to a shadow engine.

The standby owns an idle ``TpuBatchedStorage`` of the SAME geometry as
the primary (num_slots must match — rows address slots 1:1, like
checkpoints).  Frames apply as they arrive:

- limiter registrations replay in lid order (device decisions gather
  policy rows by lid, so lids must mean the same policy on both sides);
- state rows write straight into the shadow engine's HBM arrays
  (idempotent — a re-shipped row is a no-op);
- the epoch's LAST sub-frame carries the key->slot index journal, which
  is stashed (not applied): the standby's own index stays empty until
  promotion, so nothing can route traffic into half-replicated state.

Epoch accounting: frames must arrive in epoch order with no gaps.  A gap
(lost frames, a restarted primary) marks the receiver INCONSISTENT — it
keeps applying rows (they only ever move the shadow closer to the
primary) but refuses to promote until a ``full`` frame re-baselines the
stream.  The ``epoch_gap`` counter makes the event observable.  A STALE
delta frame (epoch at or before the newest applied — reordered or
duplicated delivery) is REFUSED outright: its rows are older truth and
applying them would regress newer state; the receiver counts it in
``reordered``, goes inconsistent, and waits for a full frame.  Full
frames always apply — they carry complete current state and re-baseline
unconditionally (including a restarted primary whose epochs reset).

``promote()`` is failover: rebuild the key->slot index from the last
replicated journal frame (``TpuBatchedStorage.promote_from_replica``),
bump the failover counter, and return the storage — now serving
decisions bit-identical to the oracle for every key whose last mutation
was at or before the promoted epoch (tests/test_replication.py drives
the differential).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ratelimiter_tpu.replication.wire import decode_frame


class ReplicationStateError(RuntimeError):
    """Promotion refused: the replica stream is gapped or unbootstrapped."""


class StandbyReceiver:
    """Applies frames to a shadow storage; promotes it on failover."""

    def __init__(self, storage, registry=None, start_epoch: int = 0):
        self.storage = storage
        self.last_epoch = int(start_epoch)
        # A receiver seeded from a checkpoint taken at epoch E starts
        # consistent at E; a fresh one must first see a full frame.
        self.consistent = start_epoch > 0
        self.promoted = False
        self._index_dump: Optional[Dict] = None
        self._lock = threading.Lock()
        # Promotion race guard: exactly ONE promote() wins; a concurrent
        # caller (orchestrator vs manual actuator POST) gets the typed
        # retryable refusal instead of double-rebuilding the index.
        self._promote_guard = threading.Lock()
        self._promote_inflight = False
        self._frames_applied = 0
        self.reordered = 0
        # Frames arriving AFTER promotion are refused outright: the
        # shadow is now the SERVING primary, and a zombie old-primary
        # still shipping deltas would silently overwrite live decisions
        # (the replication-side twin of the dispatch fence).
        self.refused_after_promote = 0
        if registry is not None:
            self._applied_epoch = registry.gauge(
                "ratelimiter.replication.applied_epoch",
                "Newest fully applied replication epoch")
            self._gaps = registry.counter(
                "ratelimiter.replication.epoch_gap",
                "Replication epoch gaps observed (stream inconsistent "
                "until the next full frame)")
            self._failovers = registry.counter(
                "ratelimiter.replication.failovers",
                "Standby promotions executed")
            self._reordered = registry.counter(
                "ratelimiter.replication.reordered",
                "Stale/reordered delta frames refused (stream "
                "inconsistent until the next full frame)")
        else:
            self._applied_epoch = self._gaps = self._failovers = None
            self._reordered = None

    # -- frame application ----------------------------------------------------
    def apply_bytes(self, data: bytes) -> None:
        self.apply(decode_frame(data))

    def apply(self, frame: Dict) -> None:
        with self._lock:
            if self.promoted:
                self.refused_after_promote += 1
                from ratelimiter_tpu.observability import flight_recorder

                flight_recorder().record(
                    "replication.frame_after_promote", coalesce_ms=1000.0,
                    epoch=int(frame.get("epoch", -1)))
                raise ReplicationStateError(
                    "this standby was promoted and is serving; a frame "
                    "arriving now is a zombie primary still shipping — "
                    "refused (fence the old primary)")
            if frame["num_slots"] != self.storage.engine.num_slots:
                raise ValueError(
                    f"frame geometry {frame['num_slots']} != standby "
                    f"{self.storage.engine.num_slots}; replication is "
                    "geometry-locked (like checkpoints)")
            epoch = int(frame["epoch"])
            if frame.get("full") and frame.get("seq", 0) == 0:
                # A full frame re-baselines the stream unconditionally.
                self.consistent = True
            elif epoch <= self.last_epoch and not frame.get("full"):
                # Stale delta (reordered/duplicated delivery): its rows
                # are OLDER truth — applying them would regress state the
                # newer epochs already wrote.  Refuse the frame, mark the
                # stream inconsistent, wait for a full re-baseline.
                self.consistent = False
                self.reordered += 1
                if self._reordered is not None:
                    self._reordered.increment()
                from ratelimiter_tpu.observability import flight_recorder

                flight_recorder().record(
                    "replication.reordered", coalesce_ms=1000.0,
                    epoch=epoch, applied_epoch=self.last_epoch)
                return
            elif epoch > self.last_epoch + 1 and not frame.get("full"):
                self.consistent = False
                if self._gaps is not None:
                    self._gaps.increment()
            if "limiters" in frame:
                self._register_limiters(frame["limiters"])
            for algo, payload in frame.get("algos", {}).items():
                self.storage.engine.write_rows(
                    algo, payload["slots"], payload["rows"])
            self._frames_applied += 1
            if frame.get("last"):
                self._index_dump = frame.get("index")
                self.last_epoch = epoch
                if self._applied_epoch is not None:
                    self._applied_epoch.set(epoch)

    def _register_limiters(self, limiters: Dict) -> None:
        """Replay the primary's limiter registrations (lid order) and
        verify rows already registered still agree.  A row that differs
        only in its RATES and carries a newer policy generation is a
        live policy update (ARCHITECTURE §15) and is applied at the
        primary's stamp — a promoted standby must serve the post-update
        generation; shape drift (algo/window) or an unexplained rate
        difference stays a hard error, since a drifted policy would
        silently mis-decide every replicated row of that tenant."""
        from ratelimiter_tpu.engine.checkpoint import apply_limiter_policies

        apply_limiter_policies(self.storage, limiters,
                               register_missing=True)

    # -- failover -------------------------------------------------------------
    def promote(self, force: bool = False):
        """Promote the shadow to serving primary; returns its storage.

        Exactly one caller wins: a promote racing an in-flight promote
        (auto-orchestrator vs manual actuator POST) gets the typed
        retryable ``PromotionInProgressError``; a promote arriving after
        one already completed gets ``ReplicationStateError`` (the storage
        is already serving — promoting twice would rebuild a live index
        under traffic).
        """
        from ratelimiter_tpu.storage.errors import PromotionInProgressError

        with self._promote_guard:
            if self._promote_inflight:
                raise PromotionInProgressError(
                    "another promotion of this standby is in flight; "
                    "exactly one wins")
            if self.promoted:
                raise ReplicationStateError(
                    "this standby is already promoted and serving")
            self._promote_inflight = True
        try:
            with self._lock:
                if not self.consistent and not force:
                    raise ReplicationStateError(
                        "replica stream is gapped/unbootstrapped; wait "
                        "for a full frame or promote(force=True) to "
                        "accept data loss beyond the last consistent "
                        "epoch")
                if self._index_dump is None and not force:
                    raise ReplicationStateError(
                        "no index journal replicated yet; nothing to "
                        "promote")
                if self._index_dump is not None:
                    self.storage.promote_from_replica(self._index_dump)
                self.promoted = True
                if self._failovers is not None:
                    self._failovers.increment()
                from ratelimiter_tpu.observability import flight_recorder

                flight_recorder().record("replication.promote",
                                         epoch=self.last_epoch,
                                         forced=force)
                return self.storage
        finally:
            with self._promote_guard:
                self._promote_inflight = False

    @property
    def frames_applied(self) -> int:
        return self._frames_applied
