"""Self-healing failover: the control-plane loop that automates PR 6's
one-shard-of-N promotion mechanism.

``shard_failover_drill`` proved the *mechanism* — kill one shard,
promote its standby, decisions bit-identical to the oracle — but a
human had to notice the failure and drive ``promote`` + the router
install.  At "millions of users" scale that window is an outage
("Designing Scalable Rate Limiting Systems" treats automated failover
as table stakes).  "When Two is Worse Than One" names exactly how the
naive automation fails: a false-positive health verdict promotes a
second primary next to a live one (uncoordinated over-admission), and a
flapping fault promotes/demotes in a loop.  So the orchestrator is an
explicit state machine with *fencing* and *hysteresis*, not a health
poll wired to promote():

    MONITORING ──consecutive probe failures──► SUSPECT
    SUSPECT ──probe heals──► MONITORING            (false_alarms += 1)
    SUSPECT ──still failing past hysteresis──► FENCING
    FENCING: bump the monotonic fencing epoch, install it on the
        storage being replaced (``TpuBatchedStorage.fence`` — its
        dispatch paths refuse with the typed ``FencedError``), fail the
        shard closed in the router, drop its replication stream
    FENCING ──► PROMOTING: drive ``StandbyReceiver.promote`` + router
        install with bounded retry/backoff; a failed promotion falls
        back to the next standby candidate or fails the shard closed
    PROMOTING ──promoted──► RESTORED: re-seed a FRESH standby for the
        promoted replica via a flat replication stream bootstrapped by
        a FULL frame — the system returns to N+1 standby coverage
    RESTORED ──fresh standby consistent──► MONITORING
    PROMOTING ──candidates exhausted──► FAILED (shard stays fail-closed
        until an operator intervenes; flight event records why)

Two safety rules fall out of the papers:

- **A transient blip never promotes.**  SUSPECT needs
  ``suspect_threshold`` *consecutive* probe failures to enter and must
  persist for ``hysteresis_ms`` before FENCING; a fault that heals
  inside the window increments ``false_alarms`` and nothing else.
- **A promotion never races the thing it replaces.**  The fence epoch
  is bumped and installed *before* ``promote`` runs, so a zombie
  primary's racing dispatches are refused with ``FencedError`` — and a
  promoted ``StandbyReceiver`` refuses late frames, closing the
  replication-side half of the same race.

The loop itself is single-threaded and tick-driven: ``tick()`` advances
every shard's state machine once (drills call it with a controlled
clock for deterministic timelines), ``start()`` runs it on a cadence
thread.  Re-seed replication streams are also driven from ``tick`` —
no hidden threads, so a drill's timeline is exact.

Metrics: ``ratelimiter.orchestrator.state`` (most-degraded shard state,
coded 0..5), ``.promotions``, ``.false_alarms``, ``.fence_rejected``
(decisions refused by fences this orchestrator installed), ``.reseeds``.
Flight events: one ``orchestrator.transition`` per state change (with
``shard``, ``from``/``to``), plus ``orchestrator.false_alarm``,
``orchestrator.standby_stale``, ``orchestrator.failed_closed``.
Status at ``GET /actuator/orchestrator``; wiring is config-gated OFF by
default (``ratelimiter.orchestrator.*``, service/wiring.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional

from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("replication.orchestrator")

MONITORING = "MONITORING"
SUSPECT = "SUSPECT"
FENCING = "FENCING"
PROMOTING = "PROMOTING"
RESTORED = "RESTORED"
FAILED = "FAILED"

# Gauge encoding: higher = more degraded; the exported gauge is the max
# over shards so a dashboard threshold on >0 catches any activity.
STATE_CODE = {MONITORING: 0, SUSPECT: 1, FENCING: 2, PROMOTING: 3,
              RESTORED: 4, FAILED: 5}


@dataclasses.dataclass(frozen=True)
class OrchestratorConfig:
    """Knobs, mirrored 1:1 by the ``ratelimiter.orchestrator.*`` props."""

    probe_interval_ms: float = 100.0
    # Consecutive probe failures before a shard turns SUSPECT.
    suspect_threshold: int = 3
    # A SUSPECT shard must stay failing this long before FENCING — the
    # flap damper: heal inside the window and nothing was promoted.
    hysteresis_ms: float = 500.0
    # Bounded promote retry/backoff per standby candidate.
    promote_retries: int = 3
    promote_backoff_ms: float = 50.0
    # Re-seed a fresh standby after promotion (N+1 restoration).
    reseed: bool = True
    # Distributed fence lease (cross-host topology, ARCHITECTURE §10c):
    # when > 0, the orchestrator grants each serving backend an epoch
    # lease of this TTL and renews it while probes answer — a primary
    # partitioned from the orchestrator (and from the standby-relayed
    # renewal path) SELF-FENCES within one TTL, which bounds the zombie's
    # over-admission without quorum machinery.  0 keeps PR 9's process-
    # local fencing (single-host topologies never pay).  Pick a TTL at
    # or above detection_budget_ms: a shorter one can expire a healthy
    # primary's lease during an ordinary flap-damped hysteresis window.
    fence_lease_ttl_ms: float = 0.0
    # Slack added when waiting out an unreachable zombie's lease before
    # promoting (covers grant-delivery latency; clocks are not assumed
    # synchronized — the wait runs entirely on the orchestrator's clock
    # from its own last-grant timestamp).
    fence_wait_slack_ms: float = 100.0

    @property
    def detection_budget_ms(self) -> float:
        """Upper bound on kill -> FENCING under on-schedule probes: the
        suspect threshold's probes plus the hysteresis window plus one
        probe interval of phase slack.  The drill asserts against it."""
        return (self.suspect_threshold + 1) * self.probe_interval_ms \
            + self.hysteresis_ms


class BackendLeaseChannel:
    """Serving-lease channel over a backend object held in-process (a
    local storage, or a replication/remote.py:RemoteBackend proxying a
    control port).  No relay leg — pair with a FanoutLeaseChannel
    (replication/remote.py) when a standby mailbox exists."""

    def __init__(self, backend):
        self.backend = backend

    def grant(self, epoch: int, ttl_ms: float) -> None:
        self.backend.grant_serving_lease(int(epoch), float(ttl_ms))


class _ShardWatch:
    """Per-shard state-machine bookkeeping."""

    __slots__ = ("state", "since", "since_wall_ms", "consecutive",
                 "probe_failures", "suspect_since", "promote_attempts",
                 "candidate_idx", "last_error", "lease_granted_at",
                 "fence_wait_until")

    def __init__(self, now: float):
        self.state = MONITORING
        self.since = now
        self.since_wall_ms = time.time_ns() // 1_000_000
        self.consecutive = 0
        self.probe_failures = 0
        self.suspect_since = 0.0
        self.promote_attempts = 0
        self.candidate_idx = 0
        self.last_error: Optional[str] = None
        # Orchestrator-clock stamp of the newest serving-lease grant (or
        # relay deposit) this shard's backend may hold — the FENCING wait
        # for an unreachable zombie runs from here.
        self.lease_granted_at = now
        # FENCING holds until this orchestrator-clock time (0 = no wait:
        # the explicit fence landed, or leases are off).
        self.fence_wait_until = 0.0


class FailoverOrchestrator:
    """Watches per-shard liveness; fences, promotes, and re-seeds.

    Parameters
    ----------
    router : ShardFailoverRouter over the sharded primary.
    standby_set : ShardStandbySet (the mesh the replicator feeds).
    replicator : ShardedReplicator shipping the per-shard streams (the
        orchestrator drops a shard's stream before promoting it, and
        reads per-shard link state to tell "standby gone" from
        "standby slow").
    standby_factory : zero-arg callable building one fresh flat standby
        storage of ``slots_per_shard`` geometry (the re-seed source).
        ``None`` disables re-seeding regardless of config.
    probe : ``probe(shard) -> bool`` liveness verdict.  Defaults to
        router shard health + the serving backend's ``is_available``.
        Drills inject deterministic probes.
    spares : optional ``{shard: [StandbyReceiver, ...]}`` fallback
        candidates tried (in order) when the primary standby's
        promotion fails.
    clock : monotonic-seconds source (injectable for deterministic
        drills); ``sleep`` likewise (promote backoff).
    """

    def __init__(self, router, standby_set, replicator,
                 standby_factory: Optional[Callable[[], object]] = None,
                 config: Optional[OrchestratorConfig] = None,
                 probe: Optional[Callable[[int], bool]] = None,
                 spares: Optional[Dict[int, List[object]]] = None,
                 lease_channels: Optional[Dict[int, object]] = None,
                 witness: Optional[Callable[[int], str]] = None,
                 witness_fresh_ms: Optional[float] = None,
                 repl_heartbeat_ms: Optional[float] = None,
                 registry=None, recorder=None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.router = router
        self.standby_set = standby_set
        self.replicator = replicator
        self.standby_factory = standby_factory
        self.cfg = config or OrchestratorConfig()
        self._probe = probe or self._default_probe
        self._spares = {int(q): list(v) for q, v in (spares or {}).items()}
        # Serving-lease channels (cfg.fence_lease_ttl_ms > 0): per-shard
        # objects with ``grant(epoch, ttl_ms)`` (direct to the serving
        # backend) and optionally ``deposit(epoch, ttl_ms)`` (park the
        # grant at the shard's standby for the primary to fetch over the
        # replication-side path — replication/control.py:LeaseMailbox).
        self._lease_channels = dict(lease_channels or {})
        # Second witness (cross-host): ``witness(q)`` answers "alive" /
        # "dead" / "unknown" from a vantage point OTHER than the
        # orchestrator's own probe link — in the reference topology, the
        # shard's standby reporting how recently the primary's
        # replication frames/heartbeats landed.  "alive" VETOES fencing:
        # a primary the orchestrator cannot reach but the standby can is
        # partitioned-from-the-orchestrator, not dead, and replacing it
        # is exactly the two-primaries trap.  None (default) keeps PR 9
        # behavior: the probe verdict alone drives the state machine.
        self._witness = witness
        self._clock = clock
        self._sleep = sleep
        self.n_shards = int(router.n_shards)
        now = clock()
        self._watch = [_ShardWatch(now) for _ in range(self.n_shards)]
        self.fence_epoch = 0
        self.promotions = 0
        self.false_alarms = 0
        self.reseeds = 0
        self.failed_closed = 0
        self.witness_vetoes = 0
        self.leases_granted = 0
        # Storages this orchestrator fenced (their rejected counts roll
        # up into the fence_rejected gauge) and per-shard re-seed
        # replication streams (flat Replicator, driven from tick()).
        self._fenced_storages: List[object] = []
        self._reseed_repl: Dict[int, object] = {}
        self._last_ship_errors = [0] * self.n_shards
        self._tick_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if recorder is not None:
            self._recorder = recorder
        else:
            from ratelimiter_tpu.observability import flight_recorder

            self._recorder = flight_recorder()
        if registry is not None:
            self._m_state = registry.gauge(
                "ratelimiter.orchestrator.state",
                "Most-degraded shard state (0 MONITORING, 1 SUSPECT, "
                "2 FENCING, 3 PROMOTING, 4 RESTORED, 5 FAILED)")
            self._m_promotions = registry.counter(
                "ratelimiter.orchestrator.promotions",
                "Automatic standby promotions executed")
            self._m_false = registry.counter(
                "ratelimiter.orchestrator.false_alarms",
                "SUSPECT shards that healed inside the hysteresis "
                "window (no promotion)")
            self._m_fence_rej = registry.gauge(
                "ratelimiter.orchestrator.fence_rejected",
                "Decisions refused (FencedError) by fences this "
                "orchestrator installed")
            self._m_reseeds = registry.counter(
                "ratelimiter.orchestrator.reseeds",
                "Fresh standbys re-seeded after a promotion (back to "
                "N+1)")
            self._m_vetoes = registry.counter(
                "ratelimiter.orchestrator.witness_vetoes",
                "Fencings vetoed by the standby witness (primary "
                "partitioned from the orchestrator, not dead)")
        else:
            self._m_state = self._m_promotions = None
            self._m_false = self._m_fence_rej = self._m_reseeds = None
            self._m_vetoes = None
        self._validate_timing(witness_fresh_ms, repl_heartbeat_ms)

    def _validate_timing(self, witness_fresh_ms: Optional[float],
                         repl_heartbeat_ms: Optional[float]) -> None:
        """Warn-at-construction for the two silent misconfigurations
        the cross-host drills keep tripping over (CHANGES.md PR 14):
        a ``witness_fresh_ms`` outside (replication heartbeat interval,
        detection budget) makes the second witness either read idle
        gaps as death or veto a real one, and a fence lease shorter
        than the detection budget can expire a HEALTHY primary's lease
        inside an ordinary flap-damped hysteresis window.  Both are
        tuning hazards, not contract violations — warn loudly (log +
        flight event), never raise."""
        budget = self.cfg.detection_budget_ms

        def _warn(problem: str, **fields) -> None:
            _log.warning("orchestrator misconfiguration: %s (%s)",
                         problem,
                         ", ".join(f"{k}={v}" for k, v in fields.items()))
            self._recorder.record("orchestrator.misconfigured",
                                  problem=problem, **fields)

        if witness_fresh_ms is not None:
            fresh = float(witness_fresh_ms)
            if repl_heartbeat_ms is not None \
                    and fresh <= float(repl_heartbeat_ms):
                _warn("witness_fresh_ms at or under the replication "
                      "heartbeat interval — idle replication gaps will "
                      "read as primary death and the witness can never "
                      "veto",
                      witness_fresh_ms=fresh,
                      repl_heartbeat_ms=float(repl_heartbeat_ms))
            if fresh >= budget:
                _warn("witness_fresh_ms at or past the detection "
                      "budget — a really-dead primary's last heartbeat "
                      "still reads fresh when FENCING is due, vetoing "
                      "the first fencing attempt",
                      witness_fresh_ms=fresh,
                      detection_budget_ms=budget)
        ttl = float(self.cfg.fence_lease_ttl_ms)
        if 0.0 < ttl < budget:
            _warn("fence_lease_ttl_ms under the detection budget — a "
                  "healthy primary's serving lease can expire during "
                  "an ordinary flap-damped hysteresis window",
                  fence_lease_ttl_ms=ttl, detection_budget_ms=budget)

    # -- probes ----------------------------------------------------------------
    def _default_probe(self, q: int) -> bool:
        """Non-blocking liveness verdict for one shard.

        The probe must never serialize with the decision pipeline — a
        device sync (``block_until_ready``) on a busy sharded primary
        waits out every in-flight dispatch, which turns "probing" into
        "stalling" (the idle-overhead gate in
        bench/orchestrator_overhead.py pins this).  So the primary is
        judged by signals that are already being produced: router shard
        health, and the per-shard replication stream's ship errors
        (a dead shard's row gather fails the next cut).  A promoted
        FLAT replacement has no sharded stream, so it gets the direct
        availability round-trip — it is the serving device for those
        keys, and a probe against a healthy flat engine is cheap.
        Deployments with richer signals (breaker failure streaks, lag
        SLOs, external health checks) inject their own ``probe``.
        """
        if self.router.shard_health().get(q) == "failed":
            return False
        backend = self.router._backend(q)
        if backend is None:
            return False
        if backend is not self.router.primary:
            try:
                return bool(backend.is_available())
            except Exception:  # noqa: BLE001 — erroring probe = failure
                return False
        if self.replicator is not None:
            errs = int(self.replicator.shard_errors[q])
            grew = errs > self._last_ship_errors[q]
            self._last_ship_errors[q] = errs
            if grew:
                return False
        return True

    def standby_ok(self, q: int) -> bool:
        """Is shard q's standby promotable?  Folds the receiver's
        consistency with the replication link's liveness verdict — a
        DEAD link means the replica is STALE ("standby gone"), and
        promoting onto it silently loses every epoch since the link
        died, which is worse than staying fail-closed."""
        rx = self.standby_set.receivers[q]
        if rx.promoted or not rx.consistent:
            return False
        if self.replicator is not None \
                and self.replicator.shard_link_state(q) == "dead":
            return False
        return True

    # -- state machine ---------------------------------------------------------
    def _transition(self, q: int, to: str, **fields) -> None:
        w = self._watch[q]
        if w.state == to:
            return
        self._recorder.record("orchestrator.transition", shard=q,
                              **{"from": w.state, "to": to}, **fields)
        _log.info("orchestrator shard %d: %s -> %s %s", q, w.state, to,
                  fields or "")
        w.state = to
        w.since = self._clock()
        w.since_wall_ms = time.time_ns() // 1_000_000

    def tick(self) -> None:
        """Advance every shard's state machine once (one probe round)."""
        with self._tick_lock:
            now = self._clock()
            for q in range(self.n_shards):
                try:
                    self._tick_shard(q, now)
                except Exception as exc:  # noqa: BLE001 — loop survives
                    self._watch[q].last_error = str(exc)[:200]
                    _log.warning("orchestrator tick failed for shard %d: "
                                 "%s", q, exc)
            self._export_metrics()

    def _tick_shard(self, q: int, now: float) -> None:
        w = self._watch[q]
        if w.state == MONITORING:
            self._drive_reseed_stream(q)
            if self._probe(q):
                w.consecutive = 0
                self._lease_grant(q)
                return
            w.consecutive += 1
            w.probe_failures += 1
            self._lease_relay(q)
            if w.consecutive >= self.cfg.suspect_threshold:
                w.suspect_since = now
                self._transition(q, SUSPECT,
                                 consecutive=w.consecutive)
        elif w.state == SUSPECT:
            if self._probe(q):
                # Healed inside the window: flap damped, nothing
                # promoted, nothing fenced.
                w.consecutive = 0
                self.false_alarms += 1
                if self._m_false is not None:
                    self._m_false.increment()
                self._recorder.record("orchestrator.false_alarm", shard=q,
                                      suspect_ms=round(
                                          (now - w.suspect_since) * 1000, 1))
                self._lease_grant(q)
                self._transition(q, MONITORING)
                return
            w.consecutive += 1
            w.probe_failures += 1
            self._lease_relay(q)
            if (now - w.suspect_since) * 1000.0 >= self.cfg.hysteresis_ms:
                if self._witness_alive(q):
                    # Second witness overrules the probe: the primary's
                    # replication heartbeats still land at its standby,
                    # so it is partitioned FROM US, not dead.  Fencing
                    # or promoting now would raise a second primary next
                    # to a live one — hold, keep its lease relayed.
                    self.witness_vetoes += 1
                    if self._m_vetoes is not None:
                        self._m_vetoes.increment()
                    self._recorder.record("orchestrator.witness_veto",
                                          shard=q)
                    w.consecutive = 0
                    self._transition(q, MONITORING)
                    return
                self._transition(q, FENCING)
                self._fence(q)
                self._maybe_enter_promoting(q, now)
        elif w.state == FENCING:
            # Waiting out an unreachable zombie's serving lease before
            # installing its replacement (the explicit fence RPC could
            # not be delivered — the lease expiry IS the fence).
            self._maybe_enter_promoting(q, now)
        elif w.state == PROMOTING:
            self._try_promote(q)
        elif w.state == RESTORED:
            self._drive_reseed_stream(q)
            rx = self.standby_set.receivers[q]
            if rx.consistent and not rx.promoted:
                self.reseeds += 1
                if self._m_reseeds is not None:
                    self._m_reseeds.increment()
                self._recorder.record("orchestrator.reseeded", shard=q,
                                      epoch=rx.last_epoch)
                self._transition(q, MONITORING)
        # FAILED is terminal until an operator intervenes: auto-
        # unfencing a shard the machine already declared dead twice
        # is exactly the two-primaries trap.

    # -- serving leases (the distributed fence; cfg.fence_lease_ttl_ms) --------
    def _lease_grant(self, q: int, epoch: Optional[int] = None) -> None:
        """Renew shard q's serving lease: direct grant to the serving
        backend plus (when the channel supports it) a relay deposit at
        the shard's standby.  Epoch = current fence generation + 1, so a
        replacement promoted after any future fence always carries a
        strictly higher epoch than every lease granted before it."""
        ch = self._lease_channels.get(q)
        if ch is None or self.cfg.fence_lease_ttl_ms <= 0:
            return
        ttl = self.cfg.fence_lease_ttl_ms
        ep = int(self.fence_epoch + 1 if epoch is None else epoch)
        ok = False
        try:
            ch.grant(ep, ttl)
            ok = True
        except Exception as exc:  # noqa: BLE001 — a failed renewal is
            # exactly what the lease is for; the backend runs down.
            self._watch[q].last_error = str(exc)[:200]
        dep = getattr(ch, "deposit", None)
        if dep is not None:
            try:
                dep(ep, ttl)
                ok = True
            except Exception:  # noqa: BLE001 — relay is best-effort
                pass
        if ok:
            self._watch[q].lease_granted_at = self._clock()
            self.leases_granted += 1

    def _lease_relay(self, q: int) -> None:
        """Probe failed but the shard may still be alive (partition on
        OUR link): while the standby witness vouches for it, keep its
        lease renewed through the relay mailbox only — the primary
        fetches it over the replication-side path it still has.  Without
        a witness (or with a dead/unknown verdict) nothing is renewed
        and the lease runs down toward self-fence."""
        ch = self._lease_channels.get(q)
        if ch is None or self.cfg.fence_lease_ttl_ms <= 0:
            return
        dep = getattr(ch, "deposit", None)
        if dep is None or not self._witness_alive(q):
            return
        try:
            dep(int(self.fence_epoch + 1), self.cfg.fence_lease_ttl_ms)
            self._watch[q].lease_granted_at = self._clock()
            self.leases_granted += 1
        except Exception:  # noqa: BLE001 — relay is best-effort
            pass

    def _witness_alive(self, q: int) -> bool:
        if self._witness is None:
            return False
        try:
            return self._witness(q) == "alive"
        except Exception:  # noqa: BLE001 — an erroring witness proves
            # nothing; only a positive "alive" vetoes.
            return False

    def _maybe_enter_promoting(self, q: int, now: float) -> None:
        """Leave FENCING for PROMOTING once it is SAFE: immediately when
        the explicit fence landed, otherwise only after the zombie's
        last-granted serving lease has provably expired (orchestrator
        clock, from our own grant stamp, plus slack)."""
        w = self._watch[q]
        if now < w.fence_wait_until:
            return
        w.promote_attempts = 0
        w.candidate_idx = 0
        self._transition(q, PROMOTING)
        self._try_promote(q)

    # -- FENCING ---------------------------------------------------------------
    def _fence(self, q: int) -> None:
        """Bump the monotonic fencing epoch and install it on whatever
        currently serves shard q, THEN fail the shard closed in the
        router and drop its replication stream.  Order matters: once
        this returns, no path — routed or direct — admits traffic for
        q's keys on the old backend."""
        self.fence_epoch += 1
        old = self.router.replacements.get(q)
        installed = False
        try:
            if old is not None:
                # A previously-promoted flat replacement died: fence the
                # whole flat storage.
                old.fence(self.fence_epoch)
                self._fenced_storages.append(old)
            else:
                # First failover of this shard: scope the fence to q on
                # the shard's primary — survivors keep serving.  A
                # cross-host directory resolves per-shard backends via
                # ``shard_primary`` (each is wholly one shard, so the
                # scoping is a no-op there); the in-process router keeps
                # the single sharded primary.
                prim = (self.router.shard_primary(q)
                        if hasattr(self.router, "shard_primary")
                        else self.router.primary)
                prim.fence(self.fence_epoch, shards=(q,))
                if prim not in self._fenced_storages:
                    self._fenced_storages.append(prim)
            installed = True
        except Exception as exc:  # noqa: BLE001 — a dead or PARTITIONED
            # primary may refuse (or never receive) the fence call; the
            # router's fail-closed deny still bounds routed admission,
            # and with serving leases on, the zombie's own lease expiry
            # bounds its direct admission (the wait below).
            _log.warning("fence install on shard %d backend failed: %s",
                         q, exc)
        w = self._watch[q]
        w.fence_wait_until = 0.0
        if not installed and self.cfg.fence_lease_ttl_ms > 0:
            # The fence RPC could not be delivered: the zombie's serving
            # lease IS the fence.  Hold PROMOTING until every grant we
            # (or our relay deposits) issued has provably expired —
            # measured on OUR clock from OUR last-grant stamp, so no
            # cross-host clock agreement is assumed.
            w.fence_wait_until = w.lease_granted_at + (
                self.cfg.fence_lease_ttl_ms
                + self.cfg.fence_wait_slack_ms) / 1000.0
            self._recorder.record(
                "orchestrator.fence_wait", shard=q,
                wait_ms=round(max(
                    w.fence_wait_until - self._clock(), 0.0) * 1000.0, 1))
        self.router.fail_shard(q)
        if self.replicator is not None:
            # Stop shipping into the standby we are about to promote —
            # and quiesce q's re-seed stream if this is a re-kill.
            repl = self._reseed_repl.pop(q, None)
            if repl is not None:
                try:
                    repl.stop()
                    repl.log.detach()
                except Exception:  # noqa: BLE001 — best effort
                    pass
            self.replicator.drop_shard(q)
        self._recorder.record("orchestrator.fenced", shard=q,
                              epoch=self.fence_epoch)

    # -- PROMOTING -------------------------------------------------------------
    def _candidates(self, q: int):
        return [self.standby_set.receivers[q]] + self._spares.get(q, [])

    def _try_promote(self, q: int) -> None:
        w = self._watch[q]
        candidates = self._candidates(q)
        while w.candidate_idx < len(candidates):
            rx = candidates[w.candidate_idx]
            if w.candidate_idx == 0 and not self.standby_ok(q):
                # Primary standby is stale (gapped stream or dead link):
                # promoting onto it loses epochs — skip to spares.
                self._recorder.record("orchestrator.standby_stale",
                                      shard=q)
                w.candidate_idx += 1
                continue
            for attempt in range(self.cfg.promote_retries + 1):
                try:
                    promoted = rx.promote()
                except Exception as exc:  # noqa: BLE001 — bounded retry
                    w.last_error = str(exc)[:200]
                    from ratelimiter_tpu.storage.errors import (
                        PromotionInProgressError,
                    )

                    if isinstance(exc, PromotionInProgressError):
                        # A manual promote is racing us and will win (or
                        # fail); retry next tick rather than burning the
                        # backoff budget against a held lock.
                        return
                    if getattr(rx, "promoted", False):
                        # A concurrent manual promote already won on this
                        # receiver: exactly one promotion ran — adopt its
                        # result and finish the install ourselves.
                        promoted = rx.storage
                    else:
                        if attempt < self.cfg.promote_retries:
                            self._sleep(self.cfg.promote_backoff_ms
                                        * (2 ** attempt) / 1000.0)
                        continue
                self.router.install_replacement(q, promoted)
                self.promotions += 1
                if self._m_promotions is not None:
                    self._m_promotions.increment()
                self._recorder.record("orchestrator.promoted", shard=q,
                                      epoch=rx.last_epoch,
                                      fence_epoch=self.fence_epoch)
                self._lease_adopt(q, promoted)
                if self.cfg.reseed and self.standby_factory is not None:
                    self._transition(q, RESTORED)
                    self._start_reseed(q, promoted)
                else:
                    self._transition(q, MONITORING)
                self._watch[q].consecutive = 0
                return
            w.candidate_idx += 1  # this candidate is exhausted
        # Every candidate failed: the shard fails closed (bounded
        # under-admission — router keeps denying) until an operator
        # intervenes.
        self.failed_closed += 1
        self._recorder.record("orchestrator.failed_closed", shard=q,
                              error=w.last_error)
        self._transition(q, FAILED)

    def _lease_adopt(self, q: int, backend) -> None:
        """A replacement now serves shard q: hand it a fresh serving
        lease at a STRICTLY higher epoch than every lease the zombie
        ever held (fence_epoch was bumped in _fence, so +1 is past the
        zombie's generation), and point q's lease channel at it so the
        MONITORING renewals flow to the right process."""
        if self.cfg.fence_lease_ttl_ms <= 0 \
                or q not in self._lease_channels:
            return
        grant = getattr(backend, "grant_serving_lease", None)
        if grant is None:
            return
        try:
            grant(self.fence_epoch + 1, self.cfg.fence_lease_ttl_ms)
            self._lease_channels[q] = BackendLeaseChannel(backend)
            self._watch[q].lease_granted_at = self._clock()
            self.leases_granted += 1
        except Exception as exc:  # noqa: BLE001 — the next MONITORING
            # tick retries through the (now swapped or original) channel
            _log.warning("serving-lease grant to shard %d replacement "
                         "failed: %s", q, exc)

    # -- RESTORED (re-seed) ----------------------------------------------------
    def _start_reseed(self, q: int, promoted_storage) -> None:
        """Attach a flat replication stream to the promoted storage and
        point it at a FRESH standby; the first cut ships a FULL frame
        (flat-log bootstrap), returning shard q to N+1 coverage.  The
        stream is driven from tick() — no hidden thread."""
        from ratelimiter_tpu.replication.log import ReplicationLog
        from ratelimiter_tpu.replication.replicator import Replicator
        from ratelimiter_tpu.replication.standby import StandbyReceiver
        from ratelimiter_tpu.replication.transport import InProcessSink

        fresh = self.standby_factory()
        rx = StandbyReceiver(fresh)
        repl = Replicator(ReplicationLog(promoted_storage),
                          InProcessSink(rx))
        self._reseed_repl[q] = repl
        self.standby_set.replace(q, fresh, rx)

    def _drive_reseed_stream(self, q: int) -> None:
        repl = self._reseed_repl.get(q)
        if repl is not None:
            try:
                repl.ship_now()
            except Exception as exc:  # noqa: BLE001 — stream survives
                _log.warning("re-seed ship for shard %d failed: %s", q, exc)

    # -- operator unfence (the exit from terminal FAILED) ----------------------
    def unfence(self, q: int) -> Dict:
        """Recover a terminal ``FAILED`` shard: the operator has verified
        the primary's shard is actually healthy (the kill was a false
        positive, or the fault was repaired in place), so lift the
        fence(s) covering shard ``q``, repair the router back to the
        primary, replace the shard's standby with a fresh one, resume
        its replication stream (FULL re-baseline), and reset the watch
        to MONITORING.  Exposed at ``POST /actuator/orchestrator/
        unfence`` — previously this state was only recoverable from a
        Python shell (``lift_fence`` + manual router surgery).

        Refused (``ValueError``) unless the shard is FAILED: auto-unlike
        paths out of any live state would reopen the two-primaries trap
        this machine exists to close."""
        q = int(q)
        with self._tick_lock:
            w = self._watch[q]
            if w.state != FAILED:
                raise ValueError(
                    f"shard {q} is {w.state}, not FAILED; unfence is the "
                    "operator exit from the terminal state only")
            for storage in self._fenced_storages:
                try:
                    info = storage.fence_info()
                    if info["all"]:
                        storage.lift_fence(info["epoch"])
                    elif q in set(info["shards"]):
                        storage.lift_fence(info["epoch"], shards=(q,))
                except Exception as exc:  # noqa: BLE001 — best effort:
                    # a truly-dead backend may refuse even the lift; the
                    # router repair below still restores routing.
                    _log.warning("unfence: lift on a fenced backend "
                                 "failed for shard %d: %s", q, exc)
            self.router.repair_shard(q)
            # Restore N+1 coverage: fresh standby + resumed stream
            # (the fence dropped this shard's stream; its old standby
            # may be promoted, stale, or mid-failed-promotion).
            if self.standby_factory is not None \
                    and self.replicator is not None:
                from ratelimiter_tpu.replication.standby import (
                    StandbyReceiver,
                )
                from ratelimiter_tpu.replication.transport import (
                    InProcessSink,
                )

                fresh = self.standby_factory()
                rx = StandbyReceiver(fresh)
                self.standby_set.replace(q, fresh, rx)
                self.replicator.restore_shard(q, sink=InProcessSink(rx))
            w.consecutive = 0
            w.candidate_idx = 0
            w.promote_attempts = 0
            w.last_error = None
            w.fence_wait_until = 0.0
            self._transition(q, MONITORING)
            # Re-arm the repaired primary's serving lease (its old one
            # is void — self-fenced or explicitly fenced — and lift_fence
            # above cleared the fence, so a fresh generation re-enables
            # the expiry bound before traffic routes back).
            prim = (self.router.shard_primary(q)
                    if hasattr(self.router, "shard_primary")
                    else self.router.primary)
            if self.cfg.fence_lease_ttl_ms > 0 \
                    and q in self._lease_channels:
                self._lease_channels[q] = BackendLeaseChannel(prim)
                self._lease_grant(q)
            self._recorder.record("orchestrator.unfenced", shard=q,
                                  epoch=self.fence_epoch)
            self._export_metrics()
            return {"shard": q, "state": MONITORING,
                    "fence_epoch": self.fence_epoch}

    def set_lease_channel(self, q: int, channel) -> None:
        """Swap shard ``q``'s serving-lease channel (the fleet
        autopilot re-points the relay leg at a freshly re-seeded
        standby's mailbox after an automated replacement)."""
        with self._tick_lock:
            self._lease_channels[int(q)] = channel

    # -- metrics / status ------------------------------------------------------
    def _export_metrics(self) -> None:
        if self._m_state is not None:
            self._m_state.set(float(max(
                STATE_CODE[w.state] for w in self._watch)))
        if self._m_fence_rej is not None:
            self._m_fence_rej.set(float(self.total_fence_rejected()))

    def total_fence_rejected(self) -> int:
        return sum(int(getattr(s, "fence_rejected", 0))
                   for s in self._fenced_storages)

    def status(self) -> Dict:
        now = self._clock()
        return {
            "fence_epoch": self.fence_epoch,
            "promotions": self.promotions,
            "false_alarms": self.false_alarms,
            "reseeds": self.reseeds,
            "failed_closed": self.failed_closed,
            "witness_vetoes": self.witness_vetoes,
            "leases_granted": self.leases_granted,
            "fence_rejected": self.total_fence_rejected(),
            "config": dataclasses.asdict(self.cfg),
            "shards": {
                q: {
                    "state": w.state,
                    "since_ms": w.since_wall_ms,
                    "in_state_ms": round((now - w.since) * 1000.0, 3),
                    "consecutive_failures": w.consecutive,
                    "probe_failures": w.probe_failures,
                    "last_error": w.last_error,
                }
                for q, w in enumerate(self._watch)
            },
        }

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> "FailoverOrchestrator":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="failover-orchestrator", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.probe_interval_ms / 1000.0):
            try:
                self.tick()
            except Exception as exc:  # noqa: BLE001 — loop survives
                _log.warning("orchestrator tick failed: %s", exc)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._stop.clear()

    def close(self) -> None:
        self.stop()
        for repl in self._reseed_repl.values():
            try:
                repl.close()
            except Exception:  # noqa: BLE001 — best effort
                pass
        self._reseed_repl.clear()
