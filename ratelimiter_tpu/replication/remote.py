"""Cross-host topology adapters: run the PR 9 ``FailoverOrchestrator``
against shard primaries and standbys living in OTHER processes.

The orchestrator's contracts are duck-typed — a "backend" fences and
grants leases, a "receiver" reports consistency and promotes, a
"router" books which backend serves each shard.  These classes satisfy
those contracts over :mod:`replication.control` RPC, so the same state
machine (hysteresis, witness veto, fence-or-wait, bounded promote
retry) drives a multi-process deployment unchanged:

- :class:`RemoteBackend` — a storage behind a control port.  ``fence``/
  ``grant_serving_lease``/``lift_fence`` forward over RPC; a transport
  fault raises (the orchestrator's fence path then falls back to the
  lease-expiry wait — an unreachable zombie cannot be fenced directly,
  so its lease TTL is the fence).
- :class:`RemoteReceiver` — a StandbyReceiver behind a control port.
  ``promoted``/``consistent``/``last_epoch`` are short-TTL cached probe
  reads; ``promote()`` is the remote-promotion RPC and returns a
  :class:`RemoteBackend` for the newly serving storage (plus
  ``serve_port``, the sidecar the promoted node opened — clients
  re-point there).
- :class:`RemoteShardDirectory` — the router-duck for the orchestrator
  process.  It does NOT route decisions (cross-host clients route
  themselves); it keeps the authoritative serving map the orchestrator
  mutates (fail/replace/repair) and operators read.
- :class:`FanoutLeaseChannel` — serving-lease channel with the relay
  leg: ``grant`` renews the serving backend directly, ``deposit`` parks
  the grant in the standby's :class:`~.control.LeaseMailbox` for the
  primary to fetch over the replication-side link it still has when the
  orchestrator's direct path is partitioned.
- :func:`standby_witness` — the second-witness verdict from the
  standby's vantage point: a primary whose replication frames or
  heartbeats landed within ``fresh_ms`` is "alive" no matter what the
  orchestrator's own probe link says.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ratelimiter_tpu.replication.control import ControlClient, ControlError
from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("replication.remote")


def _wall_ms() -> int:
    return time.time_ns() // 1_000_000


def parse_ready(info: dict) -> dict:
    """Validate a hostproc ready line and normalize pre-fleet fields.

    The explicit ``lid_base`` field replaces the lids-start-at-1
    convention: when the line carries registered lids at all, the base
    must be present AND agree with ``min(lids)`` — a launcher that
    would have silently mis-addressed every limiter fails loudly here
    instead.  Lines from pre-fleet nodes (no ``shards``/``version``)
    normalize to one v0 shard.
    """
    if not isinstance(info, dict) or not info.get("ready"):
        raise ValueError(f"not a hostproc ready line: {info!r}")
    if "control_port" not in info:
        raise ValueError("ready line missing control_port")
    role = info.get("role")
    if role not in ("primary", "standby"):
        raise ValueError(f"ready line has unknown role {role!r}")
    lids = info.get("lids") or []
    flat = [lid for entry in lids
            for lid in (entry if isinstance(entry, list) else [entry])]
    if flat:
        base = info.get("lid_base")
        if base is None:
            raise ValueError(
                "ready line registered lids but carries no lid_base — "
                "refusing to assume the lids-start-at-1 convention")
        if min(flat) != int(base):
            raise ValueError(
                f"ready line lid_base {base} disagrees with min(lids) "
                f"{min(flat)}")
    info.setdefault("shards", 1)
    info.setdefault("version", "v0")
    return info


class RemoteBackend:
    """Duck-typed storage proxy over a control port.

    ``shard`` addresses one shard of a multi-shard node (hostproc
    ``--shards k`` multiplexes k shard storages behind one control
    port); None keeps the bare ops for single-shard nodes and raw
    handler tables."""

    def __init__(self, ctl: ControlClient, label: str = "",
                 shard: Optional[int] = None):
        self.ctl = ctl
        self.shard = shard
        self.label = label or f"{ctl.host}:{ctl.port}"
        if shard is not None:
            self.label += f"/s{int(shard)}"

    def _kw(self, **kw) -> dict:
        if self.shard is not None:
            kw["shard"] = int(self.shard)
        return kw

    def fence(self, epoch: int, shards=None) -> int:
        """Install a whole-storage fence.  ``shards`` is accepted for
        interface parity and ignored: the storage behind this proxy IS
        exactly one shard of the cross-host topology, so whole-storage
        and shard-scoped fencing coincide."""
        del shards
        self.ctl.call_ok("fence", **self._kw(epoch=int(epoch)))
        return int(epoch)

    def lift_fence(self, epoch: int, shards=None) -> None:
        del shards
        self.ctl.call_ok("restore", **self._kw(epoch=int(epoch)))

    def grant_serving_lease(self, epoch: int, ttl_ms: float) -> dict:
        return self.ctl.call_ok("lease", **self._kw(epoch=int(epoch),
                                                    ttl_ms=float(ttl_ms)))

    def retarget(self, host: str, port: int,
                 interval_ms: Optional[float] = None,
                 timeout_s: float = 30.0) -> dict:
        """Re-point this shard's replication stream at a new standby
        listener and synchronously ship a full re-baseline frame (the
        fleet autopilot's re-seed primitive).  Generous timeout: the
        receiving side jit-compiles its first frame apply."""
        kw = self._kw(host=str(host), port=int(port))
        if interval_ms is not None:
            kw["interval_ms"] = float(interval_ms)
        return self.ctl.call_ok("retarget", timeout=float(timeout_s), **kw)

    def fence_info(self) -> dict:
        return self.ctl.call_ok("probe", **self._kw()).get("fence", {})

    def serving_lease_info(self) -> dict:
        return self.ctl.call_ok("probe", **self._kw()).get("lease", {})

    def is_available(self) -> bool:
        try:
            resp = self.ctl.call("probe", **self._kw())
        except ControlError:
            return False
        return bool(resp.get("ok")) and bool(resp.get("available"))

    def probe(self) -> Optional[dict]:
        """Raw probe payload, or None when unreachable."""
        return self.ctl.try_call("probe", **self._kw())

    # -- fleet control plane (ARCHITECTURE §15) ------------------------------
    # Thin forwarders for the controller-leadership ops every node role
    # serves; control/fleet.py drives these through its member set.

    def controller_claim(self, node: str, epoch: int,
                         ttl_ms: float = 3000.0) -> dict:
        """Claim/renew controller authority on this node's seat.  A
        refusal is IN-PROTOCOL (granted=False + the seat's epoch), so
        callers distinguish "outvoted" from "unreachable"."""
        return self.ctl.call_ok("controller_claim", **self._kw(
            node=str(node), epoch=int(epoch), ttl_ms=float(ttl_ms)))

    def set_policy_rows(self, rows: Dict, epoch: int,
                        node: str = "") -> dict:
        """Apply a batch of policy rows at the leader's generation
        stamps; stale-epoch and stale-generation refusals come back
        in-protocol (``applied=False``)."""
        return self.ctl.call_ok("set_policy", **self._kw(
            rows=dict(rows), epoch=int(epoch), node=str(node)))

    def policy_info(self) -> dict:
        """Policy table generation + rows + the controller seat."""
        return self.ctl.call_ok("policy_info", **self._kw())

    def signals(self, window_ms: int = 2000) -> dict:
        """The node's serialized per-lid UsageSignals + staleness."""
        return self.ctl.call_ok("signals",
                                **self._kw(window_ms=int(window_ms)))

    def close(self) -> None:
        self.ctl.close()


class RemoteReceiver:
    """Duck-typed StandbyReceiver proxy over a control port.

    Status attributes refresh over RPC with a short cache (one control
    round trip answers all three — ``standby_ok`` reads two attributes
    back to back and must not pay two probes).  While the standby is
    UNREACHABLE the cached status decays to not-promotable (consistent
    False), which is the safe verdict: promoting onto a standby we
    cannot even probe would be flying blind.
    """

    def __init__(self, ctl: ControlClient, cache_ttl_s: float = 0.05,
                 promote_timeout_s: float = 30.0,
                 shard: Optional[int] = None):
        self.ctl = ctl
        self.shard = shard
        self.cache_ttl_s = float(cache_ttl_s)
        self.promote_timeout_s = float(promote_timeout_s)
        self._status: dict = {}
        self._status_at = 0.0
        self._lock = threading.Lock()
        # Filled by promote(): the serving port the promoted node opened.
        self.serve_port: Optional[int] = None
        self.promote_info: dict = {}

    def _kw(self, **kw) -> dict:
        if self.shard is not None:
            kw["shard"] = int(self.shard)
        return kw

    def _refresh(self) -> dict:
        with self._lock:
            now = time.monotonic()
            if now - self._status_at >= self.cache_ttl_s:
                resp = self.ctl.try_call("probe", **self._kw())
                if resp is not None and resp.get("ok"):
                    self._status = resp
                else:
                    # Unreachable: decay to the fail-safe verdict.
                    self._status = dict(self._status,
                                        consistent=False, reachable=False)
                self._status_at = now
            return self._status

    @property
    def promoted(self) -> bool:
        return bool(self._refresh().get("promoted"))

    @property
    def consistent(self) -> bool:
        return bool(self._refresh().get("consistent"))

    @property
    def last_epoch(self) -> int:
        return int(self._refresh().get("last_epoch", 0))

    def rx_age_ms(self) -> Optional[float]:
        return self._refresh().get("repl_rx_age_ms")

    def promote(self, force: bool = False) -> RemoteBackend:
        """The remote-promotion RPC.  Raises on refusal (gapped stream,
        already promoted, promotion in flight — the orchestrator's
        bounded retry handles it) and returns a RemoteBackend for the
        storage that is now serving."""
        resp = self.ctl.call("promote", timeout=self.promote_timeout_s,
                             **self._kw(force=bool(force)))
        if not resp.get("ok"):
            raise RuntimeError(
                f"remote promote refused by {self.ctl.host}:"
                f"{self.ctl.port}: {resp.get('error')}")
        self.promote_info = resp
        self.serve_port = resp.get("serve_port")
        with self._lock:
            self._status = dict(self._status, promoted=True)
            self._status_at = time.monotonic()
        return RemoteBackend(self.ctl, label="promoted-standby",
                             shard=self.shard)

    def close(self) -> None:
        self.ctl.close()


class RemoteStandbySet:
    """Standby-mesh duck over remote receivers (``receivers[q]`` is all
    the orchestrator reads; re-seeding a NEW remote standby process is
    an operator/deployment action, so ``replace`` only swaps the
    in-memory entry)."""

    def __init__(self, receivers: List[RemoteReceiver]):
        self.n_shards = len(receivers)
        self.receivers = list(receivers)

    def replace(self, shard: int, storage, receiver) -> None:
        del storage
        self.receivers[int(shard)] = receiver

    def close(self, except_shards: tuple = ()) -> None:
        del except_shards
        for rx in self.receivers:
            try:
                rx.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


class RemoteShardDirectory:
    """The authoritative serving map for a cross-host cell.

    Satisfies the orchestrator's router contract (``shard_primary``,
    ``fail_shard``, ``install_replacement``, ``replacements``,
    ``shard_health``/``shard_status``, ``repair_shard``) without any
    decision routing: in a multi-process topology clients hold their own
    connections and re-point on promotion; this directory is what tells
    them (and /actuator/health) where each shard's keyspace lives."""

    def __init__(self, primaries: Dict[int, RemoteBackend]):
        self.n_shards = len(primaries)
        if sorted(primaries) != list(range(self.n_shards)):
            raise ValueError("primaries must be dense 0..n_shards-1")
        self.primaries = {int(q): b for q, b in primaries.items()}
        self.replacements: Dict[int, object] = {}
        self.failed: set = set()
        self._lock = threading.Lock()
        now_w, now_m = _wall_ms(), time.monotonic()
        self._since_wall = [now_w] * self.n_shards
        self._since_mono = [now_m] * self.n_shards

    # The orchestrator reads router.primary only through the
    # shard_primary hook when one exists; expose shard 0's for parity.
    @property
    def primary(self):
        return self.primaries[0]

    def shard_primary(self, q: int):
        return self.primaries[int(q)]

    def _mark(self, q: int) -> None:
        self._since_wall[q] = _wall_ms()
        self._since_mono[q] = time.monotonic()

    def fail_shard(self, shard: int) -> None:
        with self._lock:
            self.failed.add(int(shard))
            self._mark(int(shard))
        from ratelimiter_tpu.observability import flight_recorder

        flight_recorder().record("shard.failed", shard=int(shard))

    def install_replacement(self, shard: int, backend) -> None:
        with self._lock:
            self.replacements[int(shard)] = backend
            self.failed.discard(int(shard))
            self._mark(int(shard))
        from ratelimiter_tpu.observability import flight_recorder

        flight_recorder().record("shard.promoted", shard=int(shard))

    def repair_shard(self, shard: int) -> None:
        with self._lock:
            self.failed.discard(int(shard))
            self.replacements.pop(int(shard), None)
            self._mark(int(shard))
        from ratelimiter_tpu.observability import flight_recorder

        flight_recorder().record("shard.repaired", shard=int(shard))

    def serving(self, q: int):
        """Where shard q's keyspace currently lives (None = fail-closed:
        failed, replacement not yet installed)."""
        return self._backend(int(q))

    def _backend(self, q: int):
        with self._lock:
            if q in self.failed:
                return None
            return self.replacements.get(q, self.primaries[q])

    def shard_health(self) -> Dict[int, str]:
        with self._lock:
            return {q: ("failed" if q in self.failed
                        else "promoted" if q in self.replacements
                        else "active")
                    for q in range(self.n_shards)}

    def shard_status(self) -> Dict[int, Dict]:
        now = time.monotonic()
        health = self.shard_health()
        with self._lock:
            return {q: {"state": health[q],
                        "since_ms": self._since_wall[q],
                        "in_state_ms": round(
                            (now - self._since_mono[q]) * 1000.0, 3)}
                    for q in range(self.n_shards)}

    def degraded_shards(self) -> List[int]:
        with self._lock:
            return sorted(self.failed | set(self.replacements))

    def is_available(self) -> bool:
        return all(self.primaries[q].is_available()
                   for q in range(self.n_shards))

    def close(self) -> None:
        for b in self.primaries.values():
            b.close()
        with self._lock:
            reps = list(self.replacements.values())
        for r in reps:
            try:
                r.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


class FanoutLeaseChannel:
    """Serving-lease channel with both legs: ``grant`` direct to the
    serving backend, ``deposit`` into the shard's standby mailbox (the
    relay the primary fetches from when the orchestrator cannot reach it
    directly — replication/control.py:LeaseMailbox)."""

    def __init__(self, backend, standby_ctl: ControlClient,
                 shard: Optional[int] = None):
        self.backend = backend
        self.standby_ctl = standby_ctl
        self.shard = shard

    def grant(self, epoch: int, ttl_ms: float) -> None:
        self.backend.grant_serving_lease(int(epoch), float(ttl_ms))

    def deposit(self, epoch: int, ttl_ms: float) -> None:
        kw = {} if self.shard is None else {"shard": int(self.shard)}
        self.standby_ctl.call_ok("lease_deposit", epoch=int(epoch),
                                 ttl_ms=float(ttl_ms), **kw)


def standby_witness(standby_ctls: Dict[int, object],
                    fresh_ms: float = 400.0) -> Callable[[int], str]:
    """Build the orchestrator's second-witness callable: shard q's
    verdict comes from its STANDBY's control port — "alive" when the
    primary's replication frames/heartbeats landed within ``fresh_ms``,
    "dead" when they stopped longer ago, "unknown" when the standby
    itself is unreachable or has never heard from the primary.  Only
    "alive" vetoes a fencing (an unknown vantage point proves nothing).

    Entries are a bare :class:`ControlClient` (single-shard standby) or
    a ``(ControlClient, shard)`` tuple addressing one shard of a multi-
    shard node.  The dict is read AT CALL TIME, so the fleet autopilot
    retargets a shard's witness by mutating the entry in place — no
    orchestrator rewiring.

    ``fresh_ms`` must comfortably exceed the primary's replication
    heartbeat interval (or idle gaps read as death) and sit below the
    orchestrator's detection budget (or a real death is vetoed once
    before the staleness shows)."""

    def witness(q: int) -> str:
        entry = standby_ctls.get(int(q))
        if entry is None:
            return "unknown"
        if isinstance(entry, tuple):
            ctl, shard = entry
            kw = {"shard": int(shard)}
        else:
            ctl, kw = entry, {}
        # One retry: an "unknown" verdict cannot veto, so a single
        # dropped poll against a live standby must not let a healthy-
        # but-unreachable primary slip through to FENCING.
        resp = ctl.try_call("probe", **kw)
        if resp is None or not resp.get("ok"):
            resp = ctl.try_call("probe", **kw)
        if resp is None or not resp.get("ok"):
            return "unknown"
        age = resp.get("repl_rx_age_ms")
        if age is None:
            return "unknown"
        return "alive" if float(age) <= float(fresh_ms) else "dead"

    return witness
