"""Async replicator: ships epoch frames from a ReplicationLog to a sink.

Replication is strictly OFF the decision path ("When Two is Worse Than
One", PAPERS.md — naive synchronous redundancy degrades tail latency):
the hot path only marks a dirty mask; this thread wakes every
``interval_ms``, cuts an epoch, and pushes the frames through the sink.
A slow or dead standby therefore costs the primary nothing but memory
for the dirty mask — decisions never wait on the wire.

Failure model: a sink error re-marks the failed frames' slots into the
journal and requests a FULL next frame (the standby's epoch stream now
has a gap it will refuse to promote across until re-baselined), bumps
the error counter, and keeps looping — asynchronous replication degrades
to "standby lags further", never to "primary stops deciding".

Metrics (metrics/registry.py, scraped by /actuator/metrics):
  ratelimiter.replication.lag_ms    gauge   age of the oldest unshipped
                                            mutation at the last cut
  ratelimiter.replication.epoch     gauge   newest epoch cut
  ratelimiter.replication.frames    counter frames shipped
  ratelimiter.replication.bytes     counter encoded bytes shipped
  ratelimiter.replication.errors    counter ship failures
"""

from __future__ import annotations

import threading
import time

from ratelimiter_tpu.replication.wire import encode_frame
from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("replication")


class Replicator:
    def __init__(self, log, sink, interval_ms: float = 200.0,
                 registry=None):
        self.log = log
        self.sink = sink
        self.interval_ms = float(interval_ms)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._ship_lock = threading.Lock()
        self.frames_shipped = 0
        self.bytes_shipped = 0
        self.errors = 0
        if registry is not None:
            self._m_lag = registry.gauge(
                "ratelimiter.replication.lag_ms",
                "Age (ms) of the oldest unreplicated mutation at the "
                "last epoch cut")
            self._m_epoch = registry.gauge(
                "ratelimiter.replication.epoch",
                "Newest replication epoch cut on the primary")
            self._m_frames = registry.counter(
                "ratelimiter.replication.frames",
                "Replication frames shipped to the standby")
            self._m_bytes = registry.counter(
                "ratelimiter.replication.bytes",
                "Encoded replication bytes shipped")
            self._m_errors = registry.counter(
                "ratelimiter.replication.errors",
                "Replication ship failures (frames re-marked, next "
                "frame full)")
        else:
            self._m_lag = self._m_epoch = None
            self._m_frames = self._m_bytes = self._m_errors = None

    # -- one synchronous ship cycle (tests drive this deterministically) ------
    def ship_now(self) -> int:
        """Cut an epoch and ship it; returns frames shipped (0 = clean)."""
        with self._ship_lock:
            # A sink that reconnected since the last cycle may be talking
            # to a RESTARTED standby with empty state: re-baseline with a
            # full frame before shipping more deltas into a gap.
            consume = getattr(self.sink, "consume_reconnected", None)
            if consume is not None and consume():
                _log.warning("replication link reconnected; re-baselining "
                             "with a full frame")
                self.log.request_full()
            frames = self.log.cut()
            if self._m_lag is not None:
                self._m_lag.set(self.log.last_cut_lag_ms)
            if not frames:
                return 0
            if self._m_epoch is not None:
                self._m_epoch.set(self.log.epoch)
            shipped = 0
            try:
                for i, frame in enumerate(frames):
                    data = encode_frame(frame)
                    self.sink.send(data)
                    shipped += 1
                    self.frames_shipped += 1
                    self.bytes_shipped += len(data)
                    if self._m_frames is not None:
                        self._m_frames.increment()
                        self._m_bytes.add(len(data))
            except Exception:
                # Unshipped rows go back in the journal; the epoch the
                # standby half-saw is re-baselined by a full next frame.
                self.errors += 1
                if self._m_errors is not None:
                    self._m_errors.increment()
                self.log.remark(frames[shipped:])
                self.log.request_full()
                raise
            return shipped

    # -- background loop ------------------------------------------------------
    def start(self) -> "Replicator":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="replicator", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_ms / 1000.0):
            try:
                self.ship_now()
            except Exception as exc:  # noqa: BLE001 — async loop survives
                _log.warning("replication ship failed: %s (will retry "
                             "with a full frame)", exc)

    def stop(self, final_ship: bool = False) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_ship:
            try:
                self.ship_now()
            except Exception as exc:  # noqa: BLE001 — best effort drain
                _log.warning("final replication ship failed: %s", exc)

    def close(self) -> None:
        self.stop()
        self.log.detach()
        if hasattr(self.sink, "close"):
            self.sink.close()

    def lag_ms(self) -> float:
        """Current lag estimate: the last cut's measured lag, or — when
        mutations are pending — the time since the interval began."""
        return self.log.last_cut_lag_ms
