"""Async replicator: ships epoch frames from a ReplicationLog to a sink.

Replication is strictly OFF the decision path ("When Two is Worse Than
One", PAPERS.md — naive synchronous redundancy degrades tail latency):
the hot path only marks a dirty mask; this thread wakes every
``interval_ms``, cuts an epoch, and pushes the frames through the sink.
A slow or dead standby therefore costs the primary nothing but memory
for the dirty mask — decisions never wait on the wire.

Backpressure: the background loop runs a CUTTER thread (cuts + encodes
epochs into a byte-bounded in-flight queue) and a SENDER thread (drains
the queue through the sink).  When the standby link is slower than the
delta rate the queue fills; the cutter then SKIPS cuts instead of
queueing more — the marks stay in the journal (a fixed-size bitmap) and
coalesce into the next epoch that does ship.  Host memory is bounded by
``max_queue_bytes`` no matter how slow the link gets, and every skipped
cut counts in ``ratelimiter.replication.coalesced``.

Failure model: a sink error re-marks the failed frames' slots into the
journal and requests a FULL next frame (the standby's epoch stream now
has a gap it will refuse to promote across until re-baselined), bumps
the error counter, and keeps looping — asynchronous replication degrades
to "standby lags further", never to "primary stops deciding".

Metrics (metrics/registry.py, scraped by /actuator/metrics):
  ratelimiter.replication.lag_ms    gauge   age of the oldest unshipped
                                            mutation at the last cut
  ratelimiter.replication.epoch     gauge   newest epoch cut
  ratelimiter.replication.frames    counter frames shipped
  ratelimiter.replication.bytes     counter encoded bytes shipped
  ratelimiter.replication.errors    counter ship failures
  ratelimiter.replication.coalesced counter cuts skipped against a full
                                            in-flight queue (the deltas
                                            coalesced in the journal)
"""

from __future__ import annotations

import collections
import threading
import time

from ratelimiter_tpu.replication.wire import encode_frame
from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("replication")

# In-flight encoded epochs the background pipeline may hold before the
# cutter starts coalescing: four wire-budget frames' worth.
DEFAULT_MAX_QUEUE_BYTES = 64 << 20


class Replicator:
    def __init__(self, log, sink, interval_ms: float = 200.0,
                 registry=None, max_queue_bytes: int = DEFAULT_MAX_QUEUE_BYTES):
        self.log = log
        self.sink = sink
        self.interval_ms = float(interval_ms)
        self.max_queue_bytes = int(max_queue_bytes)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._sender: threading.Thread | None = None
        self._ship_lock = threading.Lock()
        # Orders cut-and-enqueue/send atomically: without it, a ship_now
        # racing the background cutter could cut epoch N+2 and send it
        # ahead of a still-queued N+1 — the receiver would then refuse
        # N+1 as stale and force a needless full re-baseline.
        self._cut_lock = threading.Lock()
        # In-flight epochs: deque of (frames, encoded, bytes) triples.
        self._queue = collections.deque()
        self._queue_cv = threading.Condition()
        self._queue_bytes = 0
        self.frames_shipped = 0
        self.bytes_shipped = 0
        self.errors = 0
        self.coalesced = 0
        if registry is not None:
            self._m_lag = registry.gauge(
                "ratelimiter.replication.lag_ms",
                "Age (ms) of the oldest unreplicated mutation at the "
                "last epoch cut")
            self._m_epoch = registry.gauge(
                "ratelimiter.replication.epoch",
                "Newest replication epoch cut on the primary")
            self._m_frames = registry.counter(
                "ratelimiter.replication.frames",
                "Replication frames shipped to the standby")
            self._m_bytes = registry.counter(
                "ratelimiter.replication.bytes",
                "Encoded replication bytes shipped")
            self._m_errors = registry.counter(
                "ratelimiter.replication.errors",
                "Replication ship failures (frames re-marked, next "
                "frame full)")
            self._m_coalesced = registry.counter(
                "ratelimiter.replication.coalesced",
                "Cuts skipped against a full in-flight queue; their "
                "deltas coalesced in the journal (slow standby link)")
            self._m_link = registry.gauge(
                "ratelimiter.replication.link_up",
                "1 while the standby link answers (sends/heartbeats "
                "acked); 0 once it is marked DEAD")
            self._m_link.set(1.0)
        else:
            self._m_lag = self._m_epoch = None
            self._m_frames = self._m_bytes = self._m_errors = None
            self._m_coalesced = None
            self._m_link = None
        self._link_last = None

    # -- link liveness ---------------------------------------------------------
    def link_state(self) -> str:
        """The sink's view of the standby link (``unknown`` for sinks
        that do not track one)."""
        fn = getattr(self.sink, "link_state", None)
        return fn() if fn is not None else "unknown"

    def _observe_link(self) -> None:
        """Record DEAD<->UP transitions: gauge + flight event.  A DEAD
        link means the standby behind it is going STALE — the signal the
        failover orchestrator uses to refuse promoting onto it."""
        state = self.link_state()
        if state == self._link_last or state == "unknown":
            return
        from ratelimiter_tpu.observability import flight_recorder

        if state == "dead":
            if self._m_link is not None:
                self._m_link.set(0.0)
            flight_recorder().record("replication.link_dead")
            _log.warning("replication link marked DEAD (standby gone, "
                         "not merely slow); its replica is going stale")
        elif state == "up":
            if self._m_link is not None:
                self._m_link.set(1.0)
            if self._link_last == "dead":
                flight_recorder().record("replication.link_restored")
        self._link_last = state

    # -- one synchronous ship cycle (tests drive this deterministically) ------
    def ship_now(self) -> int:
        """Drain any queued epochs, then cut a fresh one and ship it;
        returns frames shipped this call (0 = clean)."""
        with self._ship_lock, self._cut_lock:
            shipped = self._drain_queue_locked()
            # A sink that reconnected since the last cycle may be talking
            # to a RESTARTED standby with empty state: re-baseline with a
            # full frame before shipping more deltas into a gap.
            consume = getattr(self.sink, "consume_reconnected", None)
            if consume is not None and consume():
                _log.warning("replication link reconnected; re-baselining "
                             "with a full frame")
                self.log.request_full()
            frames = self.log.cut()
            if self._m_lag is not None:
                self._m_lag.set(self.log.last_cut_lag_ms)
            if not frames:
                return shipped
            if self._m_epoch is not None:
                self._m_epoch.set(self.log.epoch)
            return shipped + self._send_frames_locked(
                frames, [encode_frame(f) for f in frames])

    def _send_frames_locked(self, frames, encoded) -> int:
        """Send one epoch's frames (caller holds _ship_lock); on failure
        re-mark the unshipped tail and request a full re-baseline."""
        shipped = 0
        try:
            for data in encoded:
                self.sink.send(data)
                shipped += 1
                self.frames_shipped += 1
                self.bytes_shipped += len(data)
                if self._m_frames is not None:
                    self._m_frames.increment()
                    self._m_bytes.add(len(data))
        except Exception:
            # Unshipped rows go back in the journal; the epoch the
            # standby half-saw is re-baselined by a full next frame.
            self.errors += 1
            if self._m_errors is not None:
                self._m_errors.increment()
            self.log.remark(frames[shipped:])
            self.log.request_full()
            self._observe_link()
            raise
        self._observe_link()
        return shipped

    def _drain_queue_locked(self) -> int:
        shipped = 0
        while True:
            with self._queue_cv:
                if not self._queue:
                    return shipped
                frames, encoded, nbytes = self._queue.popleft()
                self._queue_bytes -= nbytes
                self._queue_cv.notify_all()
            shipped += self._send_frames_locked(frames, encoded)

    # -- background pipeline (cutter + sender) --------------------------------
    def start(self) -> "Replicator":
        if self._thread is None:
            self._sender = threading.Thread(
                target=self._send_loop, name="replicator-send", daemon=True)
            self._sender.start()
            self._thread = threading.Thread(
                target=self._run, name="replicator", daemon=True)
            self._thread.start()
        return self

    def queue_bytes(self) -> int:
        with self._queue_cv:
            return self._queue_bytes

    def _run(self) -> None:
        while not self._stop.wait(self.interval_ms / 1000.0):
            try:
                self._cut_cycle()
            except Exception as exc:  # noqa: BLE001 — async loop survives
                _log.warning("replication cut failed: %s (will retry)", exc)

    def _cut_cycle(self) -> None:
        with self._queue_cv:
            backlogged = self._queue_bytes >= self.max_queue_bytes
        if backlogged:
            # Slow link: skip the cut entirely — the journal keeps the
            # marks (fixed-size bitmap) and the next unskipped cut ships
            # one coalesced delta.  Host memory stays bounded.
            self.coalesced += 1
            if self._m_coalesced is not None:
                self._m_coalesced.increment()
            from ratelimiter_tpu.observability import flight_recorder

            flight_recorder().record("replication.coalesced",
                                     coalesce_ms=2000.0)
            return
        with self._cut_lock:
            consume = getattr(self.sink, "consume_reconnected", None)
            if consume is not None and consume():
                _log.warning("replication link reconnected; re-baselining "
                             "with a full frame")
                self.log.request_full()
            frames = self.log.cut()
            if self._m_lag is not None:
                self._m_lag.set(self.log.last_cut_lag_ms)
            if not frames:
                # Idle cycle: heartbeat the link so a standby that died
                # SILENTLY (partition, power cut — no RST) is detected
                # even with no deltas flowing.
                hb = getattr(self.sink, "heartbeat", None)
                if hb is not None:
                    hb()
                self._observe_link()
                return
            if self._m_epoch is not None:
                self._m_epoch.set(self.log.epoch)
            encoded = [encode_frame(f) for f in frames]
            nbytes = sum(len(d) for d in encoded)
            with self._queue_cv:
                self._queue.append((frames, encoded, nbytes))
                self._queue_bytes += nbytes
                self._queue_cv.notify_all()

    def _send_loop(self) -> None:
        while True:
            with self._queue_cv:
                while not self._queue and not self._stop.is_set():
                    self._queue_cv.wait(0.2)
                if not self._queue and self._stop.is_set():
                    return
                if not self._queue:
                    continue
            try:
                with self._ship_lock:
                    # Re-check under the ship lock: ship_now may have
                    # drained the queue while we were acquiring.
                    with self._queue_cv:
                        if not self._queue:
                            continue
                        frames, encoded, nbytes = self._queue.popleft()
                        self._queue_bytes -= nbytes
                        self._queue_cv.notify_all()
                    self._send_frames_locked(frames, encoded)
            except Exception as exc:  # noqa: BLE001 — sender survives
                _log.warning("replication ship failed: %s (will retry "
                             "with a full frame)", exc)

    def stop(self, final_ship: bool = False) -> None:
        self._stop.set()
        with self._queue_cv:
            self._queue_cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._sender is not None:
            self._sender.join(timeout=5.0)
            self._sender = None
        if final_ship:
            try:
                self.ship_now()
            except Exception as exc:  # noqa: BLE001 — best effort drain
                _log.warning("final replication ship failed: %s", exc)
        self._stop.clear()

    def close(self) -> None:
        self.stop()
        self.log.detach()
        if hasattr(self.sink, "close"):
            self.sink.close()

    def lag_ms(self) -> float:
        """Current lag estimate: the last cut's measured lag, or — when
        mutations are pending — the time since the interval began."""
        return self.log.last_cut_lag_ms
