"""Replication transports: in-process and sidecar-style TCP.

The TCP framing deliberately mirrors the decision sidecar
(service/sidecar.py) — ``u32 length | payload`` little-endian, one ack
byte back per frame — so any environment that can deploy the sidecar
can deploy a standby next to it.  The ack is what makes ship failures
*detectable*: a frame the standby could not apply (geometry mismatch,
decode error) acks nonzero, and the replicator's failure path re-marks
the delta and re-baselines with a full frame.

``InProcessSink`` round-trips frames through encode/decode even though
it could hand the dict over directly — the in-process path (tests, the
chaos drill) then exercises the exact bytes the TCP path ships.
"""

from __future__ import annotations

import random
import socket
import socketserver
import struct
import threading
import time

ACK_OK = 0
ACK_ERROR = 1

_LEN = struct.Struct("<I")

# Link states a sink reports (``link_state()``): UNKNOWN before first
# contact, UP after a successful send/heartbeat ack, DEAD after
# ``dead_after`` consecutive ack failures.  The distinction the
# orchestrator needs: a DEAD link means the standby behind it is STALE
# ("standby gone"), not merely behind ("standby slow") — promoting onto
# it loses every epoch since the link died.
LINK_UNKNOWN = "unknown"
LINK_UP = "up"
LINK_DEAD = "dead"


class InProcessSink:
    """Feeds a StandbyReceiver in the same process (tests, drills)."""

    def __init__(self, receiver):
        self.receiver = receiver

    def send(self, data: bytes) -> None:
        self.receiver.apply_bytes(data)

    def heartbeat(self) -> bool:
        return True

    def link_state(self) -> str:
        return LINK_UP

    def close(self) -> None:
        pass


class TeeSink:
    """Fan out frames to several sinks (e.g. a standby plus a frame
    archive in the checkpoint-catch-up tests).  All sinks get every
    frame; the first failure propagates after the fan-out completes."""

    def __init__(self, *sinks):
        self.sinks = list(sinks)

    def send(self, data: bytes) -> None:
        err = None
        for sink in self.sinks:
            try:
                sink.send(data)
            except Exception as exc:  # noqa: BLE001 — re-raised below
                err = err or exc
        if err is not None:
            raise err

    def close(self) -> None:
        for sink in self.sinks:
            if hasattr(sink, "close"):
                sink.close()


class FrameArchive:
    """A sink that just records encoded frames (replay / catch-up)."""

    def __init__(self):
        self.frames: list = []

    def send(self, data: bytes) -> None:
        self.frames.append(data)


class SocketSink:
    """Primary-side TCP sender with per-frame acks and bounded retry.

    Connects lazily.  A broken pipe (standby restart, flaky link) does
    NOT error the ship cycle immediately: ``send`` retries the frame up
    to ``max_retries`` times with capped exponential backoff + jitter,
    reconnecting each time — a blip never errors out of the replication
    thread, only a sustained outage does (and the replicator's existing
    failure path then re-marks + requests a full frame).  Frames are
    idempotent (absolute rows, monotonic epochs), so a retry after a
    lost ack can only re-apply what the standby already holds.

    Any reconnect raises :meth:`consume_reconnected` once: the standby
    behind the fresh connection may be a RESTARTED process with empty
    state, so the replicator re-baselines with a ``full`` frame on its
    next cycle instead of shipping deltas into a void (the receiver's
    gap detection would catch it anyway — the full frame makes recovery
    immediate rather than promoted-blocked).
    """

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 max_retries: int = 4, backoff_ms: float = 50.0,
                 backoff_cap_ms: float = 2000.0, seed: int = 0,
                 ack_timeout: float = 5.0, dead_after: int = 2):
        self.host = host
        self.port = int(port)
        self.timeout = float(timeout)
        self.max_retries = int(max_retries)
        self.backoff_ms = float(backoff_ms)
        self.backoff_cap_ms = float(backoff_cap_ms)
        # Ack deadline: a standby that accepted the TCP bytes but never
        # acks (process wedged, half-open connection after a silent
        # death) must fail the send within ``ack_timeout`` seconds — the
        # old behavior waited the full connect timeout per attempt, so a
        # silently-dead standby just grew the byte-bounded queue until
        # coalescing with nothing marking the link as gone.
        self.ack_timeout = float(ack_timeout)
        # Consecutive fully-failed sends/heartbeats before the link
        # reports DEAD (one blip must not flap the gauge).
        self.dead_after = max(int(dead_after), 1)
        self.reconnects = 0
        self._rng = random.Random(seed)
        self._sock: socket.socket | None = None
        self._ever_connected = False
        self._reconnected = False
        self._consec_failures = 0
        self._link = LINK_UNKNOWN
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Post-connect ops (sendall + ack recv) run under the tighter
        # ack deadline, not the connect timeout.
        sock.settimeout(self.ack_timeout)
        if self._ever_connected:
            self._reconnected = True
            self.reconnects += 1
        self._ever_connected = True
        return sock

    # -- link liveness --------------------------------------------------------
    def _note_outcome(self, ok: bool) -> None:
        """Caller holds the lock."""
        if ok:
            self._consec_failures = 0
            self._link = LINK_UP
        else:
            self._consec_failures += 1
            if self._consec_failures >= self.dead_after:
                self._link = LINK_DEAD

    def link_state(self) -> str:
        # Deliberately LOCK-FREE (one atomic attribute read): the send
        # path holds the main lock through its whole retry/backoff loop
        # — many seconds against a partitioned standby — and the control
        # plane (probe handlers, /actuator status, the orchestrator)
        # polls this as a liveness signal.  A liveness read that blocks
        # on the data plane would wedge exactly when it matters most.
        return self._link

    def heartbeat(self) -> bool:
        """One zero-length liveness frame; the standby acks it without
        applying anything.  Bounded by ``ack_timeout``.  The replicator
        sends one on every idle cycle so a standby that dies SILENTLY
        mid-stream (no RST — a network partition, a hard power cut) is
        detected even when no deltas are flowing."""
        with self._lock:
            try:
                if self._sock is None:
                    self._sock = self._connect()
                self._sock.sendall(_LEN.pack(0))
                ack = self._recv_exact(1)
                ok = ack[0] == ACK_OK
            except OSError:
                self._drop()
                ok = False
            self._note_outcome(ok)
            return ok

    def consume_reconnected(self) -> bool:
        """True once per reconnect since the last call — the replicator
        re-baselines with a full frame when it sees it."""
        with self._lock:
            seen = self._reconnected
            self._reconnected = False
            return seen

    def send(self, data: bytes) -> None:
        payload = _LEN.pack(len(data)) + data
        with self._lock:
            last_exc: OSError | None = None
            for attempt in range(self.max_retries + 1):
                if attempt:
                    delay_ms = min(self.backoff_cap_ms,
                                   self.backoff_ms * (2 ** (attempt - 1)))
                    # Jitter in [0.5x, 1.5x): reconnect stampedes from
                    # many primaries must not synchronize.
                    time.sleep(delay_ms * (0.5 + self._rng.random())
                               / 1000.0)
                try:
                    if self._sock is None:
                        self._sock = self._connect()
                    self._sock.sendall(payload)
                    ack = self._recv_exact(1)
                except OSError as exc:
                    self._drop()
                    last_exc = exc
                    continue
                if ack[0] != ACK_OK:
                    # The standby REJECTED the frame (geometry mismatch,
                    # decode error) — not a link fault; retrying the same
                    # bytes cannot help.  Let the replicator's failure
                    # path re-mark and re-baseline.
                    self._drop()
                    self._note_outcome(True)  # it answered: link is alive
                    raise ConnectionError(
                        f"standby rejected replication frame (ack={ack[0]})")
                self._note_outcome(True)
                return
            self._note_outcome(False)
            raise ConnectionError(
                f"replication link to {self.host}:{self.port} down after "
                f"{self.max_retries + 1} attempts") from last_exc

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("standby closed connection")
            buf += chunk
        return buf

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._drop()


class ReplicationServer:
    """Standby-side TCP listener feeding a StandbyReceiver."""

    def __init__(self, receiver, host: str = "0.0.0.0", port: int = 0):
        self.receiver = receiver
        # Monotonic stamp of the LAST complete frame OR heartbeat from
        # the primary — the standby-side witness signal: an orchestrator
        # that cannot reach the primary asks this standby "when did you
        # last hear from it?" to tell a dead primary from one merely
        # partitioned off the orchestrator's own link (control.py
        # standby_handlers reports it as ``repl_rx_age_ms``).
        self._last_rx_mono: float | None = None
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock: socket.socket = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                buf = b""
                while True:
                    try:
                        chunk = sock.recv(1 << 20)
                    except OSError:
                        return
                    if not chunk:
                        return
                    buf += chunk
                    out = b""
                    while len(buf) >= _LEN.size:
                        (length,) = _LEN.unpack_from(buf)
                        if len(buf) < _LEN.size + length:
                            break
                        frame = buf[_LEN.size:_LEN.size + length]
                        buf = buf[_LEN.size + length:]
                        outer._last_rx_mono = time.monotonic()
                        if length == 0:
                            # Heartbeat: liveness ack, nothing to apply.
                            out += bytes([ACK_OK])
                            continue
                        try:
                            outer.receiver.apply_bytes(frame)
                            out += bytes([ACK_OK])
                        except Exception:  # noqa: BLE001 — ack the failure
                            out += bytes([ACK_ERROR])
                    if out:
                        try:
                            sock.sendall(out)
                        except OSError:
                            return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="replication-rx",
            daemon=True)

    def rx_age_ms(self) -> float | None:
        """Milliseconds since the primary's last frame or heartbeat
        landed here (None before first contact)."""
        last = self._last_rx_mono
        if last is None:
            return None
        return (time.monotonic() - last) * 1000.0

    def start(self) -> "ReplicationServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
