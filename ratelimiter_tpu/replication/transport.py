"""Replication transports: in-process and sidecar-style TCP.

The TCP framing deliberately mirrors the decision sidecar
(service/sidecar.py) — ``u32 length | payload`` little-endian, one ack
byte back per frame — so any environment that can deploy the sidecar
can deploy a standby next to it.  The ack is what makes ship failures
*detectable*: a frame the standby could not apply (geometry mismatch,
decode error) acks nonzero, and the replicator's failure path re-marks
the delta and re-baselines with a full frame.

``InProcessSink`` round-trips frames through encode/decode even though
it could hand the dict over directly — the in-process path (tests, the
chaos drill) then exercises the exact bytes the TCP path ships.
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading

ACK_OK = 0
ACK_ERROR = 1

_LEN = struct.Struct("<I")


class InProcessSink:
    """Feeds a StandbyReceiver in the same process (tests, drills)."""

    def __init__(self, receiver):
        self.receiver = receiver

    def send(self, data: bytes) -> None:
        self.receiver.apply_bytes(data)

    def close(self) -> None:
        pass


class TeeSink:
    """Fan out frames to several sinks (e.g. a standby plus a frame
    archive in the checkpoint-catch-up tests).  All sinks get every
    frame; the first failure propagates after the fan-out completes."""

    def __init__(self, *sinks):
        self.sinks = list(sinks)

    def send(self, data: bytes) -> None:
        err = None
        for sink in self.sinks:
            try:
                sink.send(data)
            except Exception as exc:  # noqa: BLE001 — re-raised below
                err = err or exc
        if err is not None:
            raise err

    def close(self) -> None:
        for sink in self.sinks:
            if hasattr(sink, "close"):
                sink.close()


class FrameArchive:
    """A sink that just records encoded frames (replay / catch-up)."""

    def __init__(self):
        self.frames: list = []

    def send(self, data: bytes) -> None:
        self.frames.append(data)


class SocketSink:
    """Primary-side TCP sender with per-frame acks.

    Connects lazily and reconnects on the next send after a failure, so
    a standby restart does not wedge the replicator permanently.
    """

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.host = host
        self.port = int(port)
        self.timeout = float(timeout)
        self._sock: socket.socket | None = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def send(self, data: bytes) -> None:
        with self._lock:
            try:
                if self._sock is None:
                    self._sock = self._connect()
                self._sock.sendall(_LEN.pack(len(data)) + data)
                ack = self._recv_exact(1)
            except OSError:
                self._drop()
                raise
            if ack[0] != ACK_OK:
                self._drop()
                raise ConnectionError(
                    f"standby rejected replication frame (ack={ack[0]})")

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("standby closed connection")
            buf += chunk
        return buf

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._lock:
            self._drop()


class ReplicationServer:
    """Standby-side TCP listener feeding a StandbyReceiver."""

    def __init__(self, receiver, host: str = "0.0.0.0", port: int = 0):
        self.receiver = receiver
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                sock: socket.socket = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                buf = b""
                while True:
                    try:
                        chunk = sock.recv(1 << 20)
                    except OSError:
                        return
                    if not chunk:
                        return
                    buf += chunk
                    out = b""
                    while len(buf) >= _LEN.size:
                        (length,) = _LEN.unpack_from(buf)
                        if len(buf) < _LEN.size + length:
                            break
                        frame = buf[_LEN.size:_LEN.size + length]
                        buf = buf[_LEN.size + length:]
                        try:
                            outer.receiver.apply_bytes(frame)
                            out += bytes([ACK_OK])
                        except Exception:  # noqa: BLE001 — ack the failure
                            out += bytes([ACK_ERROR])
                    if out:
                        try:
                            sock.sendall(out)
                        except OSError:
                            return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="replication-rx",
            daemon=True)

    def start(self) -> "ReplicationServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
