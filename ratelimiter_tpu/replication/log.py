"""Replication log: dirty-slot deltas coalesced into epoch-stamped frames.

The primary's ``DeviceEngine`` marks every slot a dispatch touches into a
``SlotJournal`` (engine/state.py) — off the decision path, one boolean
scatter per batch.  ``ReplicationLog.cut()`` turns the journal's
accumulated delta into wire frames:

1. flush the micro-batcher (queued requests dispatch, marking their slots);
2. drain the journal (atomic swap — marks racing the drain land in the
   NEXT epoch, and a row read here that a concurrent dispatch then
   overwrites is simply re-shipped next cut: row writes are idempotent);
3. read the dirty rows from the device (one gather per algo);
4. dump the key->slot index journal + limiter table (the addressing a
   standby needs to serve the rows after promotion);
5. stamp everything with the next epoch and chunk to the wire budget
   (replication/wire.py).

Consistency model: a frame captures every mutation that completed before
its cut began; mutations concurrent with the cut land in this epoch, the
next, or both (both is harmless).  Slot REUSE concurrent with a cut (an
eviction remapping a slot between the row read and the index dump) can
pair a new key with its predecessor's row for one epoch — the next cut
repairs it, and keys whose last mutation precedes the cut are exact,
which is precisely the "at or before the replicated epoch" guarantee the
failover drill checks (storage/chaos.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

import numpy as np

from ratelimiter_tpu.engine.state import SlotJournal
from ratelimiter_tpu.replication.wire import DEFAULT_FRAME_BUDGET, chunk_frames


def _wall_ms() -> int:
    return time.time_ns() // 1_000_000


class ReplicationLog:
    """Owns the primary's journal and cuts epoch-stamped frame batches."""

    def __init__(self, storage, max_frame_bytes: int = DEFAULT_FRAME_BUDGET):
        engine = storage.engine
        if not getattr(engine, "supports_replication", False):
            raise ValueError(
                "replication requires the single-device DeviceEngine "
                "(the sharded engine is not journaled yet)")
        self.storage = storage
        self.engine = engine
        self.max_frame_bytes = int(max_frame_bytes)
        self.journal = SlotJournal(engine.num_slots)
        engine.journal = self.journal
        self.epoch = 0
        self._full_pending = True  # first cut bootstraps the standby
        self._lock = threading.Lock()
        # Lag of the newest cut: age of the oldest mutation it shipped.
        self.last_cut_lag_ms = 0.0

    def request_full(self) -> None:
        """Make the next cut ship the complete state (standby bootstrap,
        or recovery after a ship failure left the stream gapped)."""
        with self._lock:
            self._full_pending = True
            self.journal.mark_all("sw")
            self.journal.mark_all("tb")

    def cut(self) -> List[Dict]:
        """Cut one epoch: returns the frame dicts to ship (empty when
        nothing changed since the last cut — the epoch is not consumed)."""
        with self._lock:
            self.storage.flush()
            if self._full_pending:
                self.journal.mark_all("sw")
                self.journal.mark_all("tb")
            deltas_ids, oldest_ns, was_all = self.journal.drain()
            full = self._full_pending or was_all
            if not deltas_ids and not full:
                self.last_cut_lag_ms = 0.0
                return []
            deltas = {}
            for algo, ids in deltas_ids.items():
                deltas[algo] = {
                    "slots": ids,
                    "rows": self.engine.read_rows(algo, ids),
                }
            from ratelimiter_tpu.engine.checkpoint import (
                _limiter_table_dump,
                dump_slot_indexes,
            )

            index_dump = dump_slot_indexes(self.storage)
            limiters = _limiter_table_dump(self.storage)
            self.epoch += 1
            self._full_pending = False
            now = time.time_ns()
            self.last_cut_lag_ms = ((now - oldest_ns) / 1e6
                                    if oldest_ns is not None else 0.0)
            return chunk_frames(self.epoch, _wall_ms(),
                                self.engine.num_slots, deltas, index_dump,
                                limiters, full=full,
                                max_bytes=self.max_frame_bytes)

    def remark(self, frames: List[Dict]) -> None:
        """Put a failed ship's slots back in the journal so the delta is
        re-sent (the replicator also requests a full frame, since the
        standby's epoch stream now has a gap)."""
        for frame in frames:
            for algo, payload in frame.get("algos", {}).items():
                self.journal.mark(algo, payload["slots"])

    def pending(self) -> int:
        return self.journal.pending()

    def detach(self) -> None:
        """Stop journaling (the engine reverts to zero-overhead marks)."""
        self.engine.journal = None


def engine_state_fingerprint(engine) -> Dict[str, np.ndarray]:
    """Host copies of both packed state arrays (test/drill equality
    checks between a primary and a caught-up standby)."""
    engine.block_until_ready()
    return {"sw": np.asarray(engine.sw_packed).copy(),
            "tb": np.asarray(engine.tb_packed).copy()}
